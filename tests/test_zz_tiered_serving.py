"""Tiered-index × brownout serving interplay (ISSUE 15 satellite): a LIVE
REST retrieve route over a tiered IVF external index, with the brownout
ladder's rung 2 engaged mid-stream — the halved probe set must keep serving
AND must never trigger tier-promotion churn.

Lives at the end of the suite's alphabetical order on purpose (the
``test_zz_`` discipline): this test starts a real ``pw.run`` engine behind a
REST connector, and streaming REST sources run forever (daemon threads) — a
lazy autocommit tick keeps the residual idle load off earlier
timing-sensitive tests."""

from __future__ import annotations

import hashlib
import json
import socket
import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.brownout import get_brownout, reset_brownout
from pathway_tpu.internals.parse_graph import G

pytestmark = pytest.mark.tiered


def _fake_vec(text: str, dim: int = 8) -> np.ndarray:
    digest = hashlib.sha256(str(text).encode()).digest()
    rng = np.random.default_rng(int.from_bytes(digest[:8], "little"))
    v = rng.normal(size=dim).astype(np.float32)
    return v / np.linalg.norm(v)


def _start_retrieve_server(port: int, monkeypatch) -> None:
    from pathway_tpu.io.http import PathwayWebserver, rest_connector
    from pathway_tpu.stdlib.indexing import IvfKnnFactory

    monkeypatch.setenv("PATHWAY_IVF_TIERED", "on")
    # a tiny hot budget (~16 KiB) keeps most clusters COLD, so a promotion
    # during the browned-out window would be observable — the assertion is
    # about real candidates, not a vacuously-hot store
    monkeypatch.setenv("PATHWAY_IVF_HBM_BUDGET_MB", "0.016")
    G.clear()

    @pw.udf
    def embed(text: str) -> np.ndarray:
        return _fake_vec(text)

    docs = pw.debug.table_from_rows(
        pw.schema_builder({"text": str}),
        [(f"doc-{i}",) for i in range(64)],
    )
    factory = IvfKnnFactory(dimensions=8, n_clusters=4, n_probe=4, embedder=embed)
    index = factory.build_index(docs.text, docs)
    ws = PathwayWebserver(host="127.0.0.1", port=port)

    class Q(pw.Schema):
        text: str

    queries, writer = rest_connector(
        webserver=ws, route="/v1/retrieve", schema=Q,
        delete_completed_queries=True,
        # lazy tick: the daemon engine's idle churn stays off the suite
        autocommit_duration_ms=25,
    )
    res = index.query_as_of_now(
        queries.text, number_of_matches=1, collapse_rows=True
    )
    writer(res.select(result=pw.apply(lambda t: list(t), pw.this.text)))
    threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE),
        daemon=True,
    ).start()
    deadline = time.monotonic() + 20
    while True:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            assert time.monotonic() < deadline, "REST server never came up"
            time.sleep(0.2)


def _retrieve(port: int, text: str, timeout: float = 30.0):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/retrieve",
        data=json.dumps({"text": text}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"null")
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code, None
    except Exception:
        return 0, None


def test_browned_out_retrieve_serves_without_promotion_churn(monkeypatch):
    """Rung 2 engaged against a live tiered-index retrieve route: requests
    keep answering (recall degrades honestly via the halved probe set) and
    the browned-out window issues ZERO tier-promotion prefetches — the
    degradation ladder must never thrash the tiers it protects."""
    from pathway_tpu.engine import telemetry

    reset_brownout()
    try:
        port = 18911
        _start_retrieve_server(port, monkeypatch)

        def ask(text: str) -> list:
            t0 = time.monotonic()
            while time.monotonic() - t0 < 20.0:
                code, body = _retrieve(port, text)
                if code == 200:
                    return body.get("result") if isinstance(body, dict) else body
                time.sleep(0.3)  # shed/transient: honest retry
            raise AssertionError(f"retrieve {text!r} never answered")

        # warm serving at rung 0 (trains the index, settles the EWMA)
        for i in range(4):
            got = ask(f"doc-{i * 7}")
            assert got == [f"doc-{i * 7}"], got

        get_brownout().observe_occupancy(0.95)  # engage rung 2
        assert get_brownout().nprobe_shift() == 1
        before = telemetry.stage_snapshot("index.").get(
            "index.prefetch_requests", 0.0
        )
        # browned-out serving: answers keep coming (full probe is 4, halved
        # is 2 — the self-match query still lands in its own cluster)
        for i in range(6):
            got = ask(f"doc-{i * 9 + 1}")
            assert got == [f"doc-{i * 9 + 1}"], got
        after = telemetry.stage_snapshot("index.").get(
            "index.prefetch_requests", 0.0
        )
        assert after == before, (
            "browned-out probes triggered tier-promotion churn",
            before, after,
        )
    finally:
        reset_brownout()
