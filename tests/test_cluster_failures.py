"""Mesh-layer failure semantics: typed peer errors, barrier deadlines,
heartbeats, bounded inbox backpressure, and wiring-failure fd hygiene —
ClusterExchange pairs wired over localhost inside one process."""

from __future__ import annotations

import itertools
import os
import socket
import threading
import time

import pytest

from pathway_tpu.parallel.cluster import (
    ClusterExchange,
    PeerShutdownError,
    PeerTimeoutError,
)

_PORT_SLOT = itertools.count()


def _port_base() -> int:
    # distinct base per pair so back-to-back tests never contend on TIME_WAIT
    return 26000 + os.getpid() % 200 * 16 + next(_PORT_SLOT) * 4


def _pair(first_port: int):
    made: dict = {}
    errors: list = []

    def mk(me: int) -> None:
        try:
            made[me] = ClusterExchange(2, me, first_port)
        except BaseException as exc:
            errors.append(exc)

    threads = [threading.Thread(target=mk, args=(me,)) for me in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, f"pair wiring failed: {errors}"
    assert set(made) == {0, 1}
    return made[0], made[1]


def test_exchange_parts_roundtrip_and_typed_shutdown(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    a, b = _pair(_port_base())
    try:
        out: dict = {}

        def b_side() -> None:
            out["b"] = b.exchange_parts(b"t1", {0: b"from-b"})

        t = threading.Thread(target=b_side)
        t.start()
        got_a = a.exchange_parts(b"t1", {1: b"from-a"})
        t.join(timeout=10)
        assert got_a == {1: b"from-b"}
        assert out["b"] == {0: b"from-a"}

        # peer teardown surfaces as the TYPED error, quickly (socket close,
        # not a barrier timeout)
        b.close()
        t0 = time.monotonic()
        with pytest.raises(PeerShutdownError):
            a._recv(1, b"never-sent", timeout=30)
        assert time.monotonic() - t0 < 5
        assert 1 in a.dead_peers()
    finally:
        a.close()
        b.close()


def test_barrier_deadline_raises_peer_timeout(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    a, b = _pair(_port_base())
    try:
        with pytest.raises(PeerTimeoutError):
            a._recv(1, b"nobody-sends-this", timeout=0.4)
    finally:
        a.close()
        b.close()


def test_heartbeats_keep_peer_fresh_and_staleness_trips(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    monkeypatch.setenv("PATHWAY_HEARTBEAT_TIMEOUT_S", "0.6")
    a, b = _pair(_port_base())
    try:
        time.sleep(0.5)
        ages = a.heartbeat_ages()
        assert ages[1] < 0.4, f"beacons not flowing: {ages}"

        # freeze b's beacons (its process is 'alive' but its loops stopped):
        # a's next wait must trip the staleness bound, typed
        b._stop.set()
        time.sleep(0.3)
        t0 = time.monotonic()
        with pytest.raises(PeerTimeoutError, match="stale"):
            a._recv(1, b"x", timeout=30)
        assert time.monotonic() - t0 < 3
    finally:
        a.close()
        b.close()


def test_bounded_inbox_applies_backpressure_without_loss(monkeypatch):
    monkeypatch.setenv("PATHWAY_EXCHANGE_INBOX_FRAMES", "4")
    a, b = _pair(_port_base())
    try:
        n_frames = 24
        payloads = {f"t{i}".encode(): bytes([i]) * 100 for i in range(n_frames)}
        for tag, payload in payloads.items():
            b._send(0, tag, payload)
        time.sleep(0.5)
        with a._cv:
            buffered = a._inbox_count[1]
        assert buffered <= 4, f"inbox grew past its bound: {buffered}"
        # draining releases the parked reader; every frame arrives intact
        for tag, payload in payloads.items():
            assert a._recv(1, tag, timeout=10) == payload
    finally:
        a.close()
        b.close()


def test_send_deadline_trips_on_nonreading_peer(monkeypatch):
    """A peer that stopped reading (wedged userspace, live kernel TCP stack)
    must surface as a typed error from the SEND side once buffers fill — the
    recv-side deadlines never fire if sendall hangs first."""
    monkeypatch.setenv("PATHWAY_BARRIER_TIMEOUT_S", "1")
    monkeypatch.setenv("PATHWAY_EXCHANGE_INBOX_FRAMES", "1")
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0")
    a, b = _pair(_port_base())
    try:
        payload = b"x" * (1 << 20)
        t0 = time.monotonic()
        with pytest.raises((PeerTimeoutError, PeerShutdownError)):
            # b's parked reader (inbox bound 1, nobody recvs) stops draining;
            # TCP buffers fill and the send deadline must fire, bounded
            for i in range(256):
                a._send(1, f"big{i}".encode(), payload)
        assert time.monotonic() - t0 < 30
    finally:
        a.close()
        b.close()


def test_connect_failure_closes_listener_and_raises_typed(monkeypatch):
    monkeypatch.setenv("PATHWAY_CONNECT_TIMEOUT_S", "0.6")
    port = _port_base()
    t0 = time.monotonic()
    with pytest.raises(PeerTimeoutError):
        ClusterExchange(2, 0, port)  # peer 1 never comes up
    assert time.monotonic() - t0 < 10
    # the failed wiring must not strand the listener fd: the SAME port binds
    # immediately (a stranded one wedges a retry/restart on EADDRINUSE)
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        probe.bind(("127.0.0.1", port))
    finally:
        probe.close()


def test_exchange_metrics_traffic_barrier_wait_and_straggler(monkeypatch):
    """The exchange feeds the stage counters: per-peer bytes/frames both
    directions, per-barrier wait seconds, and straggler attribution (the peer
    this process blocked on longest)."""
    from pathway_tpu.engine import telemetry

    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0")  # no beacon noise
    telemetry.stage_reset("exchange.")
    a, b = _pair(_port_base())
    try:
        # b answers the barrier late: a must attribute peer 1 as the straggler
        def b_side() -> None:
            time.sleep(0.4)
            b.exchange_parts(b"t-straggle", {0: b"x" * 100})

        t = threading.Thread(target=b_side)
        t.start()
        a.exchange_parts(b"t-straggle", {1: b"y" * 200})
        t.join(timeout=10)
        counters = telemetry.stage_snapshot("exchange.")
        assert counters["exchange.peer1.frames_sent"] >= 1
        assert counters["exchange.peer1.bytes_sent"] >= 200
        assert counters["exchange.peer1.frames_received"] >= 1
        assert counters["exchange.peer1.bytes_received"] >= 100
        assert counters["exchange.barriers"] >= 1
        assert counters["exchange.barrier_wait_s"] >= 0.3
        assert counters.get("exchange.straggler.peer1", 0) >= 1
        assert counters.get("exchange.peer1.straggler_wait_s", 0) >= 0.3
    finally:
        a.close()
        b.close()
        telemetry.stage_reset("exchange.")


def test_barrier_timeout_records_stage_counter_and_flight_event(monkeypatch):
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.profile import get_flight_recorder

    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0")
    telemetry.stage_reset("cluster.")
    rec = get_flight_recorder()
    rec.reset()
    a, b = _pair(_port_base())
    try:
        a.barrier_timeout_s = 0.3
        with pytest.raises(PeerTimeoutError):
            a.exchange_parts(b"nobody-sends-this", {1: b"x"})
        counters = telemetry.stage_snapshot("cluster.")
        assert counters.get("cluster.barrier_timeouts", 0) >= 1
        events = rec.payload("test")["events"]
        timeouts = [e for e in events if e["kind"] == "barrier_timeout"]
        assert timeouts and timeouts[-1]["peer"] == 1
        assert timeouts[-1]["tag"] == "nobody-sends-this"
        # the pending-barrier mark must SURVIVE the failed barrier (the
        # fence/crash dump names it), not be wiped during unwind
        assert rec.payload("test")["summary"]["pending_barrier"] == "nobody-sends-this"
    finally:
        a.close()
        b.close()
        telemetry.stage_reset("cluster.")
        rec.reset()
