"""Deterministic-scheduler tests (pathway_tpu/internals/sched.py): seeded and
choice-list replay identity, deadlock/livelock detection, DFS distinctness,
modeled-timeout semantics, invariant plumbing, telemetry, and thread hygiene
of the harness itself."""

from __future__ import annotations

import threading

import pytest

from pathway_tpu.internals.sched import (
    DeadlockError,
    DeterministicScheduler,
    InvariantViolation,
    LivelockError,
    SchedulingError,
    default_seed,
    explore,
    run_once,
    sweep_seeds,
)

pytestmark = pytest.mark.modelcheck


def _locked_counter_model(sched):
    """Two workers increment a shared counter under a lock: always 6."""
    state = {"x": 0}
    lock = sched.lock("L")

    def worker():
        for _ in range(3):
            with lock:
                v = state["x"]
                sched.yield_point("compute")
                state["x"] = v + 1

    sched.spawn(worker, name="w1")
    sched.spawn(worker, name="w2")

    def check():
        assert state["x"] == 6, f"locked counter lost updates: {state['x']}"

    return check


def _racy_counter_model(sched):
    """Same, no lock: a classic lost update on the right interleaving."""
    state = {"x": 0}

    def worker():
        for _ in range(2):
            v = state["x"]
            sched.yield_point("compute")
            state["x"] = v + 1

    sched.spawn(worker, name="w1")
    sched.spawn(worker, name="w2")

    def check():
        assert state["x"] == 4, f"lost update: {state['x']}"

    return check


# ---------------------------------------------------------------------------
# replay identity
# ---------------------------------------------------------------------------


def test_seeded_schedules_replay_identically():
    a = run_once(_locked_counter_model, seed=42)
    b = run_once(_locked_counter_model, seed=42)
    assert a.choices_taken == b.choices_taken
    assert a.trace == b.trace


def test_different_seeds_reach_different_schedules():
    schedules = {
        tuple(run_once(_locked_counter_model, seed=s).choices_taken)
        for s in range(10)
    }
    assert len(schedules) > 1


def test_choice_list_replay_is_exact():
    a = run_once(_locked_counter_model, seed=7)
    b = run_once(_locked_counter_model, choices=a.choices_taken)
    assert b.choices_taken == a.choices_taken
    assert b.trace == a.trace


def test_failing_schedule_replays_the_failure():
    result = explore(_racy_counter_model, max_schedules=300, name="racy")
    assert result.failure is not None
    assert isinstance(result.failure, InvariantViolation)
    assert result.failing_schedule == result.failure.schedule
    with pytest.raises(InvariantViolation):
        run_once(_racy_counter_model, choices=result.failing_schedule)


# ---------------------------------------------------------------------------
# failure detection
# ---------------------------------------------------------------------------


def test_lock_inversion_deadlock_detected_with_schedule():
    def inverted(sched):
        a, b = sched.lock("A"), sched.lock("B")

        def t1():
            with a:
                sched.yield_point("gap")
                with b:
                    pass

        def t2():
            with b:
                sched.yield_point("gap")
                with a:
                    pass

        sched.spawn(t1, name="t1")
        sched.spawn(t2, name="t2")
        return None

    result = explore(inverted, max_schedules=200, name="inverted")
    assert isinstance(result.failure, DeadlockError)
    assert result.failing_schedule
    with pytest.raises(DeadlockError) as exc_info:
        run_once(inverted, choices=result.failing_schedule)
    assert exc_info.value.schedule == result.failing_schedule


def test_untimed_wait_deadlocks_timed_wait_survives():
    def waiter(timeout):
        def model(sched):
            cv = sched.condition(name="cv")
            done = {"ok": False}

            def t1():
                with cv:
                    while not done["ok"]:
                        if not cv.wait(timeout=timeout):
                            done["ok"] = True  # deadline abort path

            sched.spawn(t1, name="t1")
            return None

        return model

    # nobody will ever notify: the untimed wait is a guaranteed deadlock —
    # the dynamic proof of the PWA102 rule
    assert isinstance(explore(waiter(None), max_schedules=20).failure, DeadlockError)
    assert explore(waiter(1.0), max_schedules=20).ok


def test_livelock_bound():
    def spinner(sched):
        def t1():
            while True:
                sched.yield_point("spin")

        sched.spawn(t1, name="t1")
        return None

    with pytest.raises(LivelockError):
        run_once(spinner, seed=0, max_steps=50)


def test_model_exception_is_typed_and_replayable():
    def crasher(sched):
        def t1():
            sched.yield_point("pre")
            raise ValueError("boom")

        sched.spawn(t1, name="t1")
        return None

    with pytest.raises(SchedulingError) as exc_info:
        run_once(crasher, seed=0)
    assert "boom" in str(exc_info.value)
    assert exc_info.value.schedule  # replayable


# ---------------------------------------------------------------------------
# exploration
# ---------------------------------------------------------------------------


def test_explore_produces_distinct_schedules():
    result = explore(_locked_counter_model, max_schedules=120, name="distinct")
    assert result.ok
    assert result.schedules_run == 120
    assert result.distinct_schedules == 120  # DFS: every schedule differs


def test_explore_exhausts_tiny_trees():
    def tiny(sched):
        def t1():
            sched.yield_point("only")

        sched.spawn(t1, name="t1")
        return None

    result = explore(tiny, max_schedules=100, name="tiny")
    assert result.ok
    assert result.schedules_run < 100  # exhausted, not capped


def test_sweep_seeds_records_failing_seed():
    result = sweep_seeds(_racy_counter_model, n_seeds=100, base_seed=0)
    assert result.failure is not None
    assert result.failing_seed is not None
    with pytest.raises(InvariantViolation):
        run_once(_racy_counter_model, seed=result.failing_seed)


# ---------------------------------------------------------------------------
# seed resolution + telemetry + hygiene
# ---------------------------------------------------------------------------


def test_default_seed_env_and_chaos_plan(monkeypatch):
    from pathway_tpu.internals import chaos as chaos_mod

    monkeypatch.setenv("PATHWAY_SCHED_SEED", "1234")
    assert default_seed() == 1234
    monkeypatch.delenv("PATHWAY_SCHED_SEED")
    monkeypatch.setenv("PATHWAY_CHAOS_PLAN", '{"sched": {"seed": 77}}')
    chaos_mod.reset_chaos()
    try:
        assert default_seed() == 77
        assert chaos_mod.get_chaos().sched_seed() == 77
    finally:
        monkeypatch.delenv("PATHWAY_CHAOS_PLAN")
        chaos_mod.reset_chaos()


def test_failure_emits_modelcheck_flight_event_and_counters(monkeypatch):
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.profile import get_flight_recorder

    recorder = get_flight_recorder()
    monkeypatch.setattr(recorder, "enabled", True)
    telemetry.stage_reset("modelcheck.")
    result = sweep_seeds(_racy_counter_model, n_seeds=100, base_seed=0, name="racy-tel")
    assert result.failure is not None
    counters = telemetry.stage_snapshot("modelcheck.")
    assert counters.get("modelcheck.runs", 0) >= 1, counters
    assert counters.get("modelcheck.failures", 0) >= 1, counters
    events = [
        ev for ev in list(recorder._events) if ev.get("kind") == "modelcheck"
    ]
    assert events, "no modelcheck flight event recorded"
    ev = events[-1]
    assert ev["model"] == "racy-tel"
    assert ev["seed"] == result.failing_seed
    assert ev["schedule"] == result.failing_schedule


def test_scheduler_leaks_no_threads():
    before = {t.ident for t in threading.enumerate()}
    run_once(_locked_counter_model, seed=3)
    result = explore(_racy_counter_model, max_schedules=50)
    assert result.failure is not None  # aborted runs must clean up too
    leaked = [
        t
        for t in threading.enumerate()
        if t.ident not in before and t.name.startswith("pathway:sched")
    ]
    for t in leaked:
        t.join(timeout=5)
    leaked = [
        t
        for t in threading.enumerate()
        if t.ident not in before and t.name.startswith("pathway:sched")
    ]
    assert not leaked, [t.name for t in leaked]


def test_one_scheduler_drives_one_run():
    sched = DeterministicScheduler(seed=0)
    sched.spawn(lambda: None, name="t")
    sched.run()
    with pytest.raises(RuntimeError):
        sched.run()
