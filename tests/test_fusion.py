"""Whole-commit fusion: planner boundaries, bitwise fused-vs-unfused parity
(interpreter AND forced-XLA paths), the PATHWAY_FUSION=off escape hatch,
``fuse.*`` telemetry + the ``fusion`` flight event, the one-AnalysisContext
regression, the <1 s planning-overhead guard, and a chaos-marked fenced-rejoin
replay over a fused pipeline."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals import parse_graph as pg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.fusion


@pytest.fixture(autouse=True)
def _clean_graph(monkeypatch):
    pg.G.clear()
    monkeypatch.setenv("PATHWAY_LINT", "off")
    yield
    pg.G.clear()


def _run_capture(build, fusion: str, jit_rows: "int | None" = None) -> list:
    """Build the graph via ``build(capture_list)`` and run it under the given
    PATHWAY_FUSION mode; returns the captured per-batch sink bytes."""
    prev = {
        k: os.environ.get(k) for k in ("PATHWAY_FUSION", "PATHWAY_FUSION_JIT_ROWS")
    }
    os.environ["PATHWAY_FUSION"] = fusion
    if jit_rows is not None:
        os.environ["PATHWAY_FUSION_JIT_ROWS"] = str(jit_rows)
    try:
        pg.G.clear()
        got: list = []
        out = build()
        pw.io.subscribe(out, on_batch=lambda keys, diffs, columns, time: got.append(
            (
                keys.tobytes(),
                diffs.tobytes(),
                tuple(
                    (nm, col.tobytes())
                    if np.asarray(col).dtype != object
                    else (nm, repr(np.asarray(col).tolist()).encode())
                    for nm, col in sorted(columns.items())
                ),
            )
        ))
        runner = GraphRunner(pg.G._current)
        runner.run(monitoring_level=pw.MonitoringLevel.NONE)
        got.append(("schedule", runner._fusion_schedule is not None))
        return got
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _chain_rows(n=3_000, commits=4, seed=7):
    rng = np.random.default_rng(seed)
    per = n // commits
    return [
        (int(a), int(q), int(t), int(c), 2 * (i // per), 1)
        for i, (a, q, t, c) in enumerate(
            zip(
                rng.integers(1, 10**6, n),
                rng.integers(1, 50, n),
                rng.integers(0, 10**9, n),
                rng.integers(0, 32, n),
            )
        )
    ]


_CHAIN_SCHEMA = {"amount": int, "qty": int, "ts": int, "cat": int}


def _int_chain(rows):
    t = pw.debug.table_from_rows(pw.schema_builder(_CHAIN_SCHEMA), rows, is_stream=True)
    t1 = t.select(t.cat, total=t.amount * t.qty, day=t.ts // 86400, hod=(t.ts >> 7) & 31)
    t2 = t1.select(t1.cat, t1.day,
                   net=pw.if_else(t1.total > 10**7, t1.total - (t1.total >> 4), t1.total),
                   bucket=(t1.day & 7) * 32 + t1.cat + t1.hod)
    t3 = t2.filter((t2.net > 500_000) & ((t2.bucket & 3) != 0))
    t4 = t3.select(t3.cat, score=t3.net * 3 - t3.day, band=t3.bucket ^ (t3.net & 0xFF))
    return t4.groupby(t4.cat).reduce(
        t4.cat, s=pw.reducers.sum(t4.score), b=pw.reducers.sum(t4.band),
        n=pw.reducers.count(),
    )


# -- planner ------------------------------------------------------------------


def test_planner_chains_and_regions():
    from pathway_tpu.analysis import AnalysisContext, plan_fusion

    rows = _chain_rows(200, 2)
    _int_chain(rows)
    plan = plan_fusion(AnalysisContext(pg.G._current))
    assert plan.chains, "select/filter chain did not plan"
    # one chain covering the rowwise/filter run (4 nodes: t1 t2 filter t4)
    assert max(len(c) for c in plan.chains) == 4
    assert plan.regions and any(
        "groupby" in r.kinds for r in plan.regions
    ), "groupby member should join the fused region"
    ev = plan.to_event()
    assert ev["ops_fused"] == plan.ops_fused > 0


def test_host_udf_mid_chain_splits_region():
    """PWA004's condition is a fusion boundary: an apply() in the middle of a
    chain splits it — the surrounding pure segments still fuse separately."""
    from pathway_tpu.analysis import AnalysisContext, plan_fusion

    rows = _chain_rows(200, 2)
    t = pw.debug.table_from_rows(pw.schema_builder(_CHAIN_SCHEMA), rows, is_stream=True)
    a = t.select(t.cat, x=t.amount * t.qty)
    b = a.select(a.cat, y=a.x + 1)
    mid = b.select(b.cat, z=pw.apply(lambda y: y * 2, b.y))  # host UDF boundary
    c = mid.select(mid.cat, w=mid.z)
    d = c.select(c.cat, v=c.w)
    d.groupby(d.cat).reduce(d.cat, n=pw.reducers.count())
    plan = plan_fusion(AnalysisContext(pg.G._current))
    chain_nodes = {nid for ch in plan.chains for nid in ch.node_ids}
    assert mid._node.id not in chain_nodes, "UDF node must not fuse"
    assert mid._node.id in plan.boundaries
    assert plan.boundaries[mid._node.id] == "host_udf"
    # the pre-UDF pair and the post-UDF pair each form their own chain
    assert {a._node.id, b._node.id} <= chain_nodes
    assert {c._node.id, d._node.id} <= chain_nodes
    assert len(plan.chains) == 2


def test_drain_sensitive_ops_never_fused():
    """REWIND_SAFE=False evaluators (buffer/freeze/forget flush on the live
    ``draining`` signal) must never appear in a chain or region."""
    from pathway_tpu.analysis import AnalysisContext, plan_fusion
    from pathway_tpu.engine.evaluators import EVALUATORS

    rows = _chain_rows(200, 2)
    _int_chain(rows)
    plan = plan_fusion(AnalysisContext(pg.G._current))
    drain_kinds = {
        node_cls.kind
        for node_cls, ev in EVALUATORS.items()
        if not getattr(ev, "REWIND_SAFE", True)
    }
    node_by_id = {n.id: n for n in pg.G._current.nodes}
    for ch in plan.chains:
        for nid in ch.node_ids:
            assert node_by_id[nid].kind not in drain_kinds
    for r in plan.regions:
        for nid in r.member_ids:
            assert node_by_id[nid].kind not in drain_kinds


def test_cross_table_ref_is_boundary():
    from pathway_tpu.analysis import AnalysisContext, plan_fusion

    rows = _chain_rows(200, 2)
    t = pw.debug.table_from_rows(pw.schema_builder(_CHAIN_SCHEMA), rows, is_stream=True)
    a = t.select(t.cat, x=t.amount * t.qty)
    b = a.select(a.cat, y=a.x + 1)
    c = b.select(b.cat, z=b.y + a.x)  # cross-table reference: live dependency
    c.groupby(c.cat).reduce(c.cat, n=pw.reducers.count())
    plan = plan_fusion(AnalysisContext(pg.G._current))
    chain_nodes = {nid for ch in plan.chains for nid in ch.node_ids}
    assert c._node.id not in chain_nodes
    assert plan.boundaries[c._node.id] == "cross_table_ref"


# -- bitwise parity -----------------------------------------------------------


def test_parity_int_chain_interpreter():
    rows = _chain_rows()
    a = _run_capture(lambda: _int_chain(rows), "off")
    b = _run_capture(lambda: _int_chain(rows), "on")
    assert a[-1] == ("schedule", False) and b[-1] == ("schedule", True)
    assert a[:-1] == b[:-1]


def test_parity_int_chain_jit_forced():
    rows = _chain_rows()
    a = _run_capture(lambda: _int_chain(rows), "off")
    b = _run_capture(lambda: _int_chain(rows), "on", jit_rows=64)
    assert a[:-1] == b[:-1]


def test_parity_float_fma_chain_rejects_jit_stays_exact():
    """A float mul→add chain is where XLA:CPU contracts to FMA; the first-use
    parity probe must catch it, downgrade the program, and keep fused output
    byte-identical anyway."""
    from pathway_tpu.engine import telemetry

    rng = np.random.default_rng(3)
    n = 1_000
    rows = [
        (float(x), float(y), 2 * (i // 250), 1)
        for i, (x, y) in enumerate(
            zip(rng.standard_normal(n), rng.standard_normal(n) * 1e3)
        )
    ]

    def build():
        t = pw.debug.table_from_rows(
            pw.schema_builder({"x": float, "y": float}), rows, is_stream=True
        )
        t1 = t.select(z=t.x * t.y + t.x, w=t.x - t.y)
        t2 = t1.select(v=t1.z * 2.0 + t1.w)
        return t2.select(out=t2.v * 0.5 + 1.0)

    before = telemetry.stage_snapshot("fuse.").get("fuse.jit_parity_rejects", 0.0)
    a = _run_capture(build, "off")
    b = _run_capture(build, "on", jit_rows=64)
    assert a[:-1] == b[:-1], "fused float chain diverged from unfused"
    after = telemetry.stage_snapshot("fuse.").get("fuse.jit_parity_rejects", 0.0)
    assert after > before, "FMA contraction should have tripped the parity probe"


def test_parity_filter_empties_mid_chain():
    rows = _chain_rows(400, 2)

    def build():
        t = pw.debug.table_from_rows(
            pw.schema_builder(_CHAIN_SCHEMA), rows, is_stream=True
        )
        t1 = t.select(t.cat, x=t.amount * t.qty)
        dead = t1.filter(t1.x < 0)  # drops every row
        t2 = dead.select(dead.cat, y=dead.x + 1)
        return t2.groupby(t2.cat).reduce(t2.cat, n=pw.reducers.count())

    a = _run_capture(build, "off")
    b = _run_capture(build, "on", jit_rows=64)
    assert a[:-1] == b[:-1]


def test_parity_retraction_stream():
    """Insert/retract pairs flow through a fused chain bit-identically
    (retraction rows carry values; filters/maps must treat them alike)."""
    rows = []
    for i in range(300):
        rows.append((1000 + i, 3, i * 1000, i % 8, 0, 1))
    for i in range(0, 300, 3):
        rows.append((1000 + i, 3, i * 1000, i % 8, 2, -1))

    def build():
        t = pw.debug.table_from_rows(
            pw.schema_builder(_CHAIN_SCHEMA), rows, is_stream=True
        )
        t1 = t.select(t.cat, x=t.amount * t.qty + (t.ts >> 3))
        t2 = t1.filter((t1.x & 1) == 0)
        t3 = t2.select(t2.cat, y=t2.x * 5)
        return t3.groupby(t3.cat).reduce(t3.cat, s=pw.reducers.sum(t3.y))

    a = _run_capture(build, "off")
    b = _run_capture(build, "on", jit_rows=32)
    assert a[:-1] == b[:-1]


def test_parity_object_columns_fall_back():
    """String/object columns in the chain: the XLA path declines at runtime
    (dtype gate), composed interpreter execution stays bit-identical."""
    rows = [
        (f"u{i % 7}", i * 3, 2 * (i // 100), 1) for i in range(400)
    ]

    def build():
        t = pw.debug.table_from_rows(
            pw.schema_builder({"name": str, "v": int}), rows, is_stream=True
        )
        t1 = t.select(t.name, x=t.v * 2 + 1)
        t2 = t1.filter(t1.x > 100)
        t3 = t2.select(t2.name, y=t2.x - 50)
        return t3.groupby(t3.name).reduce(t3.name, s=pw.reducers.sum(t3.y))

    a = _run_capture(build, "off")
    b = _run_capture(build, "on", jit_rows=32)
    assert a[:-1] == b[:-1]


def test_examples_01_05_parity_fused_vs_unfused(tmp_path):
    """The example programs print their outputs and assert their results:
    identical stdout under PATHWAY_FUSION=on and =off is end-to-end bitwise
    parity over real pipelines (02 is joins, 03 temporal behaviors — the neu
    phase flows through fused chains there)."""
    examples = [
        "01_streaming_wordcount.py",
        "02_etl_joins.py",
        "03_windows_and_behaviors.py",
        "04_vector_index_rag.py",
        "05_persistence_resume.py",
    ]
    for name in examples:
        outs = {}
        for mode in ("on", "off"):
            env = dict(os.environ)
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["PATHWAY_FUSION"] = mode
            env["PATHWAY_FUSION_JIT_ROWS"] = "64"
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "examples", name)],
                capture_output=True, text=True, timeout=120,
                cwd=str(tmp_path), env=env,
            )
            assert proc.returncode == 0, f"{name} [{mode}]: {proc.stderr[-2000:]}"
            outs[mode] = proc.stdout
        assert outs["on"] == outs["off"], f"{name}: fused stdout differs"


# -- the off gate and shared analysis context ---------------------------------


def test_fusion_off_builds_no_schedule():
    rows = _chain_rows(200, 2)
    got = _run_capture(lambda: _int_chain(rows), "off")
    assert got[-1] == ("schedule", False)


def test_single_analysis_context_per_run(monkeypatch):
    """The lint gate and the fusion planner share ONE AnalysisContext — the
    regression here was each building its own (two full DAG walks per run)."""
    from pathway_tpu.analysis import framework

    counts = {"n": 0}
    orig = framework.AnalysisContext.__init__

    def counting(self, *a, **k):
        counts["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(framework.AnalysisContext, "__init__", counting)
    monkeypatch.setenv("PATHWAY_LINT", "warn")
    monkeypatch.setenv("PATHWAY_FUSION", "on")
    rows = _chain_rows(200, 2)
    pg.G.clear()
    out = _int_chain(rows)
    pw.io.subscribe(out, on_batch=lambda *a: None)
    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert counts["n"] == 1, (
        f"lint gate + fusion planner built {counts['n']} AnalysisContexts; "
        "they must share one"
    )


# -- telemetry / flight recorder ----------------------------------------------


def test_fuse_counters_and_flight_event():
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.profile import get_flight_recorder

    rec = get_flight_recorder()
    before = telemetry.stage_snapshot("fuse.")
    rows = _chain_rows(600, 3)
    _run_capture(lambda: _int_chain(rows), "on", jit_rows=64)
    after = telemetry.stage_snapshot("fuse.")

    def grew(key):
        return after.get(key, 0.0) > before.get(key, 0.0)

    assert grew("fuse.chains_built")
    assert grew("fuse.ops_fused")
    assert grew("fuse.schedules_built")
    assert grew("fuse.jit_compiles")
    assert grew("fuse.jit_hits")
    events = [e for e in rec.payload("test")["events"] if e["kind"] == "fusion"]
    assert events, "fusion flight event missing (post-mortems must name the plan)"
    ev = events[-1]
    assert ev["chains"] and ev["ops_fused"] > 0


def test_fused_region_profiler_attribution():
    """The PR-5 profiler shows a region row AND per-member estimate rows, so
    /metrics operator families stay live under fusion."""
    from pathway_tpu.engine.profile import get_profiler, reset_profile

    reset_profile()
    prev = os.environ.get("PATHWAY_PROFILE")
    os.environ["PATHWAY_PROFILE"] = "1"
    try:
        rows = _chain_rows(600, 3)
        _run_capture(lambda: _int_chain(rows), "on")
        totals = get_profiler().operator_totals()
    finally:
        if prev is None:
            os.environ.pop("PATHWAY_PROFILE", None)
        else:
            os.environ["PATHWAY_PROFILE"] = prev
    kinds = {e["kind"] for e in totals}
    assert "fused_chain" in kinds, "region row missing"
    members = [e for e in totals if e["kind"] in ("rowwise", "filter")]
    assert members and any(e["rows"] > 0 for e in members), (
        "per-member estimates missing: operator families went dark"
    )
    region = next(e for e in totals if e["kind"] == "fused_chain")
    member_s = sum(e["seconds"] for e in members)
    assert member_s <= region["seconds"] * 1.001, (
        "member estimates must partition the region's wall time"
    )
    reset_profile()


# -- jit cache discipline -----------------------------------------------------


def test_jit_cache_bounded_over_ragged_commits():
    """pow2 shape bucketing: many distinct commit sizes, few compiles."""
    sizes = [130, 260, 510, 140, 390, 770, 120, 515, 1030, 253]
    rows = []
    pos = 0
    rng = np.random.default_rng(11)
    for ci, sz in enumerate(sizes):
        for _ in range(sz):
            rows.append(
                (int(rng.integers(1, 10**6)), int(rng.integers(1, 50)),
                 int(rng.integers(0, 10**9)), int(rng.integers(0, 32)), 2 * ci, 1)
            )
        pos += sz

    prev = os.environ.get("PATHWAY_FUSION_JIT_ROWS")
    os.environ["PATHWAY_FUSION_JIT_ROWS"] = "64"
    os.environ["PATHWAY_FUSION"] = "on"
    try:
        pg.G.clear()
        out = _int_chain(rows)
        pw.io.subscribe(out, on_batch=lambda *a: None)
        runner = GraphRunner(pg.G._current)
        runner.run(monitoring_level=pw.MonitoringLevel.NONE)
        stats = [
            it.stats()
            for it in (runner._fusion_schedule or [])
            if hasattr(it, "stats")
        ]
    finally:
        os.environ.pop("PATHWAY_FUSION", None)
        if prev is None:
            os.environ.pop("PATHWAY_FUSION_JIT_ROWS", None)
        else:
            os.environ["PATHWAY_FUSION_JIT_ROWS"] = prev
    assert stats
    for s in stats:
        # 10 ragged sizes spanning 130..1030 collapse into <= 5 pow2 buckets
        assert s["jit_compiles"] <= 5 * max(1, s["runs"]), s
        assert len(s["jit_buckets"]) <= 5, s


def test_planning_overhead_under_lint_bound():
    """Tier-1 guard: fusion planning + schedule compilation on a 30-node chain
    stays under the same <1 s bound as the lint gate — planner cost must never
    show up in commit latency."""
    rows = [(i, 2 * i, 0, 1) for i in range(64)]
    t = pw.debug.table_from_rows(
        pw.schema_builder({"v": int, "w": int}), rows, is_stream=True
    )
    cur = t
    for _ in range(30):
        cur = cur.select(v=cur.v + 1, w=cur.w * 2)
    out = cur.groupby(cur.v).reduce(cur.v, n=pw.reducers.count())
    pw.io.subscribe(out, on_batch=lambda *a: None)
    from pathway_tpu.analysis import AnalysisContext, plan_fusion
    from pathway_tpu.engine.fusion import build_schedule

    runner = GraphRunner(pg.G._current)
    t0 = time.perf_counter()
    runner.setup(None)  # includes _build_fusion
    elapsed = time.perf_counter() - t0
    assert runner._fusion_schedule is not None
    assert elapsed < 1.0, f"setup incl. fusion planning took {elapsed:.3f}s"
    t0 = time.perf_counter()
    plan = plan_fusion(AnalysisContext(pg.G._current))
    build_schedule(runner, plan)
    replan = time.perf_counter() - t0
    assert replan < 1.0, f"planning alone took {replan:.3f}s on a 30-node chain"
    runner.finish()


# -- chaos: fused commits replay bit-identical through a fenced rejoin --------

FUSED_REJOIN_PROG = r"""
import json, os
import pathway_tpu as pw

tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

class RowSchema(pw.Schema):
    word: str
    v: int

t = pw.io.fs.read(
    os.path.join(tmp, "in"), format="csv", schema=RowSchema, mode="streaming"
)
t1 = t.select(t.word, x=t.v * 3 + 1)
t2 = t1.filter(t1.x > 0)
t3 = t2.select(t2.word, y=t2.x * 2 - 1)
counts = t3.groupby(t3.word).reduce(
    t3.word, total=pw.reducers.count(), s=pw.reducers.sum(t3.y)
)

out_path = os.path.join(tmp, f"out_{pid}.json")
rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[repr(key)] = {"word": row["word"], "total": int(row["total"]), "s": int(row["s"])}
    else:
        rows.pop(repr(key), None)
    with open(out_path + ".tmp", "w") as f:
        json.dump(list(rows.values()), f)
    os.replace(out_path + ".tmp", out_path)

pw.io.subscribe(counts, on_change)
cfg = pw.persistence.Config(
    pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
)
pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
"""


@pytest.mark.chaos
def test_fused_rejoin_replays_bit_identical(tmp_path):
    """SIGKILL one rank of a fused spawn -n 2 pipeline mid-run: the fenced
    survivor + relaunched rank replay fused commits and converge on output
    bit-identical to the failure-free run (fusion stays ON throughout)."""
    (tmp_path / "in").mkdir()
    first_port = 33000 + os.getpid() % 400 * 4
    for i in range(3):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word,v\n" + "\n".join(
                f"w{j % 5},{j + i}" for j in range(8 * (i + 1))
            ) + "\n"
        )
    plan = {"kill": [{"rank": 1, "commit": 3, "run": 0}]}
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_CHAOS_SEED"] = "7"
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(plan)
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "30"
    env["PATHWAY_FUSION"] = "on"
    env["PATHWAY_FUSION_JIT_ROWS"] = "4"  # force the XLA path at test scale
    env["PATHWAY_LINT"] = "off"
    prog = tmp_path / "prog.py"
    prog.write_text(FUSED_REJOIN_PROG)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(first_port),
            "--max-restarts", "2",
            sys.executable, str(prog),
        ],
        env=env, cwd=str(tmp_path), start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )

    def read_merged() -> dict:
        merged: dict = {}
        for p in range(2):
            path = tmp_path / f"out_{p}.json"
            if not path.exists():
                continue
            try:
                for r in json.loads(path.read_text()):
                    merged[r["word"]] = (r["total"], r["s"])
            except ValueError:
                pass
        return merged

    # failure-free reference, computed in-process over the same pipeline math;
    # the late file lands only AFTER the failover window, so convergence on
    # these totals proves the HEALED cluster ingested and processed it through
    # the fused chain
    def fold(expected: dict, w: str, v: int) -> None:
        x = v * 3 + 1
        y = x * 2 - 1
        tot, s = expected.get(w, (0, 0))
        expected[w] = (tot + 1, s + y)

    expected: dict = {}
    for i in range(3):
        for j in range(8 * (i + 1)):
            fold(expected, f"w{j % 5}", j + i)
    late_rows = [(f"w{j % 5}", 100 + j) for j in range(10)]
    for w, v in late_rows:
        fold(expected, w, v)

    err = ""
    try:
        time.sleep(10)  # kill + fence + rejoin window
        (tmp_path / "in" / "late.csv").write_text(
            "word,v\n" + "\n".join(f"{w},{v}" for w, v in late_rows) + "\n"
        )
        deadline = time.time() + 120
        merged: dict = {}
        while time.time() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(f"spawn exited early: {err[-3000:]}")
            merged = read_merged()
            if merged == expected:
                break
            time.sleep(0.3)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            _, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            _, err = proc.communicate()
    assert "rejoined the cluster" in (err or "") or "restarting the cluster" in (
        err or ""
    ), f"no recovery happened — the kill never fired?\n{err}"
