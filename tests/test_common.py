"""Core Table-API tests (modeled on reference ``python/pathway/tests/test_common.py``)."""

import numpy as np
import pytest

import pathway_tpu as pw

from .utils import T, assert_table_equality, assert_table_equality_wo_index, capture_rows


def test_select_arithmetic():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    res = t.select(s=t.a + t.b, d=t.a - t.b, m=t.a * t.b, q=t.b / t.a)
    rows = sorted(capture_rows(res), key=lambda r: r["s"])
    assert rows == [
        {"s": 3, "d": -1, "m": 2, "q": 2.0},
        {"s": 7, "d": -1, "m": 12, "q": 4.0 / 3.0},
    ]


def test_select_this():
    t = T(
        """
        a
        1
        2
        """
    )
    res = t.select(doubled=pw.this.a * 2)
    assert sorted(r["doubled"] for r in capture_rows(res)) == [2, 4]


def test_filter():
    t = T(
        """
        a
        1
        2
        3
        4
        """
    )
    res = t.filter(pw.this.a % 2 == 0)
    assert sorted(r["a"] for r in capture_rows(res)) == [2, 4]


def test_if_else_and_coalesce():
    t = T(
        """
        a | b
        1 | 10
        2 | None
        """
    )
    res = t.select(
        x=pw.if_else(t.a > 1, t.a * 100, t.a),
        y=pw.coalesce(t.b, 0),
    )
    rows = sorted(capture_rows(res), key=lambda r: r["x"])
    assert rows == [{"x": 1, "y": 10}, {"x": 200, "y": 0}]


def test_division_by_zero_poisons():
    t = T(
        """
        a | b
        6 | 2
        5 | 0
        """
    )
    res = t.select(q=pw.fill_error(t.a // t.b, -1))
    assert sorted(r["q"] for r in capture_rows(res)) == [-1, 3]


def test_concat():
    t1 = T(
        """
        a
        1
        """
    )
    t2 = T(
        """
        a
        2
        """
    )
    res = t1.concat_reindex(t2)
    assert sorted(r["a"] for r in capture_rows(res)) == [1, 2]


def test_update_rows():
    t1 = T(
        """
          | a
        1 | 10
        2 | 20
        """
    )
    t2 = T(
        """
          | a
        2 | 99
        3 | 30
        """
    )
    res = t1.update_rows(t2)
    assert sorted(r["a"] for r in capture_rows(res)) == [10, 30, 99]


def test_update_cells():
    t1 = T(
        """
          | a  | b
        1 | 10 | x
        2 | 20 | y
        """
    )
    t2 = T(
        """
          | a
        2 | 99
        """
    )
    res = t1.update_cells(t2)
    rows = sorted(capture_rows(res), key=lambda r: r["b"])
    assert rows == [{"a": 10, "b": "x"}, {"a": 99, "b": "y"}]


def test_intersect_difference():
    t1 = T(
        """
          | a
        1 | 1
        2 | 2
        3 | 3
        """
    )
    t2 = T(
        """
          | b
        2 | x
        3 | y
        """
    )
    assert sorted(r["a"] for r in capture_rows(t1.intersect(t2))) == [2, 3]
    assert sorted(r["a"] for r in capture_rows(t1.difference(t2))) == [1]


def test_rename_without():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    res = t.rename_columns(c=pw.this.a).without("b")
    assert capture_rows(res) == [{"c": 1}]


def test_with_id_from():
    t = T(
        """
        a | b
        1 | x
        2 | y
        """
    )
    res = t.with_id_from(t.a)
    rows = capture_rows(res)
    assert sorted(r["b"] for r in rows) == ["x", "y"]
    # keys derived deterministically from a
    again = t.with_id_from(t.a)
    assert_table_equality(res, again)


def test_flatten():
    t = T(
        """
        w
        abc
        de
        """
    )
    res = t.flatten(t.w)
    assert sorted(r["w"] for r in capture_rows(res)) == ["a", "b", "c", "d", "e"]


def test_groupby_reduce():
    t = T(
        """
        cost | owner
        100  | A
        200  | A
        50   | B
        """
    )
    res = t.groupby(t.owner).reduce(
        t.owner,
        total=pw.reducers.sum(t.cost),
        cnt=pw.reducers.count(),
        mx=pw.reducers.max(t.cost),
        mn=pw.reducers.min(t.cost),
        avg=pw.reducers.avg(t.cost),
    )
    rows = sorted(capture_rows(res), key=lambda r: r["owner"])
    assert rows == [
        {"owner": "A", "total": 300, "cnt": 2, "mx": 200, "mn": 100, "avg": 150.0},
        {"owner": "B", "total": 50, "cnt": 1, "mx": 50, "mn": 50, "avg": 50.0},
    ]


def test_groupby_argmin_argmax_tuple():
    t = T(
        """
        cost | owner
        100  | A
        200  | A
        50   | B
        """
    )
    res = t.groupby(t.owner).reduce(
        t.owner,
        all_costs=pw.reducers.sorted_tuple(t.cost),
    )
    rows = sorted(capture_rows(res), key=lambda r: r["owner"])
    assert rows == [
        {"owner": "A", "all_costs": (100, 200)},
        {"owner": "B", "all_costs": (50,)},
    ]


def test_groupby_expression_over_reducers():
    t = T(
        """
        a
        1
        2
        3
        """
    )
    res = t.reduce(rng=pw.reducers.max(t.a) - pw.reducers.min(t.a))
    assert capture_rows(res) == [{"rng": 2}]


def test_join_inner():
    t1 = T(
        """
        a | k
        1 | x
        2 | y
        """
    )
    t2 = T(
        """
        b | k
        9 | x
        8 | z
        """
    )
    res = t1.join(t2, t1.k == t2.k).select(t1.a, t2.b)
    assert capture_rows(res) == [{"a": 1, "b": 9}]


def test_join_left_outer():
    t1 = T(
        """
        a | k
        1 | x
        2 | y
        """
    )
    t2 = T(
        """
        b | k
        9 | x
        """
    )
    res = t1.join_left(t2, t1.k == t2.k).select(t1.a, t2.b)
    rows = sorted(capture_rows(res), key=lambda r: r["a"])
    assert rows == [{"a": 1, "b": 9}, {"a": 2, "b": None}]

    res_o = t1.join_outer(t2, t1.k == t2.k).select(t1.a, t2.b)
    rows = sorted(capture_rows(res_o), key=lambda r: (r["a"] is None, r["a"]))
    assert rows == [{"a": 1, "b": 9}, {"a": 2, "b": None}]


def test_join_right():
    t1 = T(
        """
        a | k
        1 | x
        """
    )
    t2 = T(
        """
        b | k
        9 | x
        8 | z
        """
    )
    res = t1.join_right(t2, t1.k == t2.k).select(t1.a, t2.b)
    rows = sorted(capture_rows(res), key=lambda r: r["b"])
    assert rows == [{"a": None, "b": 8}, {"a": 1, "b": 9}]


def test_ix():
    t = T(
        """
        a | k
        1 | x
        2 | y
        """
    )
    keyed = t.with_id_from(t.k)
    source = T(
        """
        k
        x
        x
        y
        """
    )
    res = source.select(a=keyed.ix(source.pointer_from(source.k)).a)
    assert sorted(r["a"] for r in capture_rows(res)) == [1, 1, 2]


def test_sort():
    t = T(
        """
        a
        3
        1
        2
        """
    )
    s = t.sort(t.a)
    rows = capture_rows(t.with_columns(prev=s.prev, next=s.next, a=t.a))
    by_a = {r["a"]: r for r in rows}
    assert by_a[1]["prev"] is None
    assert by_a[3]["next"] is None
    assert by_a[2]["prev"] is not None and by_a[2]["next"] is not None


def test_apply():
    t = T(
        """
        a
        1
        2
        """
    )
    res = t.select(b=pw.apply(lambda x: x * 10, t.a))
    assert sorted(r["b"] for r in capture_rows(res)) == [10, 20]


def test_udf():
    @pw.udf
    def inc(x: int) -> int:
        return x + 1

    t = T(
        """
        a
        1
        2
        """
    )
    res = t.select(b=inc(t.a))
    assert sorted(r["b"] for r in capture_rows(res)) == [2, 3]


def test_async_udf():
    @pw.udf
    async def double(x: int) -> int:
        return x * 2

    t = T(
        """
        a
        1
        2
        """
    )
    res = t.select(b=double(t.a))
    assert sorted(r["b"] for r in capture_rows(res)) == [2, 4]


def test_str_namespace():
    t = T(
        """
        s
        Hello
        World
        """
    )
    res = t.select(u=t.s.str.upper(), n=t.s.str.len(), sw=t.s.str.startswith("He"))
    rows = sorted(capture_rows(res), key=lambda r: r["u"])
    assert rows == [
        {"u": "HELLO", "n": 5, "sw": True},
        {"u": "WORLD", "n": 5, "sw": False},
    ]


def test_deduplicate():
    t = T(
        """
        a | __time__ | __diff__
        1 | 0        | 1
        3 | 2        | 1
        2 | 4        | 1
        5 | 6        | 1
        """
    )
    res = t.deduplicate(value=pw.this.a, acceptor=lambda new, old: new > old)
    assert [r["a"] for r in capture_rows(res)] == [5]


def test_iterate():
    t = T(
        """
        a
        1
        5
        """
    )

    def logic(t):
        return dict(t=t.select(a=pw.if_else(t.a < 100, t.a * 2, t.a)))

    res = pw.iterate(logic, t=t)
    assert sorted(r["a"] for r in capture_rows(res.t)) == [128, 160]


def test_update_stream_incremental_sum():
    t = T(
        """
        v | __time__ | __diff__
        1 | 0        | 1
        2 | 2        | 1
        1 | 4        | -1
        """
    )
    total = t.reduce(total=pw.reducers.sum(pw.this.v))
    from .utils import capture_update_stream

    stream = capture_update_stream(total)
    values = [(r["total"], r["__diff__"]) for r in stream]
    assert values == [(1, 1), (1, -1), (3, 1), (3, -1), (2, 1)]


def test_sql():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        5 | 6
        """
    )
    res = pw.sql("SELECT a, b, a + b AS s FROM tab WHERE a > 1", tab=t)
    rows = sorted(capture_rows(res), key=lambda r: r["a"])
    assert rows == [{"a": 3, "b": 4, "s": 7}, {"a": 5, "b": 6, "s": 11}]

    agg = pw.sql("SELECT sum(a) AS total FROM tab", tab=t)
    assert capture_rows(agg) == [{"total": 9}]


def test_universe_algebra_structural_queries():
    """Intersect/union/difference key-set reasoning (reference universe_solver's
    SAT queries, derived structurally here)."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import universe_solver

    a = pw.debug.table_from_rows(pw.schema_builder({"x": int}), [(1,), (2,), (3,)])
    b = a.filter(a.x > 1)
    c = a.filter(a.x < 3)
    inter = b.intersect(c)
    # intersection is inside each parent
    assert universe_solver.query_is_subset(inter._universe, b._universe)
    assert universe_solver.query_is_subset(inter._universe, c._universe)
    # b <= intersection's parents individually does NOT imply b inside inter
    assert not universe_solver.query_is_subset(b._universe, inter._universe)
    # x <= intersect(b, c) when x <= b and x <= c
    d = b.intersect(c).filter(pw.this.x == 2)
    assert universe_solver.query_is_subset(d._universe, inter._universe)

    u = b.concat(c.difference(b))
    # every part sits inside the union
    assert universe_solver.query_is_subset(b._universe, u._universe)
    # union <= a because each part <= a
    assert universe_solver.query_is_subset(u._universe, a._universe)

    diff = a.difference(b)
    assert universe_solver.query_is_subset(diff._universe, a._universe)
    # difference is disjoint from its right argument
    assert universe_solver.query_are_disjoint(diff._universe, b._universe)


def test_with_universe_of_runtime_violation():
    import pytest

    import pathway_tpu as pw

    a = pw.debug.table_from_rows(pw.schema_builder({"x": int}), [(1,), (2,), (3,)])
    b = pw.debug.table_from_rows(pw.schema_builder({"y": int}), [(10,), (20,)])
    # force the promise although the key sets differ — runtime must catch the lie
    a.promise_universes_are_equal(b)
    res = a.with_universe_of(b)
    pw.io.subscribe(res, on_batch=lambda *args: None)
    with pytest.raises(RuntimeError, match="universe equality violated"):
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)


def test_join_frontier_skips_probe_side_arrangement():
    """Static build side: once the build subtree is closed, the streaming probe
    side must NOT be arranged (frontier optimization) — and results stay exact.
    Asserts the code path, not just the values (VERDICT r2 'weak' item 2)."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.engine.runner import GraphRunner

    pg.G.clear()
    # probe rows stream across 4 commits; the build table is static
    probe_rows = [(f"u{i % 5}", 2 * (i // 8), 1) for i in range(32)]
    lt = pw.debug.table_from_rows(
        pw.schema_builder({"k": str}), probe_rows, is_stream=True
    )
    rt = pw.debug.table_from_rows(
        pw.schema_builder({"k2": str, "name": str}),
        [(f"u{i}", f"n{i}") for i in range(5)],
    )
    j = lt.join(rt, lt.k == rt.k2).select(lt.k, rt.name)
    got = []
    pw.io.subscribe(
        j,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["k"].tolist(), columns["name"].tolist(), diffs.tolist())
        ),
    )
    runner = GraphRunner(pg.G._current)
    runner.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert sorted(got) == sorted(
        [(f"u{i % 5}", f"n{i % 5}", 1) for i in range(32)]
    )
    join_ev = next(
        ev for ev in runner.evaluators.values()
        if ev.__class__.__name__ == "JoinEvaluator"
    )
    # build side fully arranged; probe side skipped after the first commit
    # (commit 0 carries both deltas, so its probe rows are arranged)
    assert len(join_ev.right.row_index) == 5
    assert len(join_ev.left.row_index) == 8


def test_join_streaming_both_sides_keeps_arranging():
    """When both sides stream, neither side may skip arrangement: a late build
    row must join probe rows from EARLIER commits."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.engine.runner import GraphRunner

    pg.G.clear()
    lt = pw.debug.table_from_rows(
        pw.schema_builder({"k": str}),
        [("a", 0, 1), ("b", 2, 1)],
        is_stream=True,
    )
    rt = pw.debug.table_from_rows(
        pw.schema_builder({"k2": str, "v": int}),
        [("b", 10, 0, 1), ("a", 20, 4, 1)],  # "a" arrives AFTER probe row "a"
        is_stream=True,
    )
    j = lt.join(rt, lt.k == rt.k2).select(lt.k, rt.v)
    got = []
    pw.io.subscribe(
        j,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["k"].tolist(), columns["v"].tolist(), diffs.tolist())
        ),
    )
    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert sorted(got) == [("a", 20, 1), ("b", 10, 1)]


def test_nondeterministic_udf_retraction_replays_value():
    """A UDF flagged deterministic=False must emit the SAME value when a row
    retracts as it did on insert (reference UDF `deterministic` contract) — the
    engine memoizes insert results and replays them instead of re-invoking."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.engine.runner import GraphRunner

    calls = [0]

    def nondet(x: str) -> str:
        calls[0] += 1
        return f"{x}#{calls[0]}"

    pg.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"k": str}),
        [("a", 0, 1), ("b", 0, 1), ("a", 2, -1)],
        is_stream=True,
    )
    udf = pw.udf(nondet, deterministic=False)
    res = t.select(t.k, v=udf(t.k))
    got = []
    pw.io.subscribe(
        res,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["v"].tolist(), diffs.tolist())
        ),
    )
    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert calls[0] == 2  # once per inserted row; the retraction replayed
    ins_a = [v for v, d in got if d == 1 and v.startswith("a#")]
    ret = [v for v, d in got if d == -1]
    assert ret == ins_a  # retraction carries the inserted value verbatim


def test_join_frontier_still_evicts_retracted_rows():
    """Rows arranged BEFORE the build side closed must still evict when they
    retract later, even though new inserts skip arrangement (no state leak)."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.engine.runner import GraphRunner

    pg.G.clear()
    lt = pw.debug.table_from_rows(
        pw.schema_builder({"k": str}),
        # commit 0: a, b (arranged — build delta arrives same commit);
        # commit 1: retract a (must evict); commit 2: c (skip-arranged)
        [("a", 0, 1), ("b", 0, 1), ("a", 2, -1), ("c", 4, 1)],
        is_stream=True,
    )
    rt = pw.debug.table_from_rows(
        pw.schema_builder({"k2": str, "v": int}),
        [("a", 1), ("b", 2), ("c", 3)],
    )
    j = lt.join(rt, lt.k == rt.k2).select(lt.k, rt.v)
    got = []
    pw.io.subscribe(
        j,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["k"].tolist(), diffs.tolist())
        ),
    )
    runner = GraphRunner(pg.G._current)
    runner.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert sorted(got) == [("a", -1), ("a", 1), ("b", 1), ("c", 1)]
    join_ev = next(
        ev for ev in runner.evaluators.values()
        if ev.__class__.__name__ == "JoinEvaluator"
    )
    # "a" evicted, "b" stays (commit-0 arranged), "c" never arranged
    assert len(join_ev.left.row_index) == 1


def test_cross_table_reference_is_live():
    """A select reading ANOTHER table's column must re-emit affected rows when
    that table updates, even with no delta on its own input (reference: cross
    reads are dataflow edges in DD, not snapshot lookups)."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg

    pg.G.clear()
    base = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "x": int}),
        [("a", 1, 0, 1), ("b", 2, 0, 1)],
        is_stream=True,
    )
    # companion (comp2 below) shares base's universe; its value for "b" flips
    # at t=2 via update_cells from a late stream
    late = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "f": int}),
        [("b", 99, 2, 1)],
        is_stream=True,
    )
    keyed = late.with_id_from(late.k)
    rekeyed_base = base.with_id_from(base.k)
    comp2 = rekeyed_base.select(f=pw.this.x * 10).update_cells(
        keyed.select(keyed.f)
    )
    out = rekeyed_base.select(rekeyed_base.x, y=comp2.f + 1)
    events = []
    pw.io.subscribe(
        out,
        on_batch=lambda keys, diffs, columns, time: events.extend(
            (time, x, y, d)
            for x, y, d in zip(
                columns["x"].tolist(), columns["y"].tolist(), diffs.tolist()
            )
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # t=0: initial values; t=2: row b re-emits with the patched companion value
    assert (0, 2, 21, 1) in events
    assert (2, 2, 21, -1) in events and (2, 2, 100, 1) in events
    # row a untouched at t=2 (no spurious churn from the refresh)
    assert not any(t == 2 and x == 1 for t, x, _y, _d in events)


def test_two_selects_sharing_cross_reference():
    """Review repro: TWO selects referencing the same cross table must both
    materialize their states (per-node cross-ref detection, not needed-set
    growth) and both re-fire on the referenced table's update."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg

    pg.G.clear()
    base = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "x": int}),
        [("a", 1, 0, 1), ("b", 2, 0, 1)],
        is_stream=True,
    )
    late = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "f": int}), [("b", 99, 2, 1)], is_stream=True
    )
    rb = base.with_id_from(base.k)
    comp = rb.select(f=pw.this.x * 10).update_cells(
        late.with_id_from(late.k).select(f=pw.this.f)
    )
    out1 = rb.select(rb.x, y=comp.f + 1)
    out2 = rb.select(rb.x, z=comp.f + 2)
    got1, got2 = [], []
    pw.io.subscribe(
        out1,
        on_batch=lambda keys, diffs, columns, time: got1.extend(
            zip(columns["y"].tolist(), diffs.tolist())
        ),
    )
    pw.io.subscribe(
        out2,
        on_batch=lambda keys, diffs, columns, time: got2.extend(
            zip(columns["z"].tolist(), diffs.tolist())
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert (100, 1) in got1 and (21, -1) in got1
    assert (101, 1) in got2 and (22, -1) in got2
