import os

# Tests run on a virtual 8-device CPU mesh. The axon TPU plugin is registered by
# sitecustomize at interpreter start (before this file runs) and its client grabs the
# single-tenant TPU tunnel even for CPU work — deregister its backend factory so test
# runs never touch (or block on) the TPU.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Background jit pre-warm of every pow2 bucket is the encoder SERVICE's startup
# behavior; under the test suite it would burn CPU compiling tiny throwaway
# models per embedder construction. Default it off (the pre-warm tests opt back
# in with monkeypatch / explicit ctor args).
os.environ.setdefault("PATHWAY_ENCSVC_PREWARM", "0")

try:
    import jax
    from jax._src import xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import pytest


@pytest.fixture
def leak_oracle():
    """Dynamic resource-leak oracle — the PWA201 static model proven against
    the live runtime. Snapshots this process's fds (with their targets) and
    threads before the test and fails on growth after it: a leaked socket,
    pipe, file handle, or thread surviving the test is exactly the
    acquire-without-release class the resource lint hunts. A generous settling
    grace absorbs teardown that legitimately takes a moment under full-suite
    load (daemon reapers, GC-driven closes)."""
    import gc
    import threading
    import time

    fd_dir = "/proc/self/fd"

    def fd_snapshot():
        out = {}
        for fd in os.listdir(fd_dir):
            try:
                out[fd] = os.readlink(os.path.join(fd_dir, fd))
            except OSError:
                pass  # raced a close (or the listdir fd itself)
        return out

    before_fds = fd_snapshot()
    before_threads = {t.ident for t in threading.enumerate()}
    yield
    deadline = time.monotonic() + 60
    while True:
        gc.collect()
        after_fds = fd_snapshot()
        new_threads = [
            t
            for t in threading.enumerate()
            if t.ident not in before_threads and t.is_alive()
        ]
        fd_growth = len(after_fds) - len(before_fds)
        new_sockets = [
            target
            for fd, target in after_fds.items()
            if fd not in before_fds and "socket" in target
        ]
        if fd_growth <= 0 and not new_threads and not new_sockets:
            break
        if time.monotonic() > deadline:
            raise AssertionError(
                "leak oracle: resources grew across the test — "
                f"fd growth {fd_growth} (new sockets: {new_sockets}), "
                f"leaked threads: {[t.name for t in new_threads]}"
            )
        time.sleep(0.5)


@pytest.fixture(autouse=True)
def clear_graph():
    """Each test gets a fresh global parse graph."""
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    yield
    G.clear()


@pytest.fixture(autouse=True)
def clear_brownout():
    """The brownout ladder is a process-wide singleton fed by admission
    probes; a shed test saturating one coalescer must not leave a rung
    engaged (tightened caps, shrunken coalesce windows) for the next test."""
    from pathway_tpu.engine.brownout import reset_brownout

    reset_brownout()
    yield
    reset_brownout()
