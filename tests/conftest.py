import os

# Tests run on a virtual 8-device CPU mesh. The axon TPU plugin is registered by
# sitecustomize at interpreter start (before this file runs) and its client grabs the
# single-tenant TPU tunnel even for CPU work — deregister its backend factory so test
# runs never touch (or block on) the TPU.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Background jit pre-warm of every pow2 bucket is the encoder SERVICE's startup
# behavior; under the test suite it would burn CPU compiling tiny throwaway
# models per embedder construction. Default it off (the pre-warm tests opt back
# in with monkeypatch / explicit ctor args).
os.environ.setdefault("PATHWAY_ENCSVC_PREWARM", "0")

try:
    import jax
    from jax._src import xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import pytest


@pytest.fixture(autouse=True)
def clear_graph():
    """Each test gets a fresh global parse graph."""
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    yield
    G.clear()


@pytest.fixture(autouse=True)
def clear_brownout():
    """The brownout ladder is a process-wide singleton fed by admission
    probes; a shed test saturating one coalescer must not leave a rung
    engaged (tightened caps, shrunken coalesce windows) for the next test."""
    from pathway_tpu.engine.brownout import reset_brownout

    reset_brownout()
    yield
    reset_brownout()
