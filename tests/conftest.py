import os

# tests run on a virtual 8-device CPU mesh — set before jax initializes
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest


@pytest.fixture(autouse=True)
def clear_graph():
    """Each test gets a fresh global parse graph."""
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    yield
    G.clear()
