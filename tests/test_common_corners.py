"""Core-semantics corner cases ported (re-written) from the reference's
``python/pathway/tests/test_common.py`` — the update_cells/update_rows/ix/
concat/typing/reducer/join edges VERDICT r3 item 9 called out as thin."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg

from .utils import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
    capture_rows,
)


def setup_function(_fn):
    pg.G.clear()


def _run(table):
    return capture_rows(table)


# -- ix corners ----------------------------------------------------------------


def test_ix_missing_key_raises():
    t = T(
        """
          | k | a
        1 | x | 1
        """
    )
    bad = t.select(b=t.ix(t.pointer_from("nope")).a)
    with pytest.raises(Exception, match="missing key"):
        _run(bad)


def test_ix_optional_missing_gives_none():
    t = T(
        """
          | k | a
        1 | x | 1
        """
    )
    res = t.select(b=t.ix(t.pointer_from("nope"), optional=True).a)
    assert [r["b"] for r in _run(res)] == [None]


def test_ix_self_select():
    t = T(
        """
          | a
        1 | 10
        2 | 20
        """
    )
    res = t.select(b=t.ix(t.id).a)
    assert sorted(r["b"] for r in _run(res)) == [10, 20]


def test_multiple_ix_in_one_select():
    keyed = T(
        """
          | k | v
        1 | a | 1
        2 | b | 2
        """
    ).with_id_from(pw.this.k)
    src = T(
        """
          | k1 | k2
        1 | a  | b
        """
    )
    res = src.select(
        x=keyed.ix(keyed.pointer_from(src.k1)).v,
        y=keyed.ix(keyed.pointer_from(src.k2)).v,
    )
    rows = _run(res)
    assert rows == [{"x": 1, "y": 2}]


def test_ix_ref_with_primary_keys():
    t = T(
        """
        k | v
        a | 1
        b | 2
        """
    ).with_id_from(pw.this.k)
    q = T(
        """
          | key
        1 | b
        2 | a
        """
    )
    res = q.select(v=t.ix_ref(q.key).v)
    assert sorted(r["v"] for r in _run(res)) == [1, 2]


def test_groupby_ix():
    t = T(
        """
          | g | v
        1 | a | 1
        2 | a | 2
        3 | b | 5
        """
    )
    best = t.groupby(t.g).reduce(t.g, argmax=pw.reducers.argmax(t.v))
    res = best.select(best.g, top=t.ix(best.argmax).v)
    assert sorted((r["g"], r["top"]) for r in _run(res)) == [("a", 2), ("b", 5)]


# -- update_cells / update_rows corners ---------------------------------------


def test_update_cells_empty_patch():
    t = T(
        """
          | a | b
        1 | 1 | x
        """
    )
    patch = t.filter(t.a > 100).select(t.b)
    patch = patch.promise_universe_is_subset_of(t)
    res = t.update_cells(patch)
    assert _run(res) == [{"a": 1, "b": "x"}]


def test_update_cells_unknown_column_raises():
    t = T(
        """
          | a
        1 | 1
        """
    )
    patch = T(
        """
          | zz
        1 | 9
        """
    )
    with pytest.raises(Exception):
        t.update_cells(patch)


def test_update_cells_subset_patch_universe():
    t = T(
        """
          | v
        1 | 10
        2 | 20
        3 | 30
        """
    )
    patch = t.filter(t.v >= 20).select(v=t.v * 100)
    patch = patch.promise_universe_is_subset_of(t)
    res = t.update_cells(patch)
    assert sorted(r["v"] for r in _run(res)) == [10, 2000, 3000]


def test_update_rows_empty_patch():
    t = T(
        """
          | a
        1 | 1
        """
    )
    patch = t.filter(t.a > 100)
    res = t.update_rows(patch)
    assert _run(res) == [{"a": 1}]


def test_update_rows_columns_must_match():
    t = T(
        """
          | a
        1 | 1
        """
    )
    other = T(
        """
          | b
        1 | 2
        """
    )
    with pytest.raises(Exception):
        t.update_rows(other)


def test_with_columns_replaces_and_keeps():
    t = T(
        """
          | a | b
        1 | 1 | x
        """
    )
    res = t.with_columns(b=t.a * 10)
    assert _run(res) == [{"a": 1, "b": 10}]


# -- concat corners ------------------------------------------------------------


def test_concat_disjoint_ok_and_column_order_irrelevant():
    a = T(
        """
          | x | y
        1 | 1 | a
        """
    )
    b = T(
        """
          | y | x
        9 | b | 2
        """
    )
    res = a.concat(b)
    assert sorted((r["x"], r["y"]) for r in _run(res)) == [(1, "a"), (2, "b")]


def test_concat_overlapping_universes_raises():
    a = T(
        """
          | x
        1 | 1
        """
    )
    b = T(
        """
          | x
        1 | 2
        """
    )
    with pytest.raises(Exception):
        _run(a.concat(b))


def test_concat_reindex_never_collides():
    a = T(
        """
          | x
        1 | 1
        """
    )
    b = T(
        """
          | x
        1 | 2
        """
    )
    res = a.concat_reindex(b)
    assert sorted(r["x"] for r in _run(res)) == [1, 2]


# -- typing / expression corners ----------------------------------------------


def test_cast_int_to_float_and_back():
    t = T(
        """
          | a
        1 | 3
        """
    )
    res = t.select(f=pw.cast(float, t.a), i=pw.cast(int, pw.cast(float, t.a) * 2.5))
    rows = _run(res)
    assert rows[0]["f"] == 3.0 and isinstance(rows[0]["f"], float)
    assert rows[0]["i"] == 7


def test_coalesce_optional_chain():
    t = T(
        """
          | a | b
        1 |   | 5
        2 | 3 |
        """
    )
    res = t.select(v=pw.coalesce(t.a, t.b, 0))
    assert sorted(r["v"] for r in _run(res)) == [3, 5]


def test_unwrap_raises_on_none():
    t = T(
        """
          | a
        1 |
        """
    )
    res = t.select(v=pw.unwrap(t.a))
    with pytest.raises(Exception):
        _run(res)


def test_unwrap_passes_values():
    t = T(
        """
          | a
        1 | 4
        """
    )
    assert _run(t.select(v=pw.unwrap(t.a))) == [{"v": 4}]


def test_require_propagates_none():
    t = T(
        """
          | a | b
        1 | 1 | 2
        2 | 1 |
        """
    )
    res = t.select(v=pw.require(t.a * 10, t.b))
    assert sorted((r["v"] for r in _run(res)), key=repr) == [10, None]


def test_make_tuple_and_get():
    t = T(
        """
          | a | b
        1 | 1 | 2
        """
    )
    res = t.select(tup=pw.make_tuple(t.a, t.b, 9))
    res2 = res.select(first=res.tup[0], last=res.tup[-1], missing=res.tup.get(7, -1))
    assert _run(res2) == [{"first": 1, "last": 9, "missing": -1}]


def test_sequence_get_out_of_bounds_checked_raises():
    t = T(
        """
          | a
        1 | 1
        """
    )
    res = t.select(tup=pw.make_tuple(t.a)).select(v=pw.this.tup[5])
    with pytest.raises(Exception):
        _run(res)


def test_sequence_get_from_ndarray_cells():
    pg.G.clear()
    vecs = [np.array([1.0, 2.0, 3.0]), np.array([4.0, 5.0, 6.0])]
    t = pw.debug.table_from_rows(
        pw.schema_builder({"v": np.ndarray}), [(vecs[0],), (vecs[1],)]
    )
    res = t.select(x=t.v[1])
    assert sorted(r["x"] for r in _run(res)) == [2.0, 5.0]


def test_if_else_branch_types():
    t = T(
        """
          | a
        1 | 1
        2 | 5
        """
    )
    res = t.select(v=pw.if_else(t.a > 3, t.a * 10, t.a - 1))
    assert sorted(r["v"] for r in _run(res)) == [0, 50]


def test_declare_type_passthrough():
    t = T(
        """
          | a
        1 | 1
        """
    )
    res = t.select(v=pw.declare_type(float, t.a))
    assert _run(res) == [{"v": 1}]


# -- rename / drop / wildcard corners -----------------------------------------


def test_rename_unknown_column_raises():
    t = T(
        """
          | a
        1 | 1
        """
    )
    with pytest.raises(Exception):
        t.rename_columns(b=pw.this.zz)


def test_rename_by_dict_and_without():
    t = T(
        """
          | a | b | c
        1 | 1 | 2 | 3
        """
    )
    res = t.rename_by_dict({"a": "x"}).without(pw.this.b)
    assert _run(res) == [{"x": 1, "c": 3}]


def test_wildcard_without_shadowing():
    t = T(
        """
          | a | b
        1 | 1 | 2
        """
    )
    res = t.select(*pw.this.without(pw.this.a), a=t.a * 100)
    assert _run(res) == [{"b": 2, "a": 100}]


# -- groupby / reducer corners -------------------------------------------------


def test_argmin_argmax_tie_is_deterministic():
    t = T(
        """
          | g | v
        1 | a | 1
        2 | a | 1
        3 | a | 1
        """
    )
    r1 = t.groupby(t.g).reduce(m=pw.reducers.argmin(t.v))
    r2 = t.groupby(t.g).reduce(m=pw.reducers.argmin(t.v))
    assert _run(r1) == _run(r2)


def test_earliest_latest_reducers():
    t = T(
        """
        g | v | __time__ | __diff__
        a | 1 | 0        | 1
        a | 2 | 2        | 1
        a | 3 | 4        | 1
        """
    )
    res = t.groupby(t.g).reduce(
        t.g, first=pw.reducers.earliest(t.v), last=pw.reducers.latest(t.v)
    )
    assert _run(res) == [{"g": "a", "first": 1, "last": 3}]


def test_unique_reducer_raises_on_conflict():
    t = T(
        """
          | g | v
        1 | a | 1
        2 | a | 2
        """
    )
    res = t.groupby(t.g).reduce(v=pw.reducers.unique(t.v))
    with pytest.raises(Exception):
        _run(res)


def test_unique_reducer_passes_single_value():
    t = T(
        """
          | g | v
        1 | a | 7
        2 | a | 7
        """
    )
    res = t.groupby(t.g).reduce(t.g, v=pw.reducers.unique(t.v))
    assert _run(res) == [{"g": "a", "v": 7}]


def test_avg_reducer():
    t = T(
        """
          | g | v
        1 | a | 1.0
        2 | a | 3.0
        """
    )
    res = t.groupby(t.g).reduce(t.g, m=pw.reducers.avg(t.v))
    assert _run(res) == [{"g": "a", "m": 2.0}]


def test_ndarray_reducer_stacks():
    t = T(
        """
          | g | v
        1 | a | 1.0
        2 | a | 2.0
        """
    )
    res = t.groupby(t.g).reduce(t.g, arr=pw.reducers.ndarray(t.v))
    rows = _run(res)
    assert sorted(rows[0]["arr"].tolist()) == [1.0, 2.0]


def test_groupby_reduce_no_columns_global():
    t = T(
        """
          | v
        1 | 1
        2 | 2
        """
    )
    res = t.reduce(n=pw.reducers.count(), s=pw.reducers.sum(t.v))
    assert _run(res) == [{"n": 2, "s": 3}]


def test_groupby_instance_splits_argmax():
    t = T(
        """
          | i | g | v
        1 | 0 | a | 1
        2 | 0 | a | 9
        3 | 1 | a | 5
        """
    )
    res = t.groupby(t.g, instance=t.i).reduce(
        t.g, mx=pw.reducers.max(t.v)
    )
    assert sorted(r["mx"] for r in _run(res)) == [5, 9]


def test_groupby_rejects_anonymous_expressions():
    # grouping must be over NAMED columns (reference requires select-first too)
    t = T(
        """
          | v
        1 | 1
        2 | 2
        3 | 3
        """
    )
    with pytest.raises(Exception):
        t.groupby(t.v % 2).reduce(n=pw.reducers.count())
    res = (
        t.select(t.v, parity=t.v % 2)
        .groupby(pw.this.parity)
        .reduce(n=pw.reducers.count())
    )
    assert sorted(r["n"] for r in _run(res)) == [1, 2]


def test_tuple_reducer_and_sorted_tuple():
    t = T(
        """
        g | v | __time__ | __diff__
        a | 3 | 0        | 1
        a | 1 | 2        | 1
        """
    )
    res = t.groupby(t.g).reduce(
        t.g,
        tup=pw.reducers.tuple(t.v),
        sorted_tup=pw.reducers.sorted_tuple(t.v),
    )
    rows = _run(res)
    assert rows[0]["sorted_tup"] == (1, 3)
    assert sorted(rows[0]["tup"]) == [1, 3]


# -- join corners --------------------------------------------------------------


def test_cross_join_via_constant_key():
    a = T(
        """
          | x
        1 | 1
        2 | 2
        """
    )
    b = T(
        """
          | y
        1 | 10
        2 | 20
        """
    )
    res = a.join(b).select(a.x, b.y)
    assert len(_run(res)) == 4


def test_empty_side_join():
    a = T(
        """
          | k | x
        1 | a | 1
        """
    )
    b = a.filter(a.x > 100).select(k2=pw.this.k, y=pw.this.x)
    res = a.join(b, a.k == b.k2).select(a.x, b.y)
    assert _run(res) == []
    outer = a.join_left(b, a.k == b.k2).select(a.x, y=b.y)
    assert _run(outer) == [{"x": 1, "y": None}]


def test_join_self_alias():
    t = T(
        """
          | k | v
        1 | a | 1
        2 | a | 2
        """
    )
    other = t.copy()
    res = t.join(other, t.k == other.k).select(l=t.v, r=other.v)
    assert len(_run(res)) == 4


def test_join_chain_through_two_tables():
    a = T(
        """
          | k | x
        1 | p | 1
        """
    )
    b = T(
        """
          | k | y
        1 | p | 2
        """
    )
    c = T(
        """
          | k | z
        1 | p | 3
        """
    )
    res = (
        a.join(b, a.k == b.k)
        .select(a.k, a.x, b.y)
        .join(c, pw.left.k == c.k)
        .select(pw.left.x, pw.left.y, c.z)
    )
    assert _run(res) == [{"x": 1, "y": 2, "z": 3}]


def test_join_with_id_assignment():
    a = T(
        """
          | k | x
        1 | p | 1
        """
    )
    b = T(
        """
          | k | y
        1 | p | 2
        """
    )
    res = a.join(b, a.k == b.k, id=a.id).select(a.x, b.y)
    rows_a = {k for k in pw.debug._capture_table(a)}
    rows_j = {k for k in pw.debug._capture_table(res)}
    assert rows_a == rows_j


def test_join_filter_then_reduce():
    a = T(
        """
          | k | x
        1 | p | 1
        2 | p | 5
        3 | q | 7
        """
    )
    b = T(
        """
          | k | lim
        1 | p | 3
        2 | q | 3
        """
    )
    res = (
        a.join(b, a.k == b.k)
        .select(a.k, a.x, b.lim)
        .filter(pw.this.x > pw.this.lim)
        .groupby(pw.this.k)
        .reduce(pw.this.k, n=pw.reducers.count())
    )
    assert sorted((r["k"], r["n"]) for r in _run(res)) == [("p", 1), ("q", 1)]


# -- flatten corners -----------------------------------------------------------


def test_flatten_string_to_chars():
    t = T(
        """
          | s
        1 | ab
        """
    )
    res = t.flatten(t.s)
    assert sorted(r["s"] for r in _run(res)) == ["a", "b"]


def test_flatten_with_origin_id():
    pg.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"v": tuple}), [((1, 2),), ((3,),)]
    )
    res = t.flatten(t.v, origin_id="src")
    rows = _run(res)
    assert sorted(r["v"] for r in rows) == [1, 2, 3]
    assert all(r["src"] is not None for r in rows)


def test_flatten_non_iterable_raises():
    t = T(
        """
          | v
        1 | 5
        """
    )
    with pytest.raises(Exception):
        _run(t.flatten(t.v))


# -- filter / reindex / universes ---------------------------------------------


def test_filter_column_from_different_universe_raises():
    a = T(
        """
          | x
        1 | 1
        """
    )
    b = T(
        """
          | y
        7 | 1
        """
    )
    with pytest.raises(Exception):
        _run(a.filter(b.y > 0))


def test_reindex_with_id_from_column():
    t = T(
        """
          | k | v
        1 | a | 1
        2 | b | 2
        """
    )
    res = t.with_id_from(t.k)
    res2 = res.select(w=res.ix_ref("a").v + res.v)
    assert sorted(r["w"] for r in _run(res2)) == [2, 3]


def test_restrict_to_subset():
    t = T(
        """
          | v
        1 | 1
        2 | 2
        3 | 3
        """
    )
    keep = t.filter(t.v != 2)
    res = t.restrict(keep)
    assert sorted(r["v"] for r in _run(res)) == [1, 3]


def test_intersect_many_tables():
    t = T(
        """
          | v
        1 | 1
        2 | 2
        3 | 3
        """
    )
    a = t.filter(t.v >= 2)
    b = t.filter(t.v <= 2)
    res = t.intersect(a, b)
    assert [r["v"] for r in _run(res)] == [2]


def test_difference():
    t = T(
        """
          | v
        1 | 1
        2 | 2
        """
    )
    res = t.difference(t.filter(t.v == 1))
    assert [r["v"] for r in _run(res)] == [2]


# -- iterate corners -----------------------------------------------------------


def test_iterate_with_limit_stops_early():
    t = T(
        """
          | v
        1 | 0
        """
    )

    def step(t):
        return dict(t=t.select(v=t.v + 1))

    res = pw.iterate(step, iteration_limit=3, t=t).t
    assert _run(res) == [{"v": 3}]


def test_iterate_wrong_limit_raises():
    t = T(
        """
          | v
        1 | 0
        """
    )
    with pytest.raises(ValueError):
        pw.iterate(lambda t: dict(t=t), iteration_limit=0, t=t)


def test_iterate_collatz_fixpoint():
    t = T(
        """
          | v
        1 | 6
        2 | 7
        3 | 1
        """
    )

    def collatz(t):
        nxt = pw.if_else(
            t.v == 1, t.v, pw.if_else(t.v % 2 == 0, t.v // 2, 3 * t.v + 1)
        )
        return dict(t=t.select(v=nxt))

    res = pw.iterate(collatz, t=t).t
    assert [r["v"] for r in _run(res)] == [1, 1, 1]


def test_pointer_pickle_roundtrip():
    """Slots + frozen Pointer must survive pickle (cluster exchange frames and
    persistence journals carry Pointer cells in object columns)."""
    import pickle

    from pathway_tpu.internals.keys import Pointer

    p = Pointer(0x1234_5678_9ABC_DEF0, 0xFEDC_BA98_7654_3210)
    q = pickle.loads(pickle.dumps(p, protocol=pickle.HIGHEST_PROTOCOL))
    assert (q.hi, q.lo) == (p.hi, p.lo)
    assert q == p
