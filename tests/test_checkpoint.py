"""Coordinated cluster checkpoints + incremental rewind.

Three layers under test:

- persistence (``persistence/engine.py``): versioned per-rank snapshots, the
  cluster checkpoint manifest (atomic write, read-back verification, torn-
  manifest fallback, worker-count/key-derivation guards), journal compaction;
- mesh (``parallel/cluster.py``): the per-commit serve log a rewound survivor
  replays to a recovering peer (record/seal/discard/prune/depth bound);
- chaos (``internals/chaos.py``): checkpoint-phase fault entries (kill between
  snapshot and manifest, torn manifest bytes, snapshot write error) — and the
  spawn acceptance runs proving every one of them leaves the PREVIOUS
  checkpoint recoverable bit-identically.

The n=4 acceptance (kill a rank after >=2 coordinated checkpoints -> recovery
from checkpoint + journal tail, output bit-identical) carries a hand-rolled
hard timeout: a wedged rejoin SIGKILLs the process group and fails fast
instead of eating the tier-1 budget.
"""

from __future__ import annotations

import itertools
import json
import os
import pickle
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.chaos import Chaos, ChaosBackendError, reset_chaos
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.persistence.engine import (
    KEY_DERIVATION_VERSION,
    PersistenceManager,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PORT_SLOT = itertools.count()


def _port_base() -> int:
    # distinct base per wiring so back-to-back tests never contend on TIME_WAIT
    return 33000 + os.getpid() % 150 * 40 + next(_PORT_SLOT) * 8


def _manager(tmp_path) -> PersistenceManager:
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(tmp_path / "store"))
    return PersistenceManager(cfg)


SIG = "test-graph-sig"


# -- persistence: snapshot/manifest atomicity ---------------------------------


@pytest.mark.checkpoint
def test_cluster_snapshot_manifest_roundtrip(tmp_path):
    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    blob = {"states": {1: b"abc"}, "evaluators": {}, "source_offsets": {},
            "source_deltas": {}}
    size = pm.dump_cluster_snapshot(SIG, 7, blob)
    assert size > 0
    assert pm.commit_cluster_manifest(SIG, 7, epoch=2) is True

    pm2 = _manager(tmp_path)
    manifest = pm2.load_cluster_manifest(SIG)
    assert manifest is not None
    assert manifest["commit_id"] == 7
    assert manifest["epoch"] == 2
    assert manifest["workers"] == 1
    assert manifest["key_derivation"] == KEY_DERIVATION_VERSION
    assert pm2.load_cluster_snapshot(SIG, 7) == blob


@pytest.mark.checkpoint
def test_interrupted_snapshot_write_never_corrupts_load(tmp_path):
    """A crash mid-``dump_cluster_snapshot`` leaves only a ``.tmp`` file (the
    rename never ran); a later load must see the PREVIOUS checkpoint exactly."""
    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    good = {"states": {1: b"good"}, "evaluators": {}, "source_offsets": {},
            "source_deltas": {}}
    pm.dump_cluster_snapshot(SIG, 5, good)
    assert pm.commit_cluster_manifest(SIG, 5)

    # simulated crash: half-written tmp for the NEXT attempt, no manifest
    torn = os.path.join(pm.root, "checkpoint-0000000009.pkl.tmp")
    with open(torn, "wb") as f:
        f.write(pickle.dumps({"sig": SIG})[:10])

    pm2 = _manager(tmp_path)
    manifest = pm2.load_cluster_manifest(SIG)
    assert manifest["commit_id"] == 5
    assert pm2.load_cluster_snapshot(SIG, 5) == good


@pytest.mark.checkpoint
def test_torn_manifest_falls_back_to_previous(tmp_path):
    """Torn manifest bytes (non-atomic store, crash mid-PUT): the loader skips
    the unreadable manifest with a warning and serves the previous one."""
    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    blob = {"states": {}, "evaluators": {}, "source_offsets": {}, "source_deltas": {}}
    pm.dump_cluster_snapshot(SIG, 3, blob)
    assert pm.commit_cluster_manifest(SIG, 3)

    # a NEWER manifest whose bytes tore mid-write
    raw = json.dumps({"format": 1, "sig": SIG, "commit_id": 9}).encode()
    with open(tmp_path / "store" / "cluster-manifest-0000000009.json", "wb") as f:
        f.write(raw[: len(raw) // 2])

    pm2 = _manager(tmp_path)
    manifest = pm2.load_cluster_manifest(SIG)
    assert manifest is not None and manifest["commit_id"] == 3


@pytest.mark.checkpoint
def test_manifest_name_content_mismatch_treated_as_torn(tmp_path):
    """A manifest whose body names a different commit than its filename is a
    corrupt write, not a checkpoint — skipped like torn bytes."""
    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    blob = {"states": {}, "evaluators": {}, "source_offsets": {}, "source_deltas": {}}
    pm.dump_cluster_snapshot(SIG, 3, blob)
    assert pm.commit_cluster_manifest(SIG, 3)
    meta = json.loads(
        (tmp_path / "store" / "cluster-manifest-0000000003.json").read_bytes()
    )
    (tmp_path / "store" / "cluster-manifest-0000000011.json").write_bytes(
        json.dumps(meta, sort_keys=True).encode()  # body still says commit 3
    )
    pm2 = _manager(tmp_path)
    assert pm2.load_cluster_manifest(SIG)["commit_id"] == 3


@pytest.mark.checkpoint
def test_manifest_refuses_worker_count_and_key_derivation_mismatch(tmp_path):
    """Same guards as the PWTPUJ2 journal header: a manifest from a different
    worker count or key-derivation version must refuse LOUDLY (silently
    starting from a mismatched shard layout loses data)."""
    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    blob = {"states": {}, "evaluators": {}, "source_offsets": {}, "source_deltas": {}}
    pm.dump_cluster_snapshot(SIG, 4, blob)
    assert pm.commit_cluster_manifest(SIG, 4)
    path = tmp_path / "store" / "cluster-manifest-0000000004.json"
    meta = json.loads(path.read_bytes())

    meta_bad = dict(meta, workers=4)
    path.write_bytes(json.dumps(meta_bad, sort_keys=True).encode())
    with pytest.raises(ValueError, match="worker process"):
        _manager(tmp_path).load_cluster_manifest(SIG)

    meta_bad = dict(meta, key_derivation=KEY_DERIVATION_VERSION + 1)
    path.write_bytes(json.dumps(meta_bad, sort_keys=True).encode())
    with pytest.raises(ValueError, match="key-derivation"):
        _manager(tmp_path).load_cluster_manifest(SIG)

    # and a manifest from a DIFFERENT graph is refused too
    meta_bad = dict(meta, sig="other-graph")
    path.write_bytes(json.dumps(meta_bad, sort_keys=True).encode())
    with pytest.raises(ValueError, match="different"):
        _manager(tmp_path).load_cluster_manifest(SIG)


@pytest.mark.checkpoint
def test_missing_or_corrupt_snapshot_named_by_manifest_is_loud(tmp_path):
    """The manifest promised the snapshot exists and the journal it subsumed
    is gone — treating a missing/unreadable snapshot as absent would silently
    drop all checkpointed history."""
    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    blob = {"states": {}, "evaluators": {}, "source_offsets": {}, "source_deltas": {}}
    pm.dump_cluster_snapshot(SIG, 6, blob)
    assert pm.commit_cluster_manifest(SIG, 6)

    snap = tmp_path / "store" / "checkpoint-0000000006.pkl"
    snap.write_bytes(b"\x80garbage")
    with pytest.raises(ValueError, match="unreadable"):
        _manager(tmp_path).load_cluster_snapshot(SIG, 6)
    snap.unlink()
    with pytest.raises(ValueError, match="missing"):
        _manager(tmp_path).load_cluster_snapshot(SIG, 6)


@pytest.mark.checkpoint
def test_compaction_and_cleanup_after_manifest(tmp_path):
    """Journal frames <= the manifest commit are compacted; snapshots and
    manifests older than the newest manifest are pruned; the tail-length
    counter resets."""
    from pathway_tpu.engine.columnar import Delta

    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    delta = Delta.empty(["v"])
    pm.record_commit(1, {7: delta}, {7: {"pos": 1}})
    pm.record_commit(2, {7: delta}, {7: {"pos": 2}})
    assert pm.frames_since_compact == 2
    blob = {"states": {}, "evaluators": {}, "source_offsets": {}, "source_deltas": {}}
    pm.dump_cluster_snapshot(SIG, 1, blob)
    assert pm.commit_cluster_manifest(SIG, 1)
    pm.dump_cluster_snapshot(SIG, 2, blob)
    assert pm.commit_cluster_manifest(SIG, 2)
    assert pm.compact_journal(SIG) == 2
    assert pm.frames_since_compact == 0
    pm.cleanup_cluster_checkpoints(2)

    store = tmp_path / "store"
    assert not (store / "checkpoint-0000000001.pkl").exists()
    assert (store / "checkpoint-0000000002.pkl").exists()
    assert not (store / "cluster-manifest-0000000001.json").exists()
    assert (store / "cluster-manifest-0000000002.json").exists()
    pm2 = _manager(tmp_path)
    assert pm2.load_journal(SIG) == []
    assert pm2.load_cluster_manifest(SIG)["commit_id"] == 2


def test_tail_counter_survives_relaunch(tmp_path):
    """``frames_since_compact`` is rebuilt from the loaded journal, not reset
    to 0 per process incarnation — otherwise a relaunched rank publishes
    journal_tail_frames=0 and the recovery-SLO fields claim the next recovery
    is free when it must replay the whole tail."""
    from pathway_tpu.engine.columnar import Delta

    pm = _manager(tmp_path)
    pm.open_for_append(SIG)
    delta = Delta.empty(["v"])
    for cid in (1, 2, 3):
        pm.record_commit(cid, {7: delta}, {7: {"pos": cid}})
    pm.close()

    pm2 = _manager(tmp_path)
    assert len(pm2.load_journal(SIG)) == 3
    assert pm2.frames_since_compact == 3
    # reload (the surgical-rejoin rollback path) must agree
    pm2.open_for_append(SIG)
    assert len(pm2.reload(SIG)) == 3
    assert pm2.frames_since_compact == 3
    pm2.record_commit(4, {7: delta}, {7: {"pos": 4}})
    assert pm2.frames_since_compact == 4
    assert pm2.compact_journal(SIG) == 4
    pm2.close()


# -- chaos: checkpoint-phase fault plan ---------------------------------------


def test_chaos_checkpoint_fault_gating(monkeypatch):
    """``checkpoint`` plan entries key on (op, rank, run, attempt); ``at``
    defaults to every attempt, ``run`` to every incarnation."""
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "0")
    plan = {
        "checkpoint": [
            {"op": "torn_manifest", "rank": 0, "run": 0, "at": 1},
            {"op": "snapshot_error", "rank": 1},
        ]
    }
    c = Chaos(0, plan)
    c.begin_checkpoint_attempt()  # attempt 0
    assert c.checkpoint_fault("torn_manifest", 0) is False  # wrong attempt
    assert c.checkpoint_fault("snapshot_error", 1) is True  # no at: every attempt
    assert c.checkpoint_fault("snapshot_error", 0) is False  # unscheduled rank
    c.begin_checkpoint_attempt()  # attempt 1
    assert c.checkpoint_fault("torn_manifest", 0) is True
    assert c.checkpoint_fault("post_snapshot_kill", 0) is False  # unscheduled op
    assert c.stats["checkpoint_faults"] == 2

    # a restarted incarnation (bumped PATHWAY_RESTART_COUNT) stops firing
    # run-gated entries — the replay after recovery must not re-fault
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "1")
    c2 = Chaos(0, plan)
    c2.begin_checkpoint_attempt()
    c2.begin_checkpoint_attempt()
    assert c2.checkpoint_fault("torn_manifest", 0) is False


def test_chaos_snapshot_error_fails_dump(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps({"checkpoint": [{"op": "snapshot_error", "rank": 0, "at": 0}]}),
    )
    reset_chaos()
    try:
        pm = _manager(tmp_path)
        pm.open_for_append(SIG)
        from pathway_tpu.internals.chaos import get_chaos

        get_chaos().begin_checkpoint_attempt()
        blob = {"states": {}, "evaluators": {}, "source_offsets": {},
                "source_deltas": {}}
        with pytest.raises(ChaosBackendError):
            pm.dump_cluster_snapshot(SIG, 3, blob)
        # ChaosBackendError IS a ConnectionError: the runner's transient-ack
        # triage catches it without special-casing chaos
        assert issubclass(ChaosBackendError, ConnectionError)
        # next attempt (past `at`) succeeds and the store is uncorrupted
        get_chaos().begin_checkpoint_attempt()
        pm.dump_cluster_snapshot(SIG, 4, blob)
        assert pm.commit_cluster_manifest(SIG, 4)
        assert _manager(tmp_path).load_cluster_manifest(SIG)["commit_id"] == 4
    finally:
        monkeypatch.delenv("PATHWAY_CHAOS_PLAN")
        reset_chaos()


def test_chaos_torn_manifest_fails_commit_readback(tmp_path, monkeypatch):
    """The injected torn PUT must be caught by the read-back verification:
    ``commit_cluster_manifest`` returns False and a fresh loader still sees
    the previous checkpoint."""
    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps({"checkpoint": [{"op": "torn_manifest", "rank": 0, "at": 1}]}),
    )
    reset_chaos()
    try:
        pm = _manager(tmp_path)
        pm.open_for_append(SIG)
        from pathway_tpu.internals.chaos import get_chaos

        blob = {"states": {}, "evaluators": {}, "source_offsets": {},
                "source_deltas": {}}
        get_chaos().begin_checkpoint_attempt()  # attempt 0: clean
        pm.dump_cluster_snapshot(SIG, 2, blob)
        assert pm.commit_cluster_manifest(SIG, 2) is True
        get_chaos().begin_checkpoint_attempt()  # attempt 1: torn
        pm.dump_cluster_snapshot(SIG, 5, blob)
        assert pm.commit_cluster_manifest(SIG, 5) is False
        assert _manager(tmp_path).load_cluster_manifest(SIG)["commit_id"] == 2
    finally:
        monkeypatch.delenv("PATHWAY_CHAOS_PLAN")
        reset_chaos()


# -- mesh: incremental-rewind serve log ---------------------------------------


def _wire_pair(first_port):
    from pathway_tpu.parallel.cluster import ClusterExchange

    made: dict = {}
    errors: list = []

    def mk(me: int) -> None:
        try:
            made[me] = ClusterExchange(2, me, first_port)
        except BaseException as exc:
            errors.append(exc)

    threads = [threading.Thread(target=mk, args=(me,)) for me in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, f"wiring failed: {errors}"
    return made[0], made[1]


def test_serve_log_records_seals_and_serves(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    a, b = _wire_pair(_port_base())
    try:
        a.commit_log_depth = b.commit_log_depth = 4
        for cid in range(3):
            a.begin_commit_log(cid)
            b.begin_commit_log(cid)
            done: dict = {}
            t = threading.Thread(
                target=lambda c=cid: done.setdefault(
                    "b", b.exchange_parts(b"neu:%d" % c, {0: b"from-b-%d" % c})
                )
            )
            t.start()
            got = a.exchange_parts(b"neu:%d" % cid, {1: b"from-a-%d" % cid})
            t.join(timeout=10)
            assert got == {1: b"from-b-%d" % cid}
            a.end_commit_log()
            b.end_commit_log()
        assert a.commit_log_covers([0, 1, 2])
        assert not a.commit_log_covers([0, 3])

        # serving commit 1 re-sends the ORIGINAL logged parts: the peer
        # (simulating a tail-replaying replacement) recomputes the same tag
        # live and must receive exactly what the original barrier carried
        out: dict = {}
        t = threading.Thread(
            target=lambda: out.setdefault(
                "b", b.exchange_parts(b"neu:1", {0: b"recomputed-live"})
            )
        )
        t.start()
        assert a.serve_commit_log(1) == 1
        t.join(timeout=10)
        assert out["b"] == {0: b"from-a-1"}
    finally:
        a.close()
        b.close()


def test_serve_log_depth_discard_and_prune(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0")
    from pathway_tpu.parallel.cluster import ClusterExchange

    a, b = _wire_pair(_port_base())
    try:
        a.commit_log_depth = 2
        for cid in range(4):
            a.begin_commit_log(cid)
            a._commit_log[cid].append((b"tag:%d" % cid, {1: b"p"}))
            a.end_commit_log()
        # depth bound: only the newest 2 sealed entries survive
        assert list(a._commit_log) == [2, 3]

        # an interrupted commit's PARTIAL entry is discarded, never served
        a.begin_commit_log(9)
        a._commit_log[9].append((b"tag:9", {1: b"partial"}))
        a.discard_open_commit_log()
        assert 9 not in a._commit_log
        assert a.serve_commit_log(9) == 0

        # a durable checkpoint prunes everything at or behind its commit
        a.prune_commit_log(2)
        assert list(a._commit_log) == [3]
    finally:
        a.close()
        b.close()

    # ThreadExchange never rejoins: its serve log stays disabled
    tx = ClusterExchange.__new__(ClusterExchange)  # no sockets needed
    from pathway_tpu.parallel.cluster import ThreadExchange

    assert ThreadExchange.supports_rejoin is False


# -- runner: REWIND_SAFE gating ----------------------------------------------


def test_rewind_safe_flag_gates_undo_ring():
    """A graph holding an operator with ``REWIND_SAFE = False`` (e.g. the
    external-index evaluator, whose in-place pages would cost more to snapshot
    per commit than the tail replay saves, or the drain-sensitive time-column
    family, whose ``runner.draining`` flush a rejoin replay cannot reproduce)
    must skip the rewind rung."""
    from pathway_tpu.engine.evaluators import (
        BufferEvaluator,
        Evaluator,
        ExternalIndexEvaluator,
        ForgetEvaluator,
        FreezeEvaluator,
    )

    assert Evaluator.REWIND_SAFE is True
    assert ExternalIndexEvaluator.REWIND_SAFE is False
    for cls in (BufferEvaluator, FreezeEvaluator, ForgetEvaluator):
        assert cls.REWIND_SAFE is False, cls.__name__


# -- spawn acceptance ---------------------------------------------------------

CKPT_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

    out_path = os.path.join(tmp, f"out_{pid}.json")
    rows = {}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(repr(key), None)
        with open(out_path + ".tmp", "w") as f:
            json.dump(list(rows.values()), f)
        os.replace(out_path + ".tmp", out_path)

    pw.io.subscribe(counts, on_change)
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
    )
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    """
)

# a wedged rejoin must fail fast, not eat the tier-1 budget
HARD_TIMEOUT_S = 120


def _spawn_ckpt(tmp_path, first_port, *, n, plan, max_restarts, extra_env=None):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_CHAOS_SEED"] = "7"
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(plan)
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "30"
    env["PATHWAY_CHECKPOINT_INTERVAL_S"] = "0.4"
    env.update(extra_env or {})
    prog = tmp_path / "prog.py"
    prog.write_text(CKPT_PROG)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", str(n), "--first-port", str(first_port),
            "--max-restarts", str(max_restarts),
            sys.executable, str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        start_new_session=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )
    # hard-timeout watchdog: a wedged rejoin is SIGKILLed as a group so the
    # test fails in bounded time with the stderr it produced so far
    watchdog = threading.Timer(
        HARD_TIMEOUT_S, lambda: _killpg_quiet(proc.pid)
    )
    watchdog.daemon = True
    watchdog.start()
    return proc, watchdog


def _killpg_quiet(pid: int) -> None:
    try:
        os.killpg(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def _read_merged(tmp_path, n: int) -> dict:
    merged: dict = {}
    for p in range(n):
        path = tmp_path / f"out_{p}.json"
        if not path.exists():
            continue
        try:
            for r in json.loads(path.read_text()):
                merged[r["word"]] = r["total"]
        except ValueError:
            pass
    return merged


def _terminate_group(proc, watchdog) -> str:
    watchdog.cancel()
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    try:
        _, err = proc.communicate(timeout=20)
    except subprocess.TimeoutExpired:
        _killpg_quiet(proc.pid)
        _, err = proc.communicate()
    return err or ""


def _await_counts(proc, tmp_path, n, expected, deadline_s=90) -> dict:
    deadline = time.time() + deadline_s
    merged: dict = {}
    while time.time() < deadline:
        if proc.poll() is not None:
            _, err = proc.communicate()
            raise AssertionError(f"spawn exited early (rc={proc.returncode}): {err}")
        merged = _read_merged(tmp_path, n)
        if merged == expected:
            break
        time.sleep(0.3)
    return merged


def _drip_feed(tmp_path, seconds: float, rows_per_file: int = 2) -> int:
    """Write a small ``drip`` csv every 0.2s for ``seconds``, returning the
    number of rows written. Checkpoint attempts ride the per-commit allgather,
    so an IDLE cluster stops checkpointing: the initial files drain in well
    under a second, and without a live commit stream an attempt-gated chaos
    fault (``at`` >= 2) would never fire — the run converges failure-free and
    the test flakes on ingest-speed jitter. The drip keeps commits (and the
    wall-clock attempt counter) ticking through the kill window."""
    rows = 0
    i = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        (tmp_path / "in" / f"drip{i:04d}.csv").write_text(
            "word\n" + "drip\n" * rows_per_file
        )
        rows += rows_per_file
        i += 1
        time.sleep(0.2)
    return rows


def _failure_free_counts(tmp_path) -> dict:
    """Reference output: the same pipeline run in-process with no faults."""
    G.clear()

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        str(tmp_path / "in"), format="csv", schema=WordSchema, mode="static"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    rows: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(key, None)

    pw.io.subscribe(counts, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    G.clear()
    return {r["word"]: r["total"] for r in rows.values()}


def _manifests(tmp_path) -> list:
    store = tmp_path / "store"
    if not store.exists():
        return []
    return sorted(
        int(f.name[len("cluster-manifest-"):-len(".json")])
        for f in store.iterdir()
        if f.name.startswith("cluster-manifest-") and f.name.endswith(".json")
    )


@pytest.mark.chaos
@pytest.mark.checkpoint
def test_coordinated_checkpoint_failover_n4_exact(tmp_path):
    """THE acceptance scenario: with coordinated checkpoints every 0.4s,
    SIGKILL rank 2 of ``spawn -n 4`` well after >=2 checkpoints have landed —
    the replacement recovers from the latest checkpoint + journal tail (never
    a full-history replay), survivors rewind in place, post-failover data is
    ingested exactly once, and the merged output is bit-identical to the
    failure-free run."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    for i in range(4):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    # attempt-gated (attempts tick at commit boundaries on the 0.4s cadence,
    # kept alive by the drip feed below): kill at the start of checkpoint
    # attempt 3, i.e. after exactly 3 checkpoints landed — a commit-id-gated
    # kill can lose the race against fast convergence on a loaded test host
    plan = {
        "checkpoint": [{"op": "pre_snapshot_kill", "rank": 2, "run": 0, "at": 3}]
    }
    proc, watchdog = _spawn_ckpt(tmp_path, first_port, n=4, plan=plan, max_restarts=1)
    err = ""
    try:
        # keep commits flowing so attempt 3 (the kill) is actually reached,
        # and keep dripping through the fence/rejoin so recovery is exercised
        # with data crossing the failure window
        dripped = _drip_feed(tmp_path, 8.0)
        (tmp_path / "in" / "late.csv").write_text(
            "word\n" + "\n".join(["owl"] * 3 + ["cat"] * 1) + "\n"
        )
        expected = {"cat": 11, "dog": 8, "owl": 3, "drip": dripped}
        merged = _await_counts(proc, tmp_path, 4, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc, watchdog)
    assert err.count("surgically relaunching rank 2") == 1, (
        f"expected exactly one surgical relaunch of rank 2:\n{err}"
    )
    assert "restarting the cluster" not in err, (
        f"survivors were torn down — restart-all fired instead of surgical:\n{err}"
    )
    assert "rejoined the cluster at epoch 1" in err, f"rejoin never completed:\n{err}"
    # the rejoin used a bounded-recovery rung, not a full-history replay
    assert ("via incremental rewind" in err) or ("via checkpoint+tail replay" in err), (
        f"recovery fell back to full journal replay despite checkpoints:\n{err}"
    )
    # >=1 durable manifest exists and the compacted journal stayed bounded
    assert _manifests(tmp_path), "no cluster checkpoint manifest was committed"
    # bit-identical to the failure-free run of the same pipeline
    assert _failure_free_counts(tmp_path) == merged


@pytest.mark.chaos
@pytest.mark.checkpoint
def test_kill_mid_checkpoint_protocol_recovers_from_previous(tmp_path):
    """Chaos satellite: SIGKILL rank 1 BETWEEN its snapshot write and the
    manifest commit (attempt 4 — after earlier checkpoints landed). The
    half-finished checkpoint must be invisible: recovery uses the previous
    manifest + journal tail and the output stays bit-identical."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    for i in range(2):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 2) + ["dog"] * 3) + "\n"
        )

    plan = {
        "checkpoint": [{"op": "post_snapshot_kill", "rank": 1, "run": 0, "at": 4}]
    }
    proc, watchdog = _spawn_ckpt(tmp_path, first_port, n=2, plan=plan, max_restarts=1)
    err = ""
    try:
        # the commit stream must stay alive for attempt 4 to be reached
        dripped = _drip_feed(tmp_path, 7.0)
        (tmp_path / "in" / "late.csv").write_text(
            "word\n" + "\n".join(["owl"] * 2 + ["dog"] * 1) + "\n"
        )
        expected = {"cat": 5, "dog": 7, "owl": 2, "drip": dripped}
        merged = _await_counts(proc, tmp_path, 2, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc, watchdog)
    assert "surgically relaunching rank 1" in err, f"no surgical relaunch:\n{err}"
    assert "rejoined the cluster at epoch 1" in err, f"rejoin never completed:\n{err}"
    assert _failure_free_counts(tmp_path) == merged


@pytest.mark.chaos
@pytest.mark.checkpoint
def test_torn_manifest_mid_run_previous_checkpoint_stands(tmp_path):
    """Chaos satellite: rank 0 tears the manifest bytes on checkpoint attempt
    2. The read-back verification turns the torn write into a clean "attempt
    failed" — no compaction happens for it, the run continues, later attempts
    succeed, and a SIGKILL after that still recovers bit-identically."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    for i in range(2):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    # both faults are attempt-gated (attempts tick at commit boundaries on the
    # 0.4s cadence, kept alive by the drip feed below): a commit-id-gated kill
    # can lose the race against fast convergence on a loaded test host
    plan = {
        "checkpoint": [
            {"op": "torn_manifest", "rank": 0, "run": 0, "at": 2},
            {"op": "post_snapshot_kill", "rank": 1, "run": 0, "at": 5},
        ],
    }
    proc, watchdog = _spawn_ckpt(tmp_path, first_port, n=2, plan=plan, max_restarts=1)
    err = ""
    try:
        # the commit stream must stay alive for attempts 2 (torn) and 5 (kill)
        dripped = _drip_feed(tmp_path, 8.0)
        (tmp_path / "in" / "late.csv").write_text("word\nowl\nowl\n")
        expected = {"cat": 3, "dog": 4, "owl": 2, "drip": dripped}
        merged = _await_counts(proc, tmp_path, 2, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc, watchdog)
    assert "rejoined the cluster at epoch 1" in err, f"rejoin never completed:\n{err}"
    # the torn write was caught by the read-back verification, loudly
    assert "torn/unreadable" in err, f"torn manifest was never detected:\n{err}"
    # torn manifest never became the recovery point: every surviving manifest
    # on disk parses clean and the newest one loads
    for commit in _manifests(tmp_path):
        raw = (tmp_path / "store" / f"cluster-manifest-{commit:010d}.json").read_bytes()
        json.loads(raw)
    assert _failure_free_counts(tmp_path) == merged
