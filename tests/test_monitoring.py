"""Monitoring: /status OpenMetrics endpoint + ProberStats counters."""

from __future__ import annotations

import urllib.request

import pathway_tpu as pw
from pathway_tpu.engine.http_server import MonitoringServer, ProberStats
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G


def test_status_endpoint_serves_openmetrics():
    stats = ProberStats()
    stats.record_commit(10, 4, {1: 10, 2: 4}, finished=False)
    server = MonitoringServer(stats, 0)  # ephemeral port
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/status", timeout=5
        ).read().decode()
    finally:
        server.close()
    assert "input_latency_ms" in body
    assert "output_latency_ms" in body
    assert "input_rows_total 10" in body
    assert "output_rows_total 4" in body
    assert body.rstrip().endswith("# EOF")


def test_prober_stats_fed_by_run():
    t = pw.debug.table_from_markdown(
        """
        a
        1
        2
        """
    )
    seen = []
    pw.io.subscribe(t, lambda key, row, time, is_addition: seen.append(row))
    runner = GraphRunner(G._current)
    runner.run()
    assert runner.prober_stats is not None
    assert runner.prober_stats.input_rows == 2
    assert runner.prober_stats.output_rows == 2
    assert len(seen) == 2
    metrics = runner.prober_stats.to_openmetrics()
    assert "input_latency_ms -1" in metrics  # finished


def test_rest_openapi_schema_endpoint():
    """Auto-generated OpenAPI v3 docs served at /_schema (reference
    EndpointDocumentation, io/http/_server.py:126)."""
    import json
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.io.http import EndpointDocumentation, PathwayWebserver, rest_connector
    from pathway_tpu.internals import parse_graph as pg

    pg.G.clear()
    port = 18951
    ws = PathwayWebserver(host="127.0.0.1", port=port)

    class QuerySchema(pw.Schema):
        query: str
        k: int = pw.column_definition(default_value=3, dtype=int)

    rest_connector(
        webserver=ws,
        route="/v1/ask",
        schema=QuerySchema,
        methods=("POST", "GET"),
        documentation=EndpointDocumentation(
            summary="Ask a question", tags=["rag"], method_types=("POST",)
        ),
    )
    doc = ws.openapi_description()
    assert doc["openapi"].startswith("3.")
    ask = doc["paths"]["/v1/ask"]
    assert "post" in ask and "get" not in ask  # method_types filter
    body = ask["post"]["requestBody"]["content"]["application/json"]["schema"]
    assert body["properties"]["query"] == {"type": "string"}
    assert body["properties"]["k"]["type"] == "integer"
    assert body["properties"]["k"]["default"] == 3
    assert body["required"] == ["query"]
    assert ask["post"]["summary"] == "Ask a question"

