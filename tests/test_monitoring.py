"""Monitoring: /status OpenMetrics endpoint + ProberStats counters."""

from __future__ import annotations

import urllib.request

import pathway_tpu as pw
from pathway_tpu.engine.http_server import MonitoringServer, ProberStats
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G


def test_status_endpoint_serves_openmetrics():
    stats = ProberStats()
    stats.record_commit(10, 4, {1: 10, 2: 4}, finished=False)
    server = MonitoringServer(stats, 0)  # ephemeral port
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/status", timeout=5
        ).read().decode()
    finally:
        server.close()
    assert "input_latency_ms" in body
    assert "output_latency_ms" in body
    assert "input_rows_total 10" in body
    assert "output_rows_total 4" in body
    assert body.rstrip().endswith("# EOF")


def test_healthz_endpoint_serves_liveness_json():
    """/healthz reports the shared liveness payload (per-peer heartbeat ages)
    the supervisor also reads — one signal for both consumers."""
    import json

    stats = ProberStats()
    server = MonitoringServer(stats, 0)
    server.health_source = lambda: {
        "rank": 0,
        "commit": 12,
        "persistence": True,
        "peers": {"1": 0.25},
    }
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=5
        ).read()
    finally:
        server.close()
    payload = json.loads(body)
    assert payload["alive"] is True
    assert payload["commit"] == 12
    assert payload["peers"] == {"1": 0.25}


def test_monitoring_port_released_across_back_to_back_runs(monkeypatch):
    """The listener socket must close on run teardown — including stepped runs
    (max_commits) — so back-to-back runs in one process rebind the same port."""
    import os

    port = 18900 + os.getpid() % 500  # pid-derived, as the cluster tests do
    monkeypatch.setenv("PATHWAY_MONITORING_HTTP_PORT", str(port))
    for _ in range(2):
        G.clear()
        t = pw.debug.table_from_markdown(
            """
            a
            1
            """
        )
        pw.io.subscribe(t, lambda *a, **kw: None)
        runner = GraphRunner(G._current)
        runner.run(max_commits=2, with_http_server=True)
        assert runner._http_server is None, "stepped run leaked the http server"
    # the port is genuinely free again
    server = MonitoringServer(ProberStats(), port)
    server.close()
    server.close()  # idempotent


def test_prober_stats_fed_by_run():
    t = pw.debug.table_from_markdown(
        """
        a
        1
        2
        """
    )
    seen = []
    pw.io.subscribe(t, lambda key, row, time, is_addition: seen.append(row))
    runner = GraphRunner(G._current)
    runner.run()
    assert runner.prober_stats is not None
    assert runner.prober_stats.input_rows == 2
    assert runner.prober_stats.output_rows == 2
    assert len(seen) == 2
    metrics = runner.prober_stats.to_openmetrics()
    assert "input_latency_ms -1" in metrics  # finished


def test_rest_openapi_schema_endpoint():
    """Auto-generated OpenAPI v3 docs served at /_schema (reference
    EndpointDocumentation, io/http/_server.py:126)."""
    import json
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.io.http import EndpointDocumentation, PathwayWebserver, rest_connector
    from pathway_tpu.internals import parse_graph as pg

    pg.G.clear()
    port = 18951
    ws = PathwayWebserver(host="127.0.0.1", port=port)

    class QuerySchema(pw.Schema):
        query: str
        k: int = pw.column_definition(default_value=3, dtype=int)

    rest_connector(
        webserver=ws,
        route="/v1/ask",
        schema=QuerySchema,
        methods=("POST", "GET"),
        documentation=EndpointDocumentation(
            summary="Ask a question", tags=["rag"], method_types=("POST",)
        ),
    )
    doc = ws.openapi_description()
    assert doc["openapi"].startswith("3.")
    ask = doc["paths"]["/v1/ask"]
    assert "post" in ask and "get" not in ask  # method_types filter
    body = ask["post"]["requestBody"]["content"]["application/json"]["schema"]
    assert body["properties"]["query"] == {"type": "string"}
    assert body["properties"]["k"]["type"] == "integer"
    assert body["properties"]["k"]["default"] == 3
    assert body["required"] == ["query"]
    assert ask["post"]["summary"] == "Ask a question"

    # the SERVED GET /_schema route must return the same document (the aiohttp
    # handler path: route registration + JSON serialization of defaults)
    import threading

    t = pw.debug.table_from_rows(pw.schema_builder({"x": int}), [(1,)])
    pw.io.subscribe(t, lambda *a, **kw: None)
    run_thread = threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE),
        daemon=True,
    )
    run_thread.start()
    import time as time_mod

    served = None
    deadline = time_mod.monotonic() + 20
    while time_mod.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/_schema", timeout=2
            ) as resp:
                served = json.loads(resp.read())
                break
        except Exception:
            time_mod.sleep(0.2)
    assert served is not None, "GET /_schema never became reachable"
    assert served["paths"]["/v1/ask"]["post"]["summary"] == "Ask a question"
    schema_k = served["paths"]["/v1/ask"]["post"]["requestBody"]["content"][
        "application/json"
    ]["schema"]["properties"]["k"]
    assert schema_k["default"] == 3  # default_value survived JSON serialization



def test_otel_metrics_recorder_instruments(monkeypatch):
    """With PATHWAY_TELEMETRY on, the recorder creates OTel instruments and
    records per-commit measurements (reference telemetry.rs:37-45); a fake meter
    provider captures what the SDK would export."""
    import pathway_tpu as pw
    from pathway_tpu.engine.http_server import ProberStats
    from pathway_tpu.engine.telemetry import MetricsRecorder

    recorded = {"counters": {}, "hist": []}

    class FakeInstrument:
        def __init__(self, name):
            self.name = name

        def add(self, value, attributes=None):
            recorded["counters"][self.name] = (
                recorded["counters"].get(self.name, 0) + value
            )

        def record(self, value, attributes=None):
            recorded["hist"].append((self.name, value))

    class FakeMeter:
        def __init__(self):
            self.gauges = []

        def create_observable_gauge(self, name, callbacks=None, **kw):
            self.gauges.append((name, callbacks))

        def create_counter(self, name, **kw):
            return FakeInstrument(name)

        def create_histogram(self, name, **kw):
            return FakeInstrument(name)

    fake_meter = FakeMeter()
    from opentelemetry import metrics as otel_metrics

    monkeypatch.setenv("PATHWAY_TELEMETRY", "1")
    monkeypatch.setattr(otel_metrics, "get_meter", lambda name: fake_meter)

    MetricsRecorder._instance = None  # fresh singleton for the fake meter
    stats = ProberStats()
    rec = MetricsRecorder.get(stats)
    assert rec._enabled
    # repeated runs REUSE the instruments (no duplicate gauges), only the
    # stats source swaps
    rec2 = MetricsRecorder.get(ProberStats())
    assert rec2 is rec
    assert len(fake_meter.gauges) == 4
    gauge_names = [g[0] for g in fake_meter.gauges]
    assert "process.memory.usage" in gauge_names
    assert "pathway.input.latency" in gauge_names
    rec.record_commit(10, 4, 0.05)
    rec.record_commit(0, 1, 0.01)
    assert recorded["counters"]["pathway.commits"] == 2
    assert recorded["counters"]["pathway.input.rows"] == 10
    assert recorded["counters"]["pathway.output.rows"] == 5
    assert len(recorded["hist"]) == 2
    # observable gauge callbacks are live (psutil-backed)
    mem_cb = dict(fake_meter.gauges)["process.memory.usage"][0]
    (obs,) = mem_cb(None)
    assert obs.value > 0
    MetricsRecorder._instance = None  # don't leak the fake-metered singleton


def test_rest_roundtrip_latency_floor():
    """Serving-path regression guard: a sequential REST echo round-trip must not
    pay a fat autocommit tick (the rest connector runs a 1 ms serving tick, so
    per-request overhead is wake + commit + <=1 ms)."""
    import json
    import threading
    import time as time_mod
    import urllib.request

    import numpy as np
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    pg.G.clear()
    port = 18723
    ws = PathwayWebserver(host="127.0.0.1", port=port)

    class Q(pw.Schema):
        text: str

    queries, writer = rest_connector(
        webserver=ws, route="/echo", schema=Q, delete_completed_queries=True
    )
    writer(queries.select(result=pw.this.text))
    threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE), daemon=True
    ).start()

    def post(payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/echo",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    deadline = time_mod.monotonic() + 20
    while time_mod.monotonic() < deadline:
        try:
            post({"text": "warm"})
            break
        except Exception:
            time_mod.sleep(0.2)
    lat = []
    for i in range(30):
        t0 = time_mod.perf_counter()
        out = post({"text": f"q{i}"})
        lat.append(time_mod.perf_counter() - t0)
        # single-column results serve as the raw value (reference response shape)
        got = out["result"] if isinstance(out, dict) else out
        assert got == f"q{i}"
    p50 = float(np.median(lat)) * 1000
    import os as os_mod

    if os_mod.environ.get("PATHWAY_STRICT_LATENCY_TEST"):
        # the regression this guards (serving tick raised back to 5 ms+, echo p50
        # ~7.5 ms) must stay detectable; healthy p50 is ~1.5 ms on an idle box, so
        # 5 ms keeps 3x machine-noise headroom below the regression point.
        # Strict bound is opt-in: CI containers measure ~6.7 ms on a CLEAN tree
        # (scheduler noise), so by default only the generous sanity ceiling runs.
        assert p50 < 5.0, f"REST echo p50 {p50:.1f} ms regressed past the tick bound"
    # sanity ceiling: catches a fundamentally broken serving tick (100 ms+
    # autocommit), not the 5 ms-tick regression (strict bound above). 58 ms p50
    # was measured on a CLEAN tree under full-suite CPU contention (leaked
    # daemon pw.run threads from earlier tests keep stepping commits), so the
    # ceiling must clear that noise floor.
    assert p50 < 150.0, (
        f"REST echo p50 {p50:.1f} ms blew the sanity ceiling — the serving tick "
        "is fundamentally broken, not merely noisy"
    )


def test_healthz_degraded_on_probe_failure():
    """A failing health-source callback must NOT masquerade as a healthy
    worker: HTTP stays 200 (a probe must never 500), alive stays true (the
    process does serve), but state reports "degraded" with the error."""
    import json

    stats = ProberStats()
    server = MonitoringServer(stats, 0)

    def exploding_source():
        raise RuntimeError("status file unreadable")

    server.health_source = exploding_source
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=5
        ) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read())
    finally:
        server.close()
    assert payload["alive"] is True
    assert payload["state"] == "degraded"
    assert "status file unreadable" in payload["error"]


def test_stats_monitor_plain_lines_without_tty(monkeypatch):
    """Redirected/CI stderr (isatty False) must still get throttled plain
    progress lines — the module contract the tty-gated fallback violated."""
    import io
    import sys

    from pathway_tpu.internals.monitoring import MonitoringLevel, StatsMonitor

    class FakeErr(io.StringIO):
        def isatty(self):
            return False

    fake = FakeErr()
    monkeypatch.setattr(sys, "stderr", fake)

    class Node:
        def __init__(self, nid, kind):
            self.id = nid
            self.kind = kind
            self.name = kind

    monitor = StatsMonitor([Node(1, "input"), Node(2, "output")],
                           level=MonitoringLevel.IN_OUT)
    assert monitor._live is None
    monitor._last_print = -10.0  # bypass the 1 s throttle
    monitor.update(5, {1: 10, 2: 7})
    out = fake.getvalue()
    assert "commit=5" in out
    assert "rows_processed=17" in out
    assert "rows_per_s=" in out
    # throttle: an immediate second update prints nothing new
    before = fake.getvalue()
    monitor.update(6, {1: 1})
    assert fake.getvalue() == before
    monitor.close()


def test_cpu_gauge_primed_at_registration(monkeypatch):
    """psutil.cpu_percent(interval=None) reports 0.0 on its FIRST call (no
    baseline) — the recorder must prime it at instrument registration so the
    first export interval carries a real number."""
    import psutil

    from pathway_tpu.engine.telemetry import MetricsRecorder

    calls = []

    class FakeProcess:
        def cpu_percent(self, interval=None):
            calls.append(interval)
            return 0.0 if len(calls) == 1 else 12.5

        def memory_info(self):
            class M:
                rss = 1024
            return M()

    class FakeInstrument:
        def add(self, *a, **k):
            pass

        def record(self, *a, **k):
            pass

    class FakeMeter:
        def __init__(self):
            self.gauges = {}

        def create_observable_gauge(self, name, callbacks=None, **kw):
            self.gauges[name] = callbacks

        def create_counter(self, name, **kw):
            return FakeInstrument()

        def create_histogram(self, name, **kw):
            return FakeInstrument()

    fake_meter = FakeMeter()
    from opentelemetry import metrics as otel_metrics

    monkeypatch.setenv("PATHWAY_TELEMETRY", "1")
    monkeypatch.setattr(otel_metrics, "get_meter", lambda name: fake_meter)
    monkeypatch.setattr(psutil, "Process", FakeProcess)

    MetricsRecorder._instance = None
    rec = MetricsRecorder.get(ProberStats())
    try:
        assert rec._enabled
        assert calls == [None], "cpu clock must be primed once at registration"
        (obs,) = fake_meter.gauges["process.cpu.utilization"][0](None)
        assert obs.value == 12.5, "first exported sample must not be the 0.0 priming read"
    finally:
        MetricsRecorder._instance = None


def test_rest_max_pending_sheds_with_429_and_retry_after():
    """Backpressure slice (ISSUE 6): past ``max_pending`` admitted-but-
    unanswered requests, the route sheds with HTTP 429 + a Retry-After header
    BEFORE pushing into the engine, and counts the shed on the configured
    stage counter."""
    import json
    import socket
    import threading
    import time as time_mod
    import urllib.error
    import urllib.request

    import pytest

    import pathway_tpu as pw
    from pathway_tpu.engine import telemetry
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    pg.G.clear()
    port = 18761
    ws = PathwayWebserver(host="127.0.0.1", port=port)

    class Q(pw.Schema):
        text: str

    queries, writer = rest_connector(
        webserver=ws, route="/hang", schema=Q, max_pending=1,
        retry_after=lambda: 7.0,
    )
    # responses never arrive: every admitted request stays pending forever
    writer(queries.filter(pw.this.text == "no row ever matches this"))
    threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE), daemon=True
    ).start()

    deadline = time_mod.monotonic() + 20
    while True:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            break
        except OSError:
            assert time_mod.monotonic() < deadline, "REST server never came up"
            time_mod.sleep(0.2)

    def post(payload, timeout):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/hang",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read()

    # request 1 occupies the single admission slot and hangs (daemon thread)
    threading.Thread(
        target=lambda: post({"text": "first"}, 60), daemon=True
    ).start()
    time_mod.sleep(1.0)  # let request 1 be admitted

    shed_before = telemetry.stage_snapshot("rest.").get("rest.shed", 0.0)
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        post({"text": "second"}, 10)
    assert exc_info.value.code == 429
    assert exc_info.value.headers["Retry-After"] == "7"
    assert "overloaded" in exc_info.value.read().decode()
    assert telemetry.stage_snapshot("rest.").get("rest.shed", 0.0) > shed_before
