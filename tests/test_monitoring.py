"""Monitoring: /status OpenMetrics endpoint + ProberStats counters."""

from __future__ import annotations

import urllib.request

import pathway_tpu as pw
from pathway_tpu.engine.http_server import MonitoringServer, ProberStats
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G


def test_status_endpoint_serves_openmetrics():
    stats = ProberStats()
    stats.record_commit(10, 4, {1: 10, 2: 4}, finished=False)
    server = MonitoringServer(stats, 0)  # ephemeral port
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/status", timeout=5
        ).read().decode()
    finally:
        server.close()
    assert "input_latency_ms" in body
    assert "output_latency_ms" in body
    assert "input_rows_total 10" in body
    assert "output_rows_total 4" in body
    assert body.rstrip().endswith("# EOF")


def test_prober_stats_fed_by_run():
    t = pw.debug.table_from_markdown(
        """
        a
        1
        2
        """
    )
    seen = []
    pw.io.subscribe(t, lambda key, row, time, is_addition: seen.append(row))
    runner = GraphRunner(G._current)
    runner.run()
    assert runner.prober_stats is not None
    assert runner.prober_stats.input_rows == 2
    assert runner.prober_stats.output_rows == 2
    assert len(seen) == 2
    metrics = runner.prober_stats.to_openmetrics()
    assert "input_latency_ms -1" in metrics  # finished
