"""Tests for stdlib additions: graph algorithms, whole-column applies,
pandas_transformer, inactivity detection.

Mirrors the reference's test style for these modules (`python/pathway/tests/`):
small static/streamed tables, assert on final captured state.
"""

from __future__ import annotations

import datetime

import pandas as pd
import pytest

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from pathway_tpu.stdlib.graphs import (
    WeightedGraph,
    exact_modularity,
    louvain_communities,
    louvain_level,
    pagerank,
)
from pathway_tpu.stdlib.utils.col import (
    apply_all_rows,
    groupby_reduce_majority,
    multiapply_all_rows,
)
from tests.utils import T


def _two_triangles():
    """Two 10-weight triangles {0,1,2} and {3,4,5} bridged by one weight-1 edge."""
    md_edges = []

    def und(a, b, w):
        md_edges.append((a, b, float(w)))
        md_edges.append((b, a, float(w)))

    for a, b in [(0, 1), (1, 2), (0, 2)]:
        und(a, b, 10)
    for a, b in [(3, 4), (4, 5), (3, 5)]:
        und(a, b, 10)
    und(2, 3, 1)

    vs = pw.schema_from_types(v=int)
    es = pw.schema_from_types(u_raw=int, v_raw=int, weight=float)
    vraw = dbg.table_from_rows(vs, [(i,) for i in range(6)])
    eraw = dbg.table_from_rows(es, md_edges)
    keyed = vraw.with_id_from(vraw.v)
    V = keyed.select(v=keyed.v)
    E = eraw.select(
        u=V.pointer_from(eraw.u_raw), v=V.pointer_from(eraw.v_raw), weight=eraw.weight
    )
    return V, E


def test_louvain_two_triangles():
    V, E = _two_triangles()
    graph = WeightedGraph.from_vertices_and_weighted_edges(V, E)
    flat = louvain_communities(graph, levels=1, iterations_per_level=6)
    res = flat.select(v=V.v, c=flat.c)
    df = dbg.table_to_pandas(res, include_id=False)
    groups = sorted(df.groupby("c")["v"].apply(lambda s: tuple(sorted(s))).tolist())
    assert groups == [(0, 1, 2), (3, 4, 5)]


def test_louvain_modularity_positive():
    V, E = _two_triangles()
    graph = WeightedGraph.from_vertices_and_weighted_edges(V, E)
    flat = louvain_level(graph, 6)
    mod_rows = dbg.table_to_pandas(exact_modularity(graph, flat), include_id=False)
    # perfect split of the two triangles: modularity ≈ 0.48
    assert mod_rows["modularity"].iloc[0] > 0.4


def test_pagerank_star():
    # edges all point into vertex 0 → vertex 0 accumulates rank
    es = pw.schema_from_types(u_raw=int, v_raw=int)
    eraw = dbg.table_from_rows(es, [(i, 0) for i in range(1, 5)])
    edges = eraw.select(
        u=eraw.pointer_from(eraw.u_raw), v=eraw.pointer_from(eraw.v_raw)
    )
    ranks = pagerank(edges, steps=3)
    df = dbg.table_to_pandas(ranks, include_id=True)
    assert df["rank"].max() > 1000  # the hub exceeds the initial uniform rank
    assert len(df) == 5


def test_apply_all_rows():
    t = T(
        """
      | colA | colB
    1 | 1    | 10
    2 | 2    | 20
    3 | 3    | 30
    """
    )

    def add_total_sum(c1, c2):
        s = sum(c1) + sum(c2)
        return [x + s for x in c1]

    r = apply_all_rows(t.colA, t.colB, fun=add_total_sum, result_col_name="res")
    vals = sorted(row["res"] for row in dbg.table_to_pandas(r).to_dict("records"))
    assert vals == [67, 68, 69]


def test_multiapply_all_rows():
    t = T(
        """
      | colA | colB
    1 | 1    | 10
    2 | 2    | 20
    """
    )

    def both(c1, c2):
        s = sum(c1) + sum(c2)
        return [x + s for x in c1], [x + s for x in c2]

    r = multiapply_all_rows(t.colA, t.colB, fun=both, result_col_names=["r1", "r2"])
    rows = sorted(
        (row["r1"], row["r2"]) for row in dbg.table_to_pandas(r).to_dict("records")
    )
    assert rows == [(34, 43), (35, 53)]


def test_groupby_reduce_majority():
    t = T(
        """
      | group | vote
    0 | 1     | pizza
    1 | 1     | pizza
    2 | 1     | hotdog
    3 | 2     | pasta
    4 | 2     | pasta
    5 | 2     | hotdog
    """
    )
    r = groupby_reduce_majority(t.group, t.vote)
    rows = {
        row["group"]: row["majority"] for row in dbg.table_to_pandas(r).to_dict("records")
    }
    assert rows == {1: "pizza", 2: "pasta"}


def test_pandas_transformer():
    inp = T(
        """
        | foo  | bar
    0   | 10   | 100
    1   | 20   | 200
    2   | 30   | 300
    """
    )

    class Output(pw.Schema):
        sum: int

    @pw.pandas_transformer(output_schema=Output)
    def sum_cols(t: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame(t.sum(axis=1))

    out = sum_cols(inp)
    vals = sorted(row["sum"] for row in dbg.table_to_pandas(out).to_dict("records"))
    assert vals == [110, 220, 330]


def test_pandas_transformer_output_universe():
    inp = T(
        """
        | foo
    0   | 1
    1   | 2
    """
    )

    class Output(pw.Schema):
        double: int

    @pw.pandas_transformer(output_schema=Output, output_universe=0)
    def double(t: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame(t["foo"] * 2)

    out = double(inp)
    combined = inp.with_columns(double=out.double)
    rows = sorted(
        (row["foo"], row["double"])
        for row in dbg.table_to_pandas(combined).to_dict("records")
    )
    assert rows == [(1, 2), (2, 4)]


def test_inactivity_detection_with_injected_clock():
    DT = datetime.datetime

    def ts(s):
        return DT(2026, 1, 1, 0, 0, s)

    ev_schema = pw.schema_from_types(t=DT)
    now_schema = pw.schema_from_types(timestamp_utc=DT)
    events = dbg.table_from_rows(
        ev_schema,
        [(ts(0), 1, 1), (ts(1), 2, 1), (ts(2), 3, 1), (ts(20), 40, 1), (ts(21), 41, 1)],
        is_stream=True,
    )
    now = dbg.table_from_rows(
        now_schema,
        [(ts(3), 4, 1), (ts(8), 10, 1), (ts(13), 20, 1), (ts(22), 45, 1)],
        is_stream=True,
    )
    from pathway_tpu.stdlib.temporal.time_utils import inactivity_detection

    inact, resumed = inactivity_detection(
        events.t, datetime.timedelta(seconds=5), now_table=now
    )
    inact_rows = [r["inactive_t"] for r in dbg.table_to_pandas(inact).to_dict("records")]
    resumed_rows = [r["resumed_t"] for r in dbg.table_to_pandas(resumed).to_dict("records")]
    assert inact_rows == [ts(2)]
    assert resumed_rows == [ts(20)]


def test_timed_sources_share_global_clock():
    """Two streamed tables must interleave by __time__, not by batch index."""
    s1 = pw.schema_from_types(a=int)
    s2 = pw.schema_from_types(b=int)
    t1 = dbg.table_from_rows(s1, [(1, 2, 1), (2, 6, 1)], is_stream=True)
    t2 = dbg.table_from_rows(s2, [(10, 4, 1)], is_stream=True)
    # t2's row (time 4) must arrive after t1's first (2) and before t1's second (6):
    # join as-of-now of t2 against current max(a) sees a=1 only
    from pathway_tpu.internals.reducers import reducers

    latest = t1.groupby().reduce(m=reducers.max(t1.a))
    joined = t2.asof_now_join(latest).select(b=t2.b, m=latest.m)
    rows = dbg.table_to_pandas(joined).to_dict("records")
    assert rows == [{"b": 10, "m": 1}]


def test_louvain_isolated_vertex():
    vs = pw.schema_from_types(v=int)
    es = pw.schema_from_types(u_raw=int, v_raw=int, weight=float)
    vraw = dbg.table_from_rows(vs, [(i,) for i in range(3)])
    eraw = dbg.table_from_rows(es, [(0, 1, 5.0), (1, 0, 5.0)])
    keyed = vraw.with_id_from(vraw.v)
    V = keyed.select(v=keyed.v)
    E = eraw.select(
        u=V.pointer_from(eraw.u_raw), v=V.pointer_from(eraw.v_raw), weight=eraw.weight
    )
    flat = louvain_communities(
        WeightedGraph.from_vertices_and_weighted_edges(V, E), levels=1, iterations_per_level=4
    )
    res = flat.select(v=V.v, c=flat.c)
    df = dbg.table_to_pandas(res, include_id=False)
    groups = sorted(df.groupby("c")["v"].apply(lambda s: tuple(sorted(s))).tolist())
    assert groups == [(0, 1), (2,)]


def test_unpack_col_dict_typed_fields():
    import pathway_tpu as pw
    from pathway_tpu.internals.json import Json
    from pathway_tpu.stdlib.utils.col import unpack_col_dict

    t = pw.debug.table_from_rows(
        pw.schema_builder({"data": Json}),
        [
            (Json({"field_a": 13, "field_b": "foo", "field_c": False}),),
            (Json({"field_a": 17, "field_c": True, "field_d": 3.4}),),
        ],
    )

    class DataSchema(pw.Schema):
        field_a: int
        field_b: str | None
        field_c: bool
        field_d: float | None

    out = unpack_col_dict(t.data, schema=DataSchema)
    df = pw.debug.table_to_pandas(out)
    rows = sorted(
        zip(df["field_a"], df["field_b"], df["field_c"], df["field_d"]),
        key=lambda r: r[0],
    )
    assert rows[0][0] == 13 and rows[0][1] == "foo" and rows[0][2] == False  # noqa: E712
    missing_b = rows[1][1]
    assert rows[1][0] == 17 and (missing_b is None or missing_b != missing_b)
    assert abs(rows[1][3] - 3.4) < 1e-9


def test_flatten_column_and_bucketing():
    import datetime
    import warnings

    import pathway_tpu as pw
    from pathway_tpu.stdlib.utils.bucketing import truncate_to_minutes
    from pathway_tpu.stdlib.utils.col import flatten_column

    t = pw.debug.table_from_rows(pw.schema_builder({"pet": str}), [("Dog",), ("Cat",)])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        flat = flatten_column(t.pet)
    df = pw.debug.table_to_pandas(flat)
    assert sorted(df["pet"]) == sorted("DogCat")
    assert "origin_id" in df.columns

    ts = datetime.datetime(2026, 7, 30, 12, 34, 56, 789000)
    assert truncate_to_minutes(ts) == datetime.datetime(2026, 7, 30, 12, 34)


def test_interpolate_across_none_runs():
    """Consecutive missing cells must interpolate against the NEAREST known
    neighbors (reference iterate-closed chains), not just adjacent rows."""
    import pathway_tpu as pw

    t = pw.debug.table_from_markdown(
        """
        t | v
        1 | 1.0
        2 |
        3 |
        4 | 7.0
        5 |
        """
    )
    res = pw.statistical.interpolate(t, t.t, t.v)
    df = pw.debug.table_to_pandas(res).sort_values("t")
    assert df["v"].tolist() == [1.0, 3.0, 5.0, 7.0, 7.0]


def test_iterate_fixpoint_converges_with_nan_columns():
    """Engine regression: NaN in an iterated float column must not defeat the
    fixpoint check (value semantics: NaN == NaN for convergence)."""
    import pathway_tpu as pw

    t = pw.debug.table_from_rows(
        pw.schema_builder({"x": float}), [(float("nan"),), (2.0,)]
    )

    def step(state):
        return dict(state=state.select(x=state.x))  # identity: 1 iteration

    out = pw.iterate(step, state=t).state
    df = pw.debug.table_to_pandas(out)
    vals = sorted(df["x"].tolist(), key=repr)
    assert len(vals) == 2 and 2.0 in vals
