"""Model-check suite (pathway_tpu/internals/protocol_models.py): the cluster
protocols under ≥200 distinct interleavings each, invariants holding on every
schedule; the planted-bug variants proving the harness DETECTS each bug class
with a replayable schedule; and the PWA101 ↔ model-check bridge — the same
lock-order inversion caught statically and dynamically.

Budgeted for tier-1: the whole module runs in well under the 60 s modelcheck
budget (each explore() of a few hundred schedules is ~1-3 s)."""

from __future__ import annotations

import time
from typing import Dict

import pytest

from pathway_tpu.analysis import analyze_source
from pathway_tpu.internals import protocol_models as pm
from pathway_tpu.internals.sched import (
    DeadlockError,
    InvariantViolation,
    explore,
    run_once,
    sweep_seeds,
)

pytestmark = pytest.mark.modelcheck

# acceptance: >= 200 distinct interleavings per protocol
N_SCHEDULES = 200

# wall seconds of the acceptance batteries, recorded by the tests themselves
# and asserted by test_model_check_battery_within_budget (runs last in file
# order) — the documented <60 s tier-1 budget is enforced, not aspirational
_BATTERY_SECONDS: Dict[str, float] = {}


# ---------------------------------------------------------------------------
# fence / rejoin
# ---------------------------------------------------------------------------


def test_fence_rejoin_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.fence_rejoin_model(2), max_schedules=N_SCHEDULES, name="fence"
    )
    _BATTERY_SECONDS["fence"] = time.monotonic() - t0
    assert result.ok, (
        f"fence/rejoin invariant failed on schedule {result.failing_schedule}: "
        f"{result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


def test_fence_rejoin_invariants_hold_seeded():
    result = sweep_seeds(
        pm.fence_rejoin_model(2), n_seeds=100, base_seed=1, name="fence-seeded"
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"
    assert result.distinct_schedules == 100


def test_fence_rejoin_three_survivors():
    result = explore(pm.fence_rejoin_model(3), max_schedules=100, name="fence3")
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


def test_fence_rejoin_no_purge_bug_caught_and_replayable():
    result = explore(
        pm.fence_rejoin_model(2, bug="no_purge"),
        max_schedules=400,
        name="fence-no-purge",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the install-purge regression went undetected"
    )
    assert "stale-epoch delivery" in str(result.failure)
    # the failing schedule replays the exact interleaving
    with pytest.raises(InvariantViolation, match="stale-epoch delivery"):
        run_once(
            pm.fence_rejoin_model(2, bug="no_purge"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# coordinated checkpoint
# ---------------------------------------------------------------------------


def test_checkpoint_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.checkpoint_model(3), max_schedules=N_SCHEDULES, name="ckpt"
    )
    _BATTERY_SECONDS["ckpt"] = time.monotonic() - t0
    assert result.ok, (
        f"checkpoint invariant failed on schedule {result.failing_schedule}: "
        f"{result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


def test_checkpoint_invariants_hold_seeded():
    result = sweep_seeds(
        pm.checkpoint_model(3), n_seeds=100, base_seed=5, name="ckpt-seeded"
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"


def test_checkpoint_crash_leaves_previous_manifest_intact():
    # post-snapshot kill of rank 1: the ack barrier must abort on its
    # deadline and nobody may commit or compact
    result = explore(
        pm.checkpoint_model(3, crash_rank=1), max_schedules=N_SCHEDULES,
        name="ckpt-crash",
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


def test_checkpoint_toctou_double_commit_caught_with_seed():
    result = sweep_seeds(
        pm.checkpoint_model(3, bug="toctou_commit"),
        n_seeds=300,
        base_seed=10,
        name="ckpt-toctou",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the manifest TOCTOU regression went undetected"
    )
    assert "double manifest commit" in str(result.failure)
    assert result.failing_seed is not None
    # the SEED alone reproduces the double commit (deterministic walk)
    with pytest.raises(InvariantViolation, match="double manifest commit"):
        run_once(
            pm.checkpoint_model(3, bug="toctou_commit"), seed=result.failing_seed
        )


# ---------------------------------------------------------------------------
# coalescer admission / shed
# ---------------------------------------------------------------------------


def test_coalescer_invariants_hold_exhaustive():
    result = explore(
        pm.coalescer_model(3, cap=2), max_schedules=N_SCHEDULES, name="coal"
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"
    assert result.distinct_schedules >= N_SCHEDULES


def test_coalescer_error_path_releases_slots():
    result = explore(
        pm.coalescer_model(3, cap=2, fail_batch=True),
        max_schedules=N_SCHEDULES,
        name="coal-err",
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


def test_coalescer_slot_leak_bug_caught_and_replayable():
    result = explore(
        pm.coalescer_model(3, cap=2, fail_batch=True, bug="leak_slot"),
        max_schedules=300,
        name="coal-leak",
    )
    assert isinstance(result.failure, InvariantViolation)
    assert "admission slots leaked" in str(result.failure)
    with pytest.raises(InvariantViolation, match="admission slots leaked"):
        run_once(
            pm.coalescer_model(3, cap=2, fail_batch=True, bug="leak_slot"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# encoder service admission / tick / shutdown
# ---------------------------------------------------------------------------


@pytest.mark.encsvc
def test_encoder_service_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.encoder_service_model(3, cap=2, max_inflight=2),
        max_schedules=N_SCHEDULES,
        name="encsvc",
    )
    _BATTERY_SECONDS["encsvc"] = time.monotonic() - t0
    assert result.ok, (
        f"encoder-service invariant failed on schedule "
        f"{result.failing_schedule}: {result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.encsvc
def test_encoder_service_invariants_hold_seeded():
    result = sweep_seeds(
        pm.encoder_service_model(3, cap=2, max_inflight=2),
        n_seeds=100,
        base_seed=21,
        name="encsvc-seeded",
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"
    assert result.distinct_schedules == 100


@pytest.mark.encsvc
def test_encoder_service_error_path_releases_slots():
    result = explore(
        pm.encoder_service_model(3, cap=3, max_inflight=2, fail_batch=True),
        max_schedules=N_SCHEDULES,
        name="encsvc-err",
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


@pytest.mark.encsvc
def test_encoder_service_inflight_leak_bug_caught_and_replayable():
    result = explore(
        pm.encoder_service_model(3, cap=3, max_inflight=2, fail_batch=True,
                                 bug="leak_inflight"),
        max_schedules=400,
        name="encsvc-leak",
    )
    assert isinstance(result.failure, InvariantViolation)
    assert "in-flight slots leaked" in str(result.failure)
    with pytest.raises(InvariantViolation, match="in-flight slots leaked"):
        run_once(
            pm.encoder_service_model(3, cap=3, max_inflight=2, fail_batch=True,
                                     bug="leak_inflight"),
            choices=result.failing_schedule,
        )


@pytest.mark.encsvc
def test_encoder_service_drop_on_close_bug_caught_and_replayable():
    # shutdown racing admitted requests: the no-drain worker strands them
    result = sweep_seeds(
        pm.encoder_service_model(3, cap=3, max_inflight=1, bug="drop_on_close"),
        n_seeds=300,
        base_seed=31,
        name="encsvc-drop",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the shutdown-drop regression went undetected"
    )
    assert "dropped at shutdown" in str(result.failure)
    with pytest.raises(InvariantViolation, match="dropped at shutdown"):
        run_once(
            pm.encoder_service_model(3, cap=3, max_inflight=1, bug="drop_on_close"),
            seed=result.failing_seed,
        )


@pytest.mark.encsvc
def test_encoder_service_lost_close_wakeup_deadlocks():
    # a notify-less stop against the notify-driven idle wait = the lost-wakeup
    # class (the real service's timed tick is the defense); proven a deadlock
    result = explore(
        pm.encoder_service_model(2, cap=2, max_inflight=2,
                                 bug="lost_close_wakeup"),
        max_schedules=400,
        name="encsvc-lostwake",
    )
    assert isinstance(result.failure, DeadlockError), result.failure
    with pytest.raises(DeadlockError):
        run_once(
            pm.encoder_service_model(2, cap=2, max_inflight=2,
                                     bug="lost_close_wakeup"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# elastic membership change (quiesce -> handoff -> manifest -> install)
# ---------------------------------------------------------------------------


@pytest.mark.elastic
def test_membership_grow_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.membership_model(2, 3), max_schedules=N_SCHEDULES, name="member-grow"
    )
    _BATTERY_SECONDS["membership"] = time.monotonic() - t0
    assert result.ok, (
        f"membership invariant failed on schedule {result.failing_schedule}: "
        f"{result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.elastic
def test_membership_shrink_invariants_hold_exhaustive():
    result = explore(
        pm.membership_model(3, 2), max_schedules=N_SCHEDULES, name="member-shrink"
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.elastic
def test_membership_invariants_hold_seeded():
    result = sweep_seeds(
        pm.membership_model(2, 3), n_seeds=100, base_seed=41, name="member-seeded"
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"
    assert result.distinct_schedules == 100


@pytest.mark.elastic
def test_membership_double_owner_bug_caught_and_replayable():
    # a donor that keeps serving handed-off slots: two owners at one epoch
    result = explore(
        pm.membership_model(2, 3, bug="double_owner"),
        max_schedules=300,
        name="member-double-owner",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the double-owner window went undetected"
    )
    assert (
        "owned by" in str(result.failure) or "duplicated" in str(result.failure)
    )
    with pytest.raises(InvariantViolation):
        run_once(
            pm.membership_model(2, 3, bug="double_owner"),
            choices=result.failing_schedule,
        )


@pytest.mark.elastic
def test_membership_orphan_range_bug_caught_and_replayable():
    # one moved key range's fragment never lands: no owner has its rows
    result = explore(
        pm.membership_model(2, 3, bug="orphan_range"),
        max_schedules=300,
        name="member-orphan",
    )
    assert isinstance(result.failure, InvariantViolation)
    assert "rows lost" in str(result.failure)
    with pytest.raises(InvariantViolation, match="rows lost"):
        run_once(
            pm.membership_model(2, 3, bug="orphan_range"),
            choices=result.failing_schedule,
        )


@pytest.mark.elastic
def test_membership_release_before_drain_bug_caught_with_seed():
    # a leaver tearing down before its handoff is durable loses its rows
    result = sweep_seeds(
        pm.membership_model(3, 2, bug="release_before_drain"),
        n_seeds=200,
        base_seed=51,
        name="member-early-release",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the leaver-released-before-drain regression went undetected"
    )
    assert "rows lost" in str(result.failure)
    assert result.failing_seed is not None
    with pytest.raises(InvariantViolation, match="rows lost"):
        run_once(
            pm.membership_model(3, 2, bug="release_before_drain"),
            seed=result.failing_seed,
        )


@pytest.mark.elastic
def test_membership_epoch_before_install_bug_caught_and_replayable():
    # the epoch bumps (and traffic resumes) before the ownership map
    # installs: rows route to ranks that no longer own the slot
    result = explore(
        pm.membership_model(2, 3, bug="epoch_before_install"),
        max_schedules=300,
        name="member-early-epoch",
    )
    assert isinstance(result.failure, InvariantViolation)
    assert "non-owner" in str(result.failure) or "released leavers" in str(
        result.failure
    )
    with pytest.raises(InvariantViolation):
        run_once(
            pm.membership_model(2, 3, bug="epoch_before_install"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# universal reshard: join-side state + chunked fragment streams ride the
# same membership transition (match bookkeeping, complete-or-abort chunks)
# ---------------------------------------------------------------------------


@pytest.mark.elastic
@pytest.mark.reshard
def test_membership_reshard_extension_invariants_hold_exhaustive():
    # the universal-reshard extension: join build/probe tokens, match
    # bookkeeping and chunked fragment streams all ride the transition — a
    # wider slot space forces multi-stream, multi-chunk interleavings
    t0 = time.monotonic()
    result = explore(
        pm.membership_model(2, 3, n_slots=8),
        max_schedules=N_SCHEDULES,
        name="member-reshard",
    )
    _BATTERY_SECONDS["reshard"] = time.monotonic() - t0
    assert result.ok, (
        f"reshard-extension invariant failed on schedule "
        f"{result.failing_schedule}: {result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.elastic
@pytest.mark.reshard
def test_membership_join_row_orphan_bug_caught_and_replayable():
    # one moved slot's probe-side join rows never make the fragment: the
    # arrangement re-keys under the new map with its probe side gone
    result = explore(
        pm.membership_model(2, 3, bug="join_row_orphan"),
        max_schedules=300,
        name="member-join-orphan",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the orphaned join-side rows went undetected"
    )
    assert "rows lost" in str(result.failure)
    assert "jright" in str(result.failure)
    with pytest.raises(InvariantViolation, match="rows lost"):
        run_once(
            pm.membership_model(2, 3, bug="join_row_orphan"),
            choices=result.failing_schedule,
        )


@pytest.mark.elastic
@pytest.mark.reshard
def test_membership_double_match_bug_caught_and_replayable():
    # match bookkeeping dropped from the fragments: the new owner re-emits
    # matches the donor already emitted pre-cut
    result = explore(
        pm.membership_model(2, 3, bug="double_match"),
        max_schedules=300,
        name="member-double-match",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the replayed join match went undetected"
    )
    assert "match emitted" in str(result.failure)
    with pytest.raises(InvariantViolation, match="match emitted"):
        run_once(
            pm.membership_model(2, 3, bug="double_match"),
            choices=result.failing_schedule,
        )


@pytest.mark.elastic
@pytest.mark.reshard
def test_membership_torn_chunk_install_bug_caught_with_seed():
    # a torn chunk stream (chunk durable, manifest never lands) imported by
    # an installer that skips the complete-or-abort check: rows vanish
    result = sweep_seeds(
        pm.membership_model(2, 3, bug="torn_chunk_install"),
        n_seeds=200,
        base_seed=61,
        name="member-torn-chunk",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the torn-chunk partial install went undetected"
    )
    assert "rows lost" in str(result.failure)
    assert result.failing_seed is not None
    with pytest.raises(InvariantViolation, match="rows lost"):
        run_once(
            pm.membership_model(2, 3, bug="torn_chunk_install"),
            seed=result.failing_seed,
        )


@pytest.mark.elastic
@pytest.mark.reshard
def test_membership_owner_map_stale_bug_caught_and_replayable():
    # a donor partitioning with a stale (prior-attempt) ownership map: rows
    # land on ranks the committed map does not own them to
    result = explore(
        pm.membership_model(2, 3, bug="owner_map_stale"),
        max_schedules=300,
        name="member-stale-map",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the stale-owner-map partition went undetected"
    )
    assert "reside on" in str(result.failure)
    with pytest.raises(InvariantViolation, match="reside on"):
        run_once(
            pm.membership_model(2, 3, bug="owner_map_stale"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# tiered IVF index (prefetch staging / background rebuild / generation swap)
# ---------------------------------------------------------------------------


@pytest.mark.tiered
def test_tiered_index_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.tiered_index_model(), max_schedules=N_SCHEDULES, name="tiered"
    )
    _BATTERY_SECONDS["tiered"] = time.monotonic() - t0
    assert result.ok, (
        f"tiered-index invariant failed on schedule {result.failing_schedule}: "
        f"{result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.tiered
def test_tiered_index_invariants_hold_seeded():
    result = sweep_seeds(
        pm.tiered_index_model(), n_seeds=100, base_seed=91, name="tiered-seeded"
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"
    assert result.distinct_schedules == 100


@pytest.mark.tiered
def test_tiered_torn_swap_bug_caught_with_seed():
    # the reader must land between the two swap acquisitions — deep in the
    # tree, seeded walks reach it (same split as the membership batteries)
    result = sweep_seeds(
        pm.tiered_index_model(bug="torn_swap"),
        n_seeds=300,
        base_seed=7,
        name="tiered-torn",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the torn-swap regression went undetected"
    )
    assert "torn generation read" in str(result.failure)
    assert result.failing_seed is not None
    with pytest.raises(InvariantViolation, match="torn generation read"):
        run_once(
            pm.tiered_index_model(bug="torn_swap"), seed=result.failing_seed
        )


@pytest.mark.tiered
def test_tiered_incomplete_swap_bug_caught_and_replayable():
    result = explore(
        pm.tiered_index_model(bug="swap_incomplete"),
        max_schedules=300,
        name="tiered-incomplete",
    )
    assert isinstance(result.failure, InvariantViolation)
    assert "incomplete generation" in str(result.failure)
    with pytest.raises(InvariantViolation, match="incomplete generation"):
        run_once(
            pm.tiered_index_model(bug="swap_incomplete"),
            choices=result.failing_schedule,
        )


@pytest.mark.tiered
def test_tiered_drop_old_early_bug_caught_with_seed():
    # the old generation freed before the swap commits: an in-flight query
    # must hit the hole — again a deep interleaving, reached by seeded walks
    result = sweep_seeds(
        pm.tiered_index_model(bug="drop_old_early"),
        n_seeds=300,
        base_seed=7,
        name="tiered-dropold",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the old-generation-freed-early regression went undetected"
    )
    assert "incomplete generation" in str(result.failure)
    assert result.failing_seed is not None
    with pytest.raises(InvariantViolation, match="incomplete generation"):
        run_once(
            pm.tiered_index_model(bug="drop_old_early"), seed=result.failing_seed
        )


@pytest.mark.tiered
def test_tiered_stage_leak_bug_caught_and_replayable():
    result = explore(
        pm.tiered_index_model(bug="leak_stage"),
        max_schedules=400,
        name="tiered-leak",
    )
    assert isinstance(result.failure, InvariantViolation)
    assert "staging slots leaked" in str(result.failure)
    with pytest.raises(InvariantViolation, match="staging slots leaked"):
        run_once(
            pm.tiered_index_model(bug="leak_stage"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# quantized retrieval (scale recalibration install vs concurrent scoring)
# ---------------------------------------------------------------------------


@pytest.mark.quant
def test_quant_recalibration_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.quant_recalibration_model(), max_schedules=N_SCHEDULES, name="quant"
    )
    _BATTERY_SECONDS["quant"] = time.monotonic() - t0
    assert result.ok, (
        f"quant-recalibration invariant failed on schedule "
        f"{result.failing_schedule}: {result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.quant
def test_quant_recalibration_abort_holds_exhaustive():
    # the chaos `quant` op aborts before the install: every interleaving must
    # leave the old sidecars serving, bit-exact, with nothing published
    result = explore(
        pm.quant_recalibration_model(abort=True),
        max_schedules=N_SCHEDULES,
        name="quant-abort",
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


@pytest.mark.quant
def test_quant_torn_install_bug_caught_with_seed():
    # the reader must land between the two install acquisitions — deep in
    # the tree, seeded walks reach it (same split as the tiered batteries)
    result = sweep_seeds(
        pm.quant_recalibration_model(bug="torn_install"),
        n_seeds=300,
        base_seed=7,
        name="quant-torn",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the torn sidecar-install regression went undetected"
    )
    assert "torn sidecar read" in str(result.failure)
    assert result.failing_seed is not None
    with pytest.raises(InvariantViolation, match="torn sidecar read"):
        run_once(
            pm.quant_recalibration_model(bug="torn_install"),
            seed=result.failing_seed,
        )


@pytest.mark.quant
def test_quant_stale_cast_bug_caught_and_replayable():
    result = explore(
        pm.quant_recalibration_model(bug="stale_cast"),
        max_schedules=400,
        name="quant-stale",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the stale-cached-cast regression went undetected"
    )
    assert "stale cached cast" in str(result.failure)
    with pytest.raises(InvariantViolation, match="stale cached cast"):
        run_once(
            pm.quant_recalibration_model(bug="stale_cast"),
            choices=result.failing_schedule,
        )


@pytest.mark.quant
def test_quant_install_after_abort_bug_caught_and_replayable():
    result = explore(
        pm.quant_recalibration_model(abort=True, bug="install_after_abort"),
        max_schedules=400,
        name="quant-abort-install",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the install-after-abort regression went undetected"
    )
    assert "published new scales" in str(result.failure)
    with pytest.raises(InvariantViolation, match="published new scales"):
        run_once(
            pm.quant_recalibration_model(abort=True, bug="install_after_abort"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# closed-loop autoscaler (controller <-> transition executor)
# ---------------------------------------------------------------------------


@pytest.mark.autoscale
def test_autoscaler_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.autoscaler_model(), max_schedules=N_SCHEDULES, name="autoscaler"
    )
    _BATTERY_SECONDS["autoscaler"] = time.monotonic() - t0
    assert result.ok, (
        f"autoscaler invariant failed on schedule {result.failing_schedule}: "
        f"{result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.autoscale
def test_autoscaler_invariants_hold_seeded():
    result = sweep_seeds(
        pm.autoscaler_model(), n_seeds=100, base_seed=61, name="autoscaler-seeded"
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"
    assert result.distinct_schedules == 100


@pytest.mark.autoscale
def test_autoscaler_refusal_backoff_holds():
    # the preflight vote refuses the first scale-up: the controller must back
    # off typed and retry at most once per window, on every interleaving
    result = explore(
        pm.autoscaler_model(refuse_up=True),
        max_schedules=N_SCHEDULES,
        name="autoscaler-refuse",
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


@pytest.mark.autoscale
def test_autoscaler_crash_racing_directive_holds():
    # a transition dying mid-flight hands the cluster to the recovery ladder;
    # the controller must never issue while it recovers, and never deadlock
    result = explore(
        pm.autoscaler_model(crash_up=True),
        max_schedules=N_SCHEDULES,
        name="autoscaler-crash",
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


@pytest.mark.autoscale
def test_autoscaler_double_directive_bug_caught_and_replayable():
    result = explore(
        pm.autoscaler_model(bug="double_directive"),
        max_schedules=400,
        name="autoscaler-double",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the double-directive regression went undetected"
    )
    assert "two membership transitions in flight" in str(result.failure)
    with pytest.raises(InvariantViolation, match="two membership transitions"):
        run_once(
            pm.autoscaler_model(bug="double_directive"),
            choices=result.failing_schedule,
        )


@pytest.mark.autoscale
def test_autoscaler_cooldown_skip_bug_caught_with_seed():
    # the back-to-back issue needs the executor to complete BETWEEN two
    # controller ticks — deep in the decision tree, where seeded walks reach
    # faster than root-systematic DFS (same split as the membership
    # release-before-drain battery)
    result = sweep_seeds(
        pm.autoscaler_model(bug="cooldown_skip"),
        n_seeds=200,
        base_seed=71,
        name="autoscaler-cooldown",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the cooldown-skip regression went undetected"
    )
    assert "cooldown violated" in str(result.failure)
    assert result.failing_seed is not None
    # the SEED alone reproduces the storm (deterministic walk)
    with pytest.raises(InvariantViolation, match="cooldown violated"):
        run_once(
            pm.autoscaler_model(bug="cooldown_skip"), seed=result.failing_seed
        )


@pytest.mark.autoscale
def test_autoscaler_refusal_retry_storm_caught_with_seed():
    # the storm needs the refusal to land BETWEEN controller ticks before the
    # cooldown re-opens — deep in the tree, seeded walks reach it
    result = sweep_seeds(
        pm.autoscaler_model(refuse_up=True, bug="refusal_retry"),
        n_seeds=200,
        base_seed=81,
        name="autoscaler-retry-storm",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the refusal-retry storm went undetected"
    )
    assert "backoff window" in str(result.failure)
    assert result.failing_seed is not None
    with pytest.raises(InvariantViolation, match="backoff window"):
        run_once(
            pm.autoscaler_model(refuse_up=True, bug="refusal_retry"),
            seed=result.failing_seed,
        )


@pytest.mark.autoscale
def test_autoscaler_no_shed_first_bug_caught_and_replayable():
    result = explore(
        pm.autoscaler_model(bug="no_shed_first"),
        max_schedules=400,
        name="autoscaler-no-shed",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the shed-first ordering regression went undetected"
    )
    assert "shed-first" in str(result.failure)
    with pytest.raises(InvariantViolation, match="shed-first"):
        run_once(
            pm.autoscaler_model(bug="no_shed_first"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# read-replica follow / bounded-staleness serve
# ---------------------------------------------------------------------------


@pytest.mark.replicas
def test_replica_follow_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.replica_follow_model(), max_schedules=N_SCHEDULES, name="replica"
    )
    _BATTERY_SECONDS["replica"] = time.monotonic() - t0
    assert result.ok, (
        f"replica-follow invariant failed on schedule "
        f"{result.failing_schedule}: {result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.replicas
def test_replica_follow_invariants_hold_seeded():
    result = sweep_seeds(
        pm.replica_follow_model(), n_seeds=100, base_seed=29,
        name="replica-seeded",
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"
    assert result.distinct_schedules == 100


@pytest.mark.replicas
def test_replica_torn_bootstrap_refuses_exhaustive():
    # a torn bootstrap is a typed refusal: out of rotation, zero serves,
    # every client query still reaches a terminal outcome (router failover)
    result = explore(
        pm.replica_follow_model(torn=True),
        max_schedules=N_SCHEDULES,
        name="replica-torn",
    )
    assert result.ok, f"{result.failing_schedule}: {result.failure}"


@pytest.mark.replicas
def test_replica_double_apply_bug_caught_with_seed():
    # the double apply needs BOTH pollers to list the same frame before
    # either applies it — deep in the tree, where seeded walks reach faster
    # than root-systematic DFS (same split as the membership and autoscaler
    # deep-race batteries); a small instance keeps the walk dense
    result = sweep_seeds(
        pm.replica_follow_model(2, 1, bug="double_apply"),
        n_seeds=300,
        base_seed=37,
        name="replica-double-apply",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the double-apply regression went undetected"
    )
    assert "applied twice" in str(result.failure)
    assert result.failing_seed is not None
    # the SEED alone reproduces the double apply (deterministic walk)
    with pytest.raises(InvariantViolation, match="applied twice"):
        run_once(
            pm.replica_follow_model(2, 1, bug="double_apply"),
            seed=result.failing_seed,
        )


@pytest.mark.replicas
def test_replica_stale_serve_bug_caught_with_seed():
    result = sweep_seeds(
        pm.replica_follow_model(bug="stale_serve"),
        n_seeds=300,
        base_seed=31,
        name="replica-stale-serve",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the stale-serve-past-bound regression went undetected"
    )
    assert "past the bound" in str(result.failure)
    assert result.failing_seed is not None
    # the SEED alone reproduces the stale serve (deterministic walk)
    with pytest.raises(InvariantViolation, match="past the bound"):
        run_once(
            pm.replica_follow_model(bug="stale_serve"),
            seed=result.failing_seed,
        )


@pytest.mark.replicas
def test_replica_torn_bootstrap_serve_bug_caught_and_replayable():
    result = explore(
        pm.replica_follow_model(torn=True, bug="torn_bootstrap_serve"),
        max_schedules=400,
        name="replica-torn-serve",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the torn-bootstrap-serve regression went undetected"
    )
    assert "half-installed" in str(result.failure)
    with pytest.raises(InvariantViolation, match="half-installed"):
        run_once(
            pm.replica_follow_model(torn=True, bug="torn_bootstrap_serve"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# trace ring: crash flush, epoch bump, cross-rank sampling consistency
# ---------------------------------------------------------------------------


@pytest.mark.trace
def test_trace_ring_invariants_hold_exhaustive():
    t0 = time.monotonic()
    result = explore(
        pm.trace_ring_model(), max_schedules=N_SCHEDULES, name="trace"
    )
    _BATTERY_SECONDS["trace"] = time.monotonic() - t0
    assert result.ok, (
        f"trace-ring invariant failed on schedule "
        f"{result.failing_schedule}: {result.failure}"
    )
    assert result.distinct_schedules >= N_SCHEDULES


@pytest.mark.trace
def test_trace_ring_invariants_hold_seeded():
    result = sweep_seeds(
        pm.trace_ring_model(), n_seeds=100, base_seed=43,
        name="trace-seeded",
    )
    assert result.ok, f"seed {result.failing_seed}: {result.failure}"
    assert result.distinct_schedules == 100


@pytest.mark.trace
def test_trace_orphan_on_bump_bug_caught_with_seed():
    # the orphan needs the bump to land inside a writer's start->verdict
    # window — deep in the tree, where seeded walks reach faster than
    # root-systematic DFS; the 1x1 instance keeps the walk dense
    result = sweep_seeds(
        pm.trace_ring_model(1, 1, bug="orphan_on_bump"),
        n_seeds=300,
        base_seed=41,
        name="trace-orphan",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the pending-swept-on-bump regression went undetected"
    )
    assert "orphaned" in str(result.failure)
    assert result.failing_seed is not None
    # the SEED alone reproduces the orphaned span (deterministic walk)
    with pytest.raises(InvariantViolation, match="orphaned"):
        run_once(
            pm.trace_ring_model(1, 1, bug="orphan_on_bump"),
            seed=result.failing_seed,
        )


@pytest.mark.trace
def test_trace_flush_deadlock_bug_caught_with_seed():
    # writer promotion holding the ring lock while wanting the file lock is
    # the AB/BA inversion with the crash flush's file-then-ring order — the
    # bug class the tracer's single re-entrant lock exists to prevent
    result = sweep_seeds(
        pm.trace_ring_model(1, 1, bug="flush_deadlock"),
        n_seeds=300,
        base_seed=47,
        name="trace-deadlock",
    )
    assert isinstance(result.failure, DeadlockError), (
        "the flush-on-crash lock inversion went undetected"
    )
    assert result.failing_seed is not None
    with pytest.raises(DeadlockError):
        run_once(
            pm.trace_ring_model(1, 1, bug="flush_deadlock"),
            seed=result.failing_seed,
        )


@pytest.mark.trace
def test_trace_split_sampling_bug_caught_and_replayable():
    result = explore(
        pm.trace_ring_model(bug="split_sampling"),
        max_schedules=400,
        name="trace-split",
    )
    assert isinstance(result.failure, InvariantViolation), (
        "the per-rank-coin sampling divergence went undetected"
    )
    assert "sampling split" in str(result.failure)
    with pytest.raises(InvariantViolation, match="sampling split"):
        run_once(
            pm.trace_ring_model(bug="split_sampling"),
            choices=result.failing_schedule,
        )


# ---------------------------------------------------------------------------
# PWA101 <-> model check: the same inversion caught both ways
# ---------------------------------------------------------------------------

_INVERSION_SOURCE = '''
import threading

class MeshLocks:
    def __init__(self):
        self.inbox_lock = threading.Lock()
        self.gen_lock = threading.Lock()

    def deliver(self):
        with self.inbox_lock:
            with self.gen_lock:
                pass

    def install(self):
        with self.gen_lock:
            with self.inbox_lock:
                pass
'''


def test_planted_inversion_caught_by_pwa101_and_model_check():
    # statically: the lint pass names the cycle
    report = analyze_source(_INVERSION_SOURCE)
    pwa101 = report.by_code("PWA101")
    assert pwa101, report.to_json()
    assert "MeshLocks.inbox_lock" in pwa101[0].message
    assert "MeshLocks.gen_lock" in pwa101[0].message
    # dynamically: the scheduler finds the deadlocking interleaving of the
    # same AB/BA shape, with a replayable schedule
    result = explore(
        pm.lock_order_model(inverted=True), max_schedules=200, name="inversion"
    )
    assert isinstance(result.failure, DeadlockError)
    with pytest.raises(DeadlockError):
        run_once(pm.lock_order_model(inverted=True), choices=result.failing_schedule)
    # and the disciplined ordering is clean under BOTH
    fixed = _INVERSION_SOURCE.replace(
        "with self.gen_lock:\n            with self.inbox_lock:",
        "with self.inbox_lock:\n            with self.gen_lock:",
    )
    assert not analyze_source(fixed).by_code("PWA101")
    assert explore(pm.lock_order_model(inverted=False), max_schedules=200).ok


# ---------------------------------------------------------------------------
# budget guard: the whole protocol battery stays inside tier-1 bounds
# ---------------------------------------------------------------------------


def test_model_check_battery_within_budget():
    # the acceptance batteries above recorded their own wall time (no work is
    # redone here); each 200-schedule explore is a few seconds solo, and the
    # documented <60 s budget must hold even under full-suite load
    if set(_BATTERY_SECONDS) != {
        "fence", "ckpt", "encsvc", "membership", "reshard", "autoscaler",
        "tiered", "quant", "replica", "trace",
    }:
        pytest.skip("acceptance batteries did not run in this session (-k selection)")
    total = sum(_BATTERY_SECONDS.values())
    assert total < 60, f"model-check acceptance batteries too slow: {_BATTERY_SECONDS}"
