"""Cached-object storage (reference ``persistence/cached_object_storage.rs:377``)
and the connector behavior it exists for: resume without refetching unchanged
objects (VERDICT r3 item 10) — plus the snapshot-mode postgres sink."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.persistence.cached_objects import CachedObjectStorage


# -- CachedObjectStorage unit behavior ---------------------------------------


def test_place_lookup_remove(tmp_path):
    cache = CachedObjectStorage(tmp_path)
    v1 = cache.place_object("s3://b/a.csv", b"one", {"etag": "e1", "size": 3})
    v2 = cache.place_object("s3://b/b.csv", b"two", {"etag": "e2"})
    assert (v1, v2) == (1, 2)
    assert cache.contains_object("s3://b/a.csv")
    assert cache.get_object("s3://b/a.csv") == b"one"
    assert cache.get_metadata("s3://b/a.csv") == {"etag": "e1", "size": 3}
    assert cache.actual_key_set() == {"s3://b/a.csv", "s3://b/b.csv"}

    cache.place_object("s3://b/a.csv", b"one-v2", {"etag": "e3"})
    assert cache.get_object("s3://b/a.csv") == b"one-v2"
    cache.remove_object("s3://b/b.csv")
    assert not cache.contains_object("s3://b/b.csv")
    assert cache.actual_key_set() == {"s3://b/a.csv"}


def test_state_survives_restart(tmp_path):
    cache = CachedObjectStorage(tmp_path)
    cache.place_object("u1", b"alpha", {"m": 1})
    cache.place_object("u2", b"beta", {"m": 2})
    cache.remove_object("u1")
    reopened = CachedObjectStorage(tmp_path)
    assert reopened.actual_key_set() == {"u2"}
    assert reopened.get_object("u2") == b"beta"
    assert reopened.get_metadata("u2") == {"m": 2}
    # appends continue after the surviving max version
    v = reopened.place_object("u3", b"gamma")
    assert v > 3


def test_rewind_drops_newer_events_durably(tmp_path):
    cache = CachedObjectStorage(tmp_path)
    cache.place_object("u", b"v1", {"rev": 1})  # version 1
    cache.place_object("u", b"v2", {"rev": 2})  # version 2
    cache.place_object("w", b"w1", {"rev": 1})  # version 3
    cache.rewind(2)
    assert cache.get_metadata("u") == {"rev": 2}
    assert not cache.contains_object("w")
    # durably: a reload sees the rewound state, not the dropped events
    reopened = CachedObjectStorage(tmp_path)
    assert reopened.actual_key_set() == {"u"}
    assert reopened.get_object("u") == b"v2"
    # rewind(0) clears everything
    reopened.rewind(0)
    assert reopened.actual_key_set() == set()
    assert CachedObjectStorage(tmp_path).actual_key_set() == set()


def test_memory_backend_roundtrip():
    cache = CachedObjectStorage(None)
    cache.place_object("u", b"x", {"a": 1})
    assert cache.get_object("u") == b"x"
    cache.rewind(0)
    assert not cache.contains_object("u")


def test_manager_accessor(tmp_path):
    from pathway_tpu.persistence.engine import PersistenceManager

    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(tmp_path / "store"))
    mgr = PersistenceManager(cfg)
    cache = mgr.cached_objects()
    cache.place_object("u", b"x")
    assert mgr.cached_objects() is cache  # one instance per manager
    assert (tmp_path / "store").exists()


# -- resume without refetch ---------------------------------------------------


class CountingS3Client:
    """Minimal boto3 surface counting get_object calls per key."""

    def __init__(self, objects: dict[str, bytes]):
        self.objects = dict(objects)
        self.fetches: dict[str, int] = {}

    def list_objects_v2(self, Bucket, Prefix, ContinuationToken=None):
        import hashlib

        keys = sorted(k for k in self.objects if k.startswith(Prefix))
        return {
            "Contents": [
                {
                    "Key": k,
                    "ETag": hashlib.md5(self.objects[k]).hexdigest(),
                    "Size": len(self.objects[k]),
                }
                for k in keys
            ],
            "IsTruncated": False,
        }

    def get_object(self, Bucket, Key):
        self.fetches[Key] = self.fetches.get(Key, 0) + 1

        class Body:
            def __init__(self, data):
                self._data = data

            def read(self):
                return self._data

        return {"Body": Body(self.objects[Key])}


def _run_s3_pipeline(client, store) -> dict:
    pg.G.clear()
    t = pw.io.s3.read(
        "s3://bucket/d/",
        format="json",
        schema=pw.schema_builder({"v": int}),
        mode="static",
        _client_factory=lambda settings: client,
    )
    counts = t.groupby(t.v).reduce(t.v, n=pw.reducers.count())
    got: dict = {}
    pw.io.subscribe(
        counts,
        lambda key, row, time, is_addition: got.__setitem__(row["v"], row["n"])
        if is_addition
        else got.pop(row["v"], None),
    )
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    return got


def test_s3_resume_does_not_refetch_unchanged_objects(tmp_path):
    """Second run over the same persistence store must not re-download objects
    whose ETag is unchanged — the reference pins them in cached object storage;
    here the journaled per-object state deltas carry the parsed rows."""
    objects = {"d/a.jsonl": b'{"v": 1}\n{"v": 1}\n', "d/b.jsonl": b'{"v": 2}\n'}
    client = CountingS3Client(objects)
    store = tmp_path / "store"

    got = _run_s3_pipeline(client, store)
    assert got == {1: 2, 2: 1}
    assert client.fetches == {"d/a.jsonl": 1, "d/b.jsonl": 1}

    got = _run_s3_pipeline(client, store)
    assert got == {1: 2, 2: 1}
    assert client.fetches == {"d/a.jsonl": 1, "d/b.jsonl": 1}, (
        "resume refetched unchanged objects"
    )

    # a changed object IS refetched (and only it) — streaming resume notices the
    # new ETag on its rescan; static runs conclude from restored offsets
    client.objects["d/b.jsonl"] = b'{"v": 3}\n'
    pg.G.clear()
    t = pw.io.s3.read(
        "s3://bucket/d/",
        format="json",
        schema=pw.schema_builder({"v": int}),
        mode="streaming",
        autocommit_duration_ms=10,
        _client_factory=lambda settings: client,
    )
    counts = t.groupby(t.v).reduce(t.v, n=pw.reducers.count())
    got = {}
    pw.io.subscribe(
        counts,
        lambda key, row, time, is_addition: got.__setitem__(row["v"], row["n"])
        if is_addition
        else got.pop(row["v"], None),
    )
    from pathway_tpu.engine.runner import GraphRunner

    runner = GraphRunner(pg.G._current)
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    runner.setup(monitoring_level=pw.MonitoringLevel.NONE, persistence_config=cfg)
    import time as time_mod

    deadline = time_mod.monotonic() + 20
    while time_mod.monotonic() < deadline and got != {1: 2, 3: 1}:
        runner.step()
        time_mod.sleep(0.02)
    assert got == {1: 2, 3: 1}
    assert client.fetches == {"d/a.jsonl": 1, "d/b.jsonl": 2}, (
        "only the changed object may be refetched"
    )


# -- snapshot-mode postgres sink ----------------------------------------------


class FakeCursor:
    def __init__(self, log):
        self.log = log

    def execute(self, sql, params=None):
        self.log.append(("execute", sql, list(params or [])))


class FakeConnection:
    def __init__(self):
        self.log: list = []
        self.closed = False

    def cursor(self):
        return FakeCursor(self.log)

    def commit(self):
        self.log.append(("commit",))

    def close(self):
        self.closed = True


def test_postgres_write_snapshot_end_to_end():
    pg.G.clear()
    t = pw.debug.table_from_markdown(
        """
        word | n | __time__ | __diff__
        cat  | 1 | 0        | 1
        dog  | 2 | 0        | 1
        cat  | 1 | 2        | -1
        cat  | 5 | 2        | 1
        dog  | 2 | 4        | -1
        """
    )
    conn = FakeConnection()
    pw.io.postgres.write_snapshot(
        t,
        {},
        "tbl",
        ["word"],
        init_mode="create_if_not_exists",
        _connection_factory=lambda settings: conn,
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)

    executes = [e for e in conn.log if e[0] == "execute"]
    create = executes[0][1]
    assert create.startswith("CREATE TABLE IF NOT EXISTS tbl")
    assert "PRIMARY KEY (word)" in create and "time BIGINT" in create

    upserts = [e for e in executes if e[1].startswith("INSERT")]
    deletes = [e for e in executes if e[1].startswith("DELETE")]
    assert all("ON CONFLICT (word) DO UPDATE" in e[1] for e in upserts)
    # final state reachable from the statement stream: replay it
    state: dict = {}
    for e in executes[1:]:
        if e[1].startswith("INSERT"):
            word, n, _time, _diff = e[2]
            state[word] = n
        elif e[1].startswith("DELETE"):
            state.pop(e[2][0], None)
    assert state == {"cat": 5}
    assert deletes, "retraction without replacement must DELETE"
    assert conn.closed


def test_postgres_write_snapshot_batching():
    pg.G.clear()
    t = pw.debug.table_from_markdown(
        """
        word | n
        a    | 1
        b    | 2
        c    | 3
        d    | 4
        """
    )
    conn = FakeConnection()
    pw.io.postgres.write_snapshot(
        t, {}, "tbl", ["word"], max_batch_size=3,
        _connection_factory=lambda settings: conn,
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # 4 statements with batch size 3 -> a commit after 3, then the tail commit
    kinds = [e[0] for e in conn.log]
    assert kinds.count("commit") >= 2
    first_commit = kinds.index("commit")
    assert kinds[:first_commit].count("execute") == 3


def test_postgres_write_snapshot_rejects_unknown_key():
    pg.G.clear()
    t = pw.debug.table_from_markdown(
        """
        word | n
        a    | 1
        """
    )
    with pytest.raises(ValueError, match="primary key"):
        pw.io.postgres.write_snapshot(
            t, {}, "tbl", ["nope"], _connection_factory=lambda s: FakeConnection()
        )
