"""Chaos harness: seeded deterministic fault injection (internals/chaos.py)
driving the supervised cluster runtime.

The two spawn tests here are the PR's acceptance scenario: SIGKILL one worker
of ``spawn -n 2`` mid-run via a seeded chaos plan — with persistence on the
supervisor restarts the cluster and the final output is bit-identical to the
failure-free run; with persistence off the cluster exits with a typed peer
error within the barrier deadline. No hang in either case."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import chaos as chaos_mod
from pathway_tpu.internals.chaos import Chaos, get_chaos, reset_chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- plan / schedule determinism (pure unit) ---------------------------------


def test_chaos_schedule_is_seed_deterministic():
    plan = {"frames": {"drop_prob": 0.2, "delay_prob": 0.3, "delay_ms": 5}}
    a = Chaos(7, plan)
    b = Chaos(7, plan)
    seq_a = [a.frame_action(0, 1).kind for _ in range(200)]
    seq_b = [b.frame_action(0, 1).kind for _ in range(200)]
    assert seq_a == seq_b, "same seed must replay the same schedule"
    # independent per (rank, peer) stream: draws to another peer don't shift it
    c = Chaos(7, plan)
    interleaved = []
    for _ in range(200):
        interleaved.append(c.frame_action(0, 1).kind)
        c.frame_action(0, 2)  # traffic on another link
    assert interleaved == seq_a
    d = Chaos(8, plan)
    seq_d = [d.frame_action(0, 1).kind for _ in range(200)]
    assert seq_d != seq_a, "different seed must give a different schedule"


def test_chaos_kill_matches_rank_commit_and_run(monkeypatch):
    killed = []
    monkeypatch.setattr(chaos_mod.os, "kill", lambda pid, sig: killed.append((pid, sig)))
    plan = {"kill": [{"rank": 1, "commit": 3, "run": 0}]}
    c = Chaos(0, plan)
    c.maybe_kill(0, 3)  # wrong rank
    c.maybe_kill(1, 2)  # wrong commit
    assert killed == []
    c.maybe_kill(1, 3)
    assert killed == [(os.getpid(), signal.SIGKILL)]
    # a restarted incarnation (PATHWAY_RESTART_COUNT=1) must survive the replay
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "1")
    c2 = Chaos(0, plan)
    killed.clear()
    c2.maybe_kill(1, 3)
    assert killed == []


def test_get_chaos_env_contract(monkeypatch):
    reset_chaos()
    monkeypatch.delenv("PATHWAY_CHAOS_PLAN", raising=False)
    assert get_chaos() is None
    reset_chaos()
    monkeypatch.setenv("PATHWAY_CHAOS_PLAN", json.dumps({"frames": {"drop_prob": 1.0}}))
    monkeypatch.setenv("PATHWAY_CHAOS_SEED", "42")
    try:
        c = get_chaos()
        assert c is not None and c.seed == 42
        assert c.frame_action(0, 1).kind == "drop"
    finally:
        reset_chaos()


# -- transient backend write errors retried (satellite) -----------------------


@pytest.mark.chaos
def test_chaos_transient_s3_write_errors_are_retried(tmp_path, monkeypatch):
    """Injected transient PUT failures on the S3 persistence backend are
    absorbed by ExponentialBackoffRetryStrategy — the run completes, every
    journal object lands, and a resume replays them exactly."""
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.udfs import ExponentialBackoffRetryStrategy

    from .mocks import DirS3Client

    monkeypatch.setenv("PATHWAY_CHAOS_SEED", "11")
    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps({"backend": {"put_error_prob": 0.6, "max_errors": 5}}),
    )
    reset_chaos()
    try:
        client = DirS3Client(str(tmp_path / "fake-s3"))

        def run_once():
            from pathway_tpu.engine.runner import GraphRunner

            t = pw.debug.table_from_markdown(
                """
                word  | n
                cat   | 1
                dog   | 2
                cat   | 3
                """
            )
            counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.sum(t.n))
            rows = {}

            def on_change(key, row, time, is_addition):
                if is_addition:
                    rows[key] = row
                else:
                    rows.pop(key, None)

            pw.io.subscribe(counts, on_change)
            cfg = pw.persistence.Config(
                pw.persistence.Backend.s3(
                    "s3://bucket/chaos", _client_factory=lambda settings: client
                ),
                backend_retry_strategy=ExponentialBackoffRetryStrategy(
                    max_retries=6, initial_delay=5, backoff_factor=2, jitter_ms=2
                ),
            )
            GraphRunner(G._current).run(persistence_config=cfg)
            return {r["word"]: r["total"] for r in rows.values()}

        first = run_once()
        assert first == {"cat": 4, "dog": 2}
        harness = get_chaos()
        assert harness is not None and harness.stats["backend_errors"] > 0, (
            "the plan never injected a write error — the retry path went untested"
        )
        # resume: every frame object must exist despite the injected failures
        G.clear()
        second = run_once()
        assert second == first
    finally:
        reset_chaos()


# -- spawn acceptance scenarios ----------------------------------------------

CHAOS_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

    out_path = os.path.join(tmp, f"out_{pid}.json")
    rows = {}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(repr(key), None)
        with open(out_path + ".tmp", "w") as f:
            json.dump(list(rows.values()), f)
        os.replace(out_path + ".tmp", out_path)

    pw.io.subscribe(counts, on_change)
    kwargs = {}
    if os.environ.get("PW_TEST_PERSIST") == "1":
        kwargs["persistence_config"] = pw.persistence.Config(
            pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
        )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE, **kwargs)
    """
)


def _chaos_spawn(tmp_path, first_port, *, plan, persist, max_restarts,
                 extra_env=None, restart_mode=None):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_CHAOS_SEED"] = "7"
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(plan)
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "30"
    if persist:
        env["PW_TEST_PERSIST"] = "1"
    env.update(extra_env or {})
    prog = tmp_path / "prog.py"
    prog.write_text(CHAOS_PROG)
    mode_args = ["--restart-mode", restart_mode] if restart_mode else []
    return subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(first_port),
            "--max-restarts", str(max_restarts), *mode_args,
            sys.executable, str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        start_new_session=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def _read_merged(tmp_path) -> dict:
    merged: dict = {}
    for p in range(2):
        path = tmp_path / f"out_{p}.json"
        if not path.exists():
            continue
        try:
            for r in json.loads(path.read_text()):
                merged[r["word"]] = r["total"]
        except ValueError:
            pass
    return merged


def _terminate_group(proc) -> str:
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    try:
        _, err = proc.communicate(timeout=20)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        _, err = proc.communicate()
    return err or ""


def _failure_free_counts(tmp_path) -> dict:
    """The reference output: the same pipeline, run in-process with no faults."""
    from pathway_tpu.internals.parse_graph import G

    G.clear()

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        str(tmp_path / "in"), format="csv", schema=WordSchema, mode="static"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    rows: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(key, None)

    pw.io.subscribe(counts, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    G.clear()
    return {r["word"]: r["total"] for r in rows.values()}


@pytest.mark.chaos
def test_chaos_kill_one_worker_supervisor_failover_exact(tmp_path):
    """Seeded kill of rank 0 at commit 3 (persistence on, ``--restart-mode
    all`` pinning the PR 2 rung): the supervisor restarts the cluster, the
    journal union replays, streaming continues, and the merged output is
    bit-identical to the failure-free run. (Surgical mode — the default — is
    covered by ``test_rejoin.py``.)"""
    (tmp_path / "in").mkdir()
    first_port = 28000 + os.getpid() % 500 * 4
    for i in range(4):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    plan = {"kill": [{"rank": 0, "commit": 3, "run": 0}]}
    proc = _chaos_spawn(tmp_path, first_port, plan=plan, persist=True,
                        max_restarts=1, restart_mode="all")
    err = ""
    try:
        time.sleep(5)  # kill + restart window
        # data arriving AFTER the failover must still be ingested exactly once
        (tmp_path / "in" / "b.csv").write_text(
            "word\n" + "\n".join(["owl"] * 3 + ["cat"] * 1) + "\n"
        )
        expected = {"cat": 11, "dog": 8, "owl": 3}
        deadline = time.time() + 120
        merged: dict = {}
        while time.time() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early (rc={proc.returncode}): {err}"
                )
            merged = _read_merged(tmp_path)
            if merged == expected:
                break
            time.sleep(0.3)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "restarting the cluster" in err, (
        f"supervisor never restarted — the chaos kill did not fire?\n{err}"
    )
    # bit-identical to the failure-free run of the same pipeline
    assert _failure_free_counts(tmp_path) == merged


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_repeated_kills_long_torture(tmp_path):
    """Long variant (excluded from tier-1 via ``slow``): BOTH ranks die across
    consecutive incarnations — rank 0 first, then the surviving rank 1 after
    the first recovery — and two supervised failovers still converge to exact
    totals. With ``--max-restarts`` > 0 the supervisor runs in surgical mode,
    so each death should relaunch only the dead rank (a restart-all fallback
    still counts as a recovery, but at least one rung must fire per death)."""
    (tmp_path / "in").mkdir()
    first_port = 28000 + os.getpid() % 500 * 4 + 4
    for i in range(6):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 3) + "\n"
        )

    plan = {
        "kill": [
            {"rank": 0, "commit": 3, "run": 0},
            # the survivor keeps run 0 across rank 0's surgical restart, so its
            # own scheduled kill fires later at a live post-rejoin commit; the
            # run-1 companion covers the tolerated restart-all fallback, where
            # rank 1 is relaunched with a bumped restart count and the run-0
            # entry would never match again
            {"rank": 1, "commit": 9, "run": 0},
            {"rank": 1, "commit": 9, "run": 1},
        ]
    }
    # budget 3 absorbs one surgical->restart-all fallback and still leaves a
    # recovery for the second death
    proc = _chaos_spawn(tmp_path, first_port, plan=plan, persist=True, max_restarts=3)
    err = ""
    try:
        time.sleep(10)  # both kill + recovery windows
        (tmp_path / "in" / "late.csv").write_text(
            "word\n" + "\n".join(["owl"] * 5) + "\n"
        )
        expected = {"cat": sum(i + 1 for i in range(6)), "dog": 18, "owl": 5}
        deadline = time.time() + 240
        merged: dict = {}
        while time.time() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early (rc={proc.returncode}): {err}"
                )
            merged = _read_merged(tmp_path)
            if merged == expected:
                break
            time.sleep(0.3)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    recoveries = err.count("surgically relaunching") + err.count(
        "restarting the cluster"
    )
    assert recoveries >= 2, f"expected two supervised recoveries:\n{err}"
    assert "surgically relaunching" in err, (
        f"--max-restarts > 0 should exercise surgical mode:\n{err}"
    )


@pytest.mark.chaos
def test_chaos_kill_without_persistence_fails_typed_and_fast(tmp_path):
    """Same kill with persistence OFF: no restart — the surviving rank must
    fail with a typed peer error within the barrier deadline and the
    supervisor must tear down with a per-rank post-mortem. Never a hang."""
    (tmp_path / "in").mkdir()
    first_port = 28000 + os.getpid() % 500 * 4 + 2
    (tmp_path / "in" / "a.csv").write_text("word\ncat\ncat\ndog\n")

    plan = {"kill": [{"rank": 0, "commit": 3, "run": 0}]}
    t0 = time.monotonic()
    proc = _chaos_spawn(tmp_path, first_port, plan=plan, persist=False, max_restarts=1)
    try:
        _, err = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        _terminate_group(proc)
        raise AssertionError("cluster HUNG after a worker SIGKILL (persistence off)")
    elapsed = time.monotonic() - t0
    assert proc.returncode != 0, "cluster reported success after losing a worker"
    # detection is socket-close driven, so teardown must beat the 30 s barrier
    # deadline by a wide margin (imports dominate the elapsed time)
    assert elapsed < 90, f"teardown took {elapsed:.0f}s — failure path is too slow"
    assert "PeerShutdownError" in err or "PeerTimeoutError" in err, (
        f"survivor did not fail with a typed peer error:\n{err}"
    )
    assert "post-mortem" in err, f"supervisor printed no post-mortem:\n{err}"
    assert "persistence is off" in err, f"missing loud no-restart reason:\n{err}"
