"""Distributed-tracing plane tests (``engine/tracing.py``): header
round-trip, hash-of-trace-id sampling (one decision per trace, every rank),
slow-root promotion / fast-root drop of the pending buffer, epoch-bump
survival, ring flush + the cross-rank merger (clock-offset alignment,
flight-dump partials), the critical-path one-liner, and the ``trace.*``
counters on the strict OpenMetrics exposition.

Isolation note: these tests assert EXACT ring contents and counter values,
but the full suite leaks daemon ``pw.run`` threads that keep stepping
commits (see test_monitoring.py's noise-floor comment) — any of them would
write spans the moment the process-wide tracer turns on. So each test runs
against a PRIVATE ``Tracer`` instance while the global singleton is pinned
disabled: module-level sampling helpers still read the global's refreshed
rate, and the leaked engines stay silent.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from pathway_tpu.engine import telemetry, tracing
from pathway_tpu.engine.tracing import (
    TRACE_HEADER,
    TraceContext,
    Tracer,
    commit_trace_context,
    critical_path,
    critical_path_line,
    format_trace_header,
    format_trace_tree,
    get_tracer,
    load_flight_spans,
    load_trace_file,
    merge_trace_files,
    new_trace_context,
    parse_trace_header,
)

pytestmark = pytest.mark.trace


def _sync_env(inst: Tracer) -> None:
    """Re-read flipped env knobs on the private tracer AND the global one
    (``_head_sampled`` reads the global's rate) — the global stays DISABLED
    so leaked daemon engines from earlier suite files cannot write spans."""
    g = get_tracer()
    g.refresh()
    g.enabled = False
    inst.refresh()


@pytest.fixture(autouse=True)
def tracer(monkeypatch):
    monkeypatch.setenv("PATHWAY_TRACE", "on")
    monkeypatch.setenv("PATHWAY_TRACE_SAMPLE", "1.0")
    monkeypatch.delenv("PATHWAY_TRACE_DIR", raising=False)
    monkeypatch.delenv("PATHWAY_TRACE_SLOW_MS", raising=False)
    monkeypatch.delenv("PATHWAY_TRACE_RING", raising=False)
    telemetry.stage_reset("trace.")
    inst = Tracer()
    inst.configure(rank=0)
    _sync_env(inst)
    yield inst
    g = get_tracer()
    g.reset()
    g.enabled = False


# -- header propagation -------------------------------------------------------


def test_header_format_parse_round_trip():
    ctx = TraceContext("ab" * 8, "cd" * 8, True)
    assert format_trace_header(ctx) == "ab" * 8 + "-" + "cd" * 8 + "-01"
    back = parse_trace_header(format_trace_header(ctx))
    assert (back.trace_id, back.span_id, back.sampled) == (
        ctx.trace_id, ctx.span_id, True,
    )
    off = parse_trace_header("ab" * 8 + "-" + "cd" * 8 + "-00")
    assert off is not None and off.sampled is False


def test_header_parse_tolerates_malformed_input():
    # a bad client header must read as absent, never 500 the route
    for bad in (None, "", "zz", "abc-def", "g" * 16 + "-" + "cd" * 8,
                "ab" * 8, "ab" * 9 + "-" + "cd" * 8):
        assert parse_trace_header(bad) is None
    # a missing/unknown flag falls back to the hash decision (rate=1.0 here)
    assert parse_trace_header("ab" * 8 + "-" + "cd" * 8).sampled is True
    assert parse_trace_header("ab" * 8 + "-" + "cd" * 8 + "-xx").sampled is True


# -- sampling -----------------------------------------------------------------


def test_sampling_is_a_pure_function_of_the_trace_id(monkeypatch, tracer):
    # every rank and component derives the SAME verdict from the id alone —
    # no sampling bit ever needs to ride the wire
    monkeypatch.setenv("PATHWAY_TRACE_SAMPLE", "0.5")
    _sync_env(tracer)
    for i in range(64):
        ctx = new_trace_context()
        header = format_trace_header(
            TraceContext(ctx.trace_id, ctx.span_id, ctx.sampled)
        )
        again = parse_trace_header(header.rsplit("-", 1)[0])  # strip flag
        assert again.sampled == ctx.sampled
    sampled = sum(new_trace_context().sampled for _ in range(400))
    assert 80 < sampled < 320  # rate actually thins, and actually keeps


def test_commit_trace_context_agrees_across_ranks():
    a = commit_trace_context(3, 41, rank=0)
    b = commit_trace_context(3, 41, rank=1)
    assert a.trace_id == b.trace_id  # lockstep commit id IS the cross-rank key
    assert a.span_id != b.span_id  # each rank's commit span is its own sibling
    assert a.sampled == b.sampled
    assert commit_trace_context(3, 42).trace_id != a.trace_id
    assert commit_trace_context(4, 41).trace_id != a.trace_id


def test_trace_defaults_off_when_env_unset(monkeypatch):
    # the master gate is OPT-IN: a process that never set PATHWAY_TRACE must
    # pay zero span bookkeeping (README knob row: default off)
    monkeypatch.delenv("PATHWAY_TRACE", raising=False)
    inst = Tracer()
    assert inst.enabled is False
    with inst.trace_span("rest", "GET /never") as span:
        assert span is None


# -- span lifecycle / routing -------------------------------------------------


def test_trace_span_nests_and_lands_in_ring(tracer):
    with tracer.trace_span("rest", "GET /v1/retrieve") as root:
        assert tracing.current_context().span_id == root.span_id
        with tracer.trace_span("coalesce", "coalesce 2") as child:
            pass
    assert child.parent_id == root.span_id
    assert child.trace_id == root.trace_id
    recent = tracer.recent_spans()
    assert {s["span_id"] for s in recent} >= {root.span_id, child.span_id}
    assert telemetry.stage_snapshot("trace.")["trace.span"] == 2.0


def test_slow_root_promotes_buffered_children(monkeypatch, tracer):
    monkeypatch.setenv("PATHWAY_TRACE_SAMPLE", "0.0")
    monkeypatch.setenv("PATHWAY_TRACE_SLOW_MS", "0")
    _sync_env(tracer)
    with tracer.trace_span("rest", "GET /slow") as root:
        with tracer.trace_span("coalesce", "admit"):
            pass
    assert root.sampled  # promoted at finish: slow roots always sample
    ids = {s["span_id"] for s in tracer.recent_spans()}
    assert root.span_id in ids and len(ids) == 2
    counters = telemetry.stage_snapshot("trace.")
    assert counters["trace.promoted"] == 1.0
    assert counters["trace.span"] == 2.0


def test_fast_root_drops_buffered_children(monkeypatch, tracer):
    monkeypatch.setenv("PATHWAY_TRACE_SAMPLE", "0.0")
    monkeypatch.setenv("PATHWAY_TRACE_SLOW_MS", "60000")
    _sync_env(tracer)
    with tracer.trace_span("rest", "GET /fast"):
        with tracer.trace_span("coalesce", "admit"):
            pass
    assert tracer.recent_spans() == []
    assert telemetry.stage_snapshot("trace.")["trace.dropped"] == 1.0


def test_epoch_bump_never_orphans_pending_spans(monkeypatch, tracer):
    # the trace_ring_model invariant, exercised against the real tracer: a
    # membership epoch bump between a child's finish and its root's verdict
    # must not strand the buffered child
    monkeypatch.setenv("PATHWAY_TRACE_SAMPLE", "0.0")
    monkeypatch.setenv("PATHWAY_TRACE_SLOW_MS", "0")
    _sync_env(tracer)
    with tracer.trace_span("rest", "GET /bump") as root:
        with tracer.trace_span("coalesce", "admit") as child:
            pass
        tracer.set_epoch(7)
    spans = {s["span_id"]: s for s in tracer.recent_spans()}
    assert child.span_id in spans and root.span_id in spans
    assert spans[child.span_id]["epoch"] == 0  # stamped at start, not at bump
    tracer.set_epoch(0)


def test_off_gate_disables_everything(monkeypatch, tracer, tmp_path):
    monkeypatch.setenv("PATHWAY_TRACE", "off")
    _sync_env(tracer)
    with tracer.trace_span("rest", "GET /off") as span:
        assert span is None
    assert tracer.start("barrier", "b") is None
    assert tracer.flush(str(tmp_path)) is None
    assert tracer.recent_spans() == []


def test_query_and_commit_link_registries_drain_once(tracer):
    q1, q2, c1 = new_trace_context(), new_trace_context(), new_trace_context()
    tracer.register_query_link("what is pathway", q1)
    tracer.register_query_link("what is pathway", q2)
    tracer.register_commit_link(c1)
    got = tracer.take_query_links(["what is pathway", "absent"])
    assert {g.span_id for g in got} == {q1.span_id, q2.span_id}
    assert tracer.take_query_links(["what is pathway"]) == []
    assert [c.span_id for c in tracer.take_commit_links()] == [c1.span_id]
    assert tracer.take_commit_links() == []


# -- flush / merge / critical path --------------------------------------------


def _flush_two_ranks(tracer, tmp_path, *, skew_s: float = 5.0):
    """One commit trace spread over two 'ranks' (same process, reconfigured
    tracer): rank 0 holds the commit root + a groupby child + the barrier
    span with straggler attribution; rank 1's sibling commit span is stamped
    with a deliberately skewed wall clock that only the heartbeat-estimated
    offset in rank 0's _meta can undo."""
    ctx0 = commit_trace_context(0, 12, rank=0)
    with tracer.trace_span("commit", "commit 12", self_ctx=ctx0) as root:
        root.ts, root.ts_mono = 1000.0, 100.0
        root.duration_s = 0.100
        tracer.record_span(
            "operator", "groupby:words", parent=root.context(),
            ts=1000.01, ts_mono=100.01, duration_s=0.078,
        )
        with tracer.trace_span("barrier", "barrier DELTA") as bar:
            bar.ts, bar.ts_mono = 1000.05, 100.05
            bar.duration_s = 0.041
            bar.attrs["straggler_rank"] = 3
            bar.attrs["straggler_wait_s"] = 0.041
    # rank 0 measured rank 1's wall clock as skew_s ahead
    tracer.set_clock_offsets({1: skew_s})
    path0 = tracer.flush(str(tmp_path), reason="test")
    assert path0 is not None and tracer.flushes == 1
    # rank 1: sibling commit span in the SAME trace, skewed wall clock
    tracer.reset()
    tracer.configure(rank=1)
    ctx1 = commit_trace_context(0, 12, rank=1)
    with tracer.trace_span("commit", "commit 12", self_ctx=ctx1) as sib:
        sib.ts, sib.ts_mono = 1000.02 + skew_s, 200.0
        sib.duration_s = 0.055
    path1 = tracer.flush(str(tmp_path), reason="test")
    tracer.reset()
    tracer.configure(rank=0)
    return path0, path1, ctx0


def test_flush_merge_aligns_clocks_and_names_critical_path(tracer, tmp_path):
    path0, path1, ctx0 = _flush_two_ranks(tracer, tmp_path, skew_s=5.0)
    meta0, spans0 = load_trace_file(path0)
    assert meta0["rank"] == 0 and meta0["clock_offsets"] == {"1": 5.0}
    assert len(spans0) == 3
    merged = merge_trace_files([path0, path1])
    assert merged["ranks"] == [0, 1]
    by_id = {s["span_id"]: s for s in merged["spans"]}
    sib = by_id[commit_trace_context(0, 12, rank=1).span_id]
    # the 5 s skew is undone: rank 1's span lands 20 ms after rank 0's root
    assert abs(sib["ts_adj"] - 1000.02) < 1e-6
    result = critical_path(merged, ctx0.trace_id)
    assert "commit 12" in result["line"]
    assert "78% in rank 0 groupby:words" in result["line"]
    assert "barrier held 41 ms by rank 3" in result["line"]
    tree = format_trace_tree(merged, ctx0.trace_id)
    assert any("operator groupby:words" in line for line in tree)
    # rank 1's sibling has no local parent span -> renders as its own root
    assert sum("commit commit 12" in line for line in tree) == 2
    # and the directory-level convenience the supervisor post-mortem uses
    assert "commit 12" in critical_path_line(str(tmp_path))


def test_merge_tolerates_torn_tail_and_flight_partials(tracer, tmp_path):
    path0, path1, ctx0 = _flush_two_ranks(tracer, tmp_path)
    with open(path1, "a") as f:
        f.write('{"span_id": "torn-mid-wri')  # rank killed mid-write
    flight = tmp_path / "flight-rank-2.json"
    killed = {
        "trace_id": ctx0.trace_id, "span_id": "f" * 16, "parent_id": None,
        "rank": 2, "epoch": 0, "kind": "commit", "name": "commit 12",
        "ts": 1000.03, "ts_mono": 1.0, "duration_s": 0.02, "attrs": {},
        "links": [],
    }
    flight.write_text(json.dumps({"trace": {"rank": 2, "spans": [killed]}}))
    assert load_flight_spans(str(flight)) == [killed]
    merged = merge_trace_files([path0, path1], [str(flight)])
    ids = {s["span_id"] for s in merged["spans"]}
    assert "f" * 16 in ids  # the chaos-killed rank still contributed
    assert not any(i.startswith("torn") for i in ids)


def test_flush_is_atomic_and_reentrant_under_held_lock(tracer, tmp_path):
    # the SIGTERM path: flush may run while the same thread already holds
    # the tracer lock (RLock) — and a failing directory never raises
    with tracer.trace_span("rest", "GET /crash"):
        pass
    with tracer._lock:
        path = tracer.flush(str(tmp_path), reason="sigterm")
    assert path is not None and os.path.exists(path)
    assert tracer.flush(str(tmp_path / "missing" / "nested")) is None


def test_trace_counters_ride_strict_openmetrics(tracer):
    from pathway_tpu.engine.http_server import ProberStats

    from .utils import validate_openmetrics

    with tracer.trace_span("rest", "GET /metrics-check"):
        pass
    text = ProberStats().to_openmetrics()
    families = validate_openmetrics(text)
    assert 'pathway_stage_total{stage="trace.span"}' in text
    samples = families["pathway_stage"]["samples"]
    stages = {labels.get("stage") for (_, labels, _) in samples}
    assert "trace.span" in stages
