"""Row transformer tests (mirrors reference tests/test_transformers.py patterns)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from tests.utils import T


def test_simple_transformer():
    class OutputSchema(pw.Schema):
        ret: int

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg, output=OutputSchema):
            arg = pw.input_attribute()

            @pw.output_attribute
            def ret(self) -> int:
                return self.arg + 1

    table = T(
        """
            | arg
        1   | 1
        2   | 2
        3   | 3
        """
    )
    ret = foo_transformer(table).table
    assert sorted(dbg.table_to_pandas(ret)["ret"]) == [2, 3, 4]


def test_aux_objects():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            const = 10

            def fun(self, a) -> int:
                return a * self.arg + self.const

            @staticmethod
            def sfun(b) -> int:
                return b * 100

            @pw.attribute
            def attr(self) -> int:
                return self.arg / 2

            @pw.output_attribute
            def ret(self) -> int:
                return self.arg + self.const + self.fun(1) + self.sfun(self.arg) + self.attr

    table = T(
        """
            | arg
        1   | 10
        2   | 20
        3   | 30
        """
    )
    ret = foo_transformer(table).table
    assert sorted(dbg.table_to_pandas(ret)["ret"]) == [1045, 2070, 3095]


def test_pointer_chasing_across_tables():
    @pw.transformer
    class list_traversal:
        class nodes(pw.ClassArg):
            next = pw.input_attribute()
            val = pw.input_attribute()

        class requests(pw.ClassArg):
            node = pw.input_attribute()
            steps = pw.input_attribute()

            @pw.output_attribute
            def reached_value(self) -> int:
                node = self.transformer.nodes[self.node]
                for _ in range(self.steps):
                    node = self.transformer.nodes[node.next]
                return node.val

    raw = T(
        """
            | val
        1   | 11
        2   | 12
        3   | 13
        """
    )
    keyed = raw.with_id_from(raw.val)
    # chain 11 -> 12 -> 13 (13 points at itself)
    chain = keyed.select(
        next=keyed.pointer_from(
            pw.apply_with_type(lambda v: min(v + 1, 13), int, keyed.val)
        ),
        val=keyed.val,
    )
    reqs_raw = T(
        """
            | node | steps
        10  | 11   | 2
        20  | 13   | 0
        """
    )
    reqs = reqs_raw.select(node=chain.pointer_from(reqs_raw.node), steps=reqs_raw.steps)
    out = list_traversal(chain, reqs).requests
    assert sorted(dbg.table_to_pandas(out)["reached_value"]) == [13, 13]


def test_output_attribute_rename():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            @pw.output_attribute(output_name="foo")
            def ret(self) -> int:
                return self.arg + 1

    table = T(
        """
            | arg
        1   | 1
        """
    )
    ret = foo_transformer(table).table
    assert ret.column_names() == ["foo"]
    assert list(dbg.table_to_pandas(ret)["foo"]) == [2]


def test_output_schema_validation_error():
    with pytest.raises(RuntimeError):

        class OutputSchema(pw.Schema):
            foo: int

        @pw.transformer
        class foo_transformer:
            class table(pw.ClassArg, output=OutputSchema):
                arg = pw.input_attribute()

                @pw.output_attribute(output_name="bar")
                def foo(self) -> int:
                    return self.arg + 1


def test_transformer_incremental_update():
    """New rows arriving later re-derive outputs incrementally (diffs only)."""

    @pw.transformer
    class inc:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            @pw.output_attribute
            def double(self) -> int:
                return self.arg * 2

    table = T(
        """
        arg | __time__
        1   | 0
        2   | 2
        3   | 4
        """
    )
    out = inc(table).table
    stream = dbg._capture_update_stream(out)  # runs the graph
    additions = [e for e in stream if e["__diff__"] == 1]
    assert sorted(e["double"] for e in additions) == [2, 4, 6]
    # no spurious retractions of unchanged rows
    assert all(e["__diff__"] == 1 for e in stream)


# -- AsyncTransformer loop-back semantics (reference _AsyncConnector:61-527) ------


def test_async_transformer_failed_table():
    import pathway_tpu as pw
    from tests.utils import capture_rows

    class OutSchema(pw.Schema):
        ret: int

    class Flaky(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            if value == 13:
                raise RuntimeError("boom")
            return {"ret": value + 1}

    t = pw.debug.table_from_rows(pw.schema_builder({"value": int}), [(1,), (13,), (3,)])
    tr = Flaky(input_table=t)
    ok = tr.successful
    bad = tr.failed
    got_ok = sorted(r["ret"] for r in capture_rows(ok))
    assert got_ok == [2, 4]
    import pathway_tpu.internals.parse_graph as pg

    pg.G.clear()
    t = pw.debug.table_from_rows(pw.schema_builder({"value": int}), [(1,), (13,), (3,)])
    tr = Flaky(input_table=t)
    bad_rows = capture_rows(tr.failed)
    assert len(bad_rows) == 1 and bad_rows[0]["ret"] is None


def test_async_transformer_instance_group_poisoning():
    """With instance grouping, one failure marks the whole (instance, time) group
    FAILURE (reference .failed contract)."""
    import pathway_tpu as pw
    from tests.utils import capture_rows

    class OutSchema(pw.Schema):
        ret: int

    class Flaky(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, grp) -> dict:
            if value == 2:
                raise RuntimeError("boom")
            return {"ret": value * 10}

    t = pw.debug.table_from_rows(
        pw.schema_builder({"value": int, "grp": int}),
        [(1, 0), (2, 0), (3, 1)],
    )
    tr = Flaky(input_table=t, instance=t.grp)
    finished = tr.finished
    rows = capture_rows(finished)
    by_status = {}
    for r in rows:
        by_status.setdefault(r["_async_status"], []).append(r["ret"])
    # group 0 wholly FAILURE (value=1 succeeded but shares the instance with the
    # failure); group 1 SUCCESS
    assert by_status.get("-FAILURE-", []) == [None, None]
    assert by_status.get("-SUCCESS-") == [30]


def test_async_transformer_with_options_retry():
    import pathway_tpu as pw
    from tests.utils import capture_rows

    class OutSchema(pw.Schema):
        ret: int

    attempts = {"n": 0}

    class Retrying(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            attempts["n"] += 1
            if attempts["n"] < 3:
                raise RuntimeError("transient")
            return {"ret": value}

    t = pw.debug.table_from_rows(pw.schema_builder({"value": int}), [(7,)])
    tr = Retrying(input_table=t).with_options(
        retry_strategy=pw.udfs.FixedDelayRetryStrategy(max_retries=5, delay_ms=1)
    )
    rows = capture_rows(tr.successful)
    assert rows == [{"ret": 7}] and attempts["n"] == 3


def test_gradual_broadcast_hysteresis():
    """Threshold drift re-emits only rows the band moved past (reference
    gradual_broadcast.rs hysteresis)."""
    import pathway_tpu as pw
    from tests.utils import T, capture_update_stream

    t = T(
        """
        name
        a
        b
        c
        d
        e
        f
        """
    )
    thr = T(
        """
        lower | value | upper | __time__
        0.0   | 0.5   | 1.0   | 0
        0.4   | 0.6   | 1.0   | 4
        """
    )
    res = t._gradual_broadcast(thr, thr.lower, thr.value, thr.upper)
    events = capture_update_stream(res)
    first = [e for e in events if e["__diff__"] == 1 and e["__time__"] == min(ev["__time__"] for ev in events)]
    assert len(first) == 6
    # after the band narrows to [0.4, 1.0], only rows whose apx fell below 0.4 move
    moved = [e for e in events if e["__time__"] > min(ev["__time__"] for ev in events)]
    retracted = [e for e in moved if e["__diff__"] == -1]
    for e in retracted:
        assert e["apx_value"] < 0.4  # rows inside the new band stayed put
    readded = [e for e in moved if e["__diff__"] == 1]
    for e in readded:
        assert 0.4 <= e["apx_value"] <= 1.0
    assert len(retracted) == len(readded) > 0
