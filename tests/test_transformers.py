"""Row transformer tests (mirrors reference tests/test_transformers.py patterns)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from tests.utils import T


def test_simple_transformer():
    class OutputSchema(pw.Schema):
        ret: int

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg, output=OutputSchema):
            arg = pw.input_attribute()

            @pw.output_attribute
            def ret(self) -> int:
                return self.arg + 1

    table = T(
        """
            | arg
        1   | 1
        2   | 2
        3   | 3
        """
    )
    ret = foo_transformer(table).table
    assert sorted(dbg.table_to_pandas(ret)["ret"]) == [2, 3, 4]


def test_aux_objects():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            const = 10

            def fun(self, a) -> int:
                return a * self.arg + self.const

            @staticmethod
            def sfun(b) -> int:
                return b * 100

            @pw.attribute
            def attr(self) -> int:
                return self.arg / 2

            @pw.output_attribute
            def ret(self) -> int:
                return self.arg + self.const + self.fun(1) + self.sfun(self.arg) + self.attr

    table = T(
        """
            | arg
        1   | 10
        2   | 20
        3   | 30
        """
    )
    ret = foo_transformer(table).table
    assert sorted(dbg.table_to_pandas(ret)["ret"]) == [1045, 2070, 3095]


def test_pointer_chasing_across_tables():
    @pw.transformer
    class list_traversal:
        class nodes(pw.ClassArg):
            next = pw.input_attribute()
            val = pw.input_attribute()

        class requests(pw.ClassArg):
            node = pw.input_attribute()
            steps = pw.input_attribute()

            @pw.output_attribute
            def reached_value(self) -> int:
                node = self.transformer.nodes[self.node]
                for _ in range(self.steps):
                    node = self.transformer.nodes[node.next]
                return node.val

    raw = T(
        """
            | val
        1   | 11
        2   | 12
        3   | 13
        """
    )
    keyed = raw.with_id_from(raw.val)
    # chain 11 -> 12 -> 13 (13 points at itself)
    chain = keyed.select(
        next=keyed.pointer_from(
            pw.apply_with_type(lambda v: min(v + 1, 13), int, keyed.val)
        ),
        val=keyed.val,
    )
    reqs_raw = T(
        """
            | node | steps
        10  | 11   | 2
        20  | 13   | 0
        """
    )
    reqs = reqs_raw.select(node=chain.pointer_from(reqs_raw.node), steps=reqs_raw.steps)
    out = list_traversal(chain, reqs).requests
    assert sorted(dbg.table_to_pandas(out)["reached_value"]) == [13, 13]


def test_output_attribute_rename():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            @pw.output_attribute(output_name="foo")
            def ret(self) -> int:
                return self.arg + 1

    table = T(
        """
            | arg
        1   | 1
        """
    )
    ret = foo_transformer(table).table
    assert ret.column_names() == ["foo"]
    assert list(dbg.table_to_pandas(ret)["foo"]) == [2]


def test_output_schema_validation_error():
    with pytest.raises(RuntimeError):

        class OutputSchema(pw.Schema):
            foo: int

        @pw.transformer
        class foo_transformer:
            class table(pw.ClassArg, output=OutputSchema):
                arg = pw.input_attribute()

                @pw.output_attribute(output_name="bar")
                def foo(self) -> int:
                    return self.arg + 1


def test_transformer_incremental_update():
    """New rows arriving later re-derive outputs incrementally (diffs only)."""

    @pw.transformer
    class inc:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            @pw.output_attribute
            def double(self) -> int:
                return self.arg * 2

    table = T(
        """
        arg | __time__
        1   | 0
        2   | 2
        3   | 4
        """
    )
    out = inc(table).table
    stream = dbg._capture_update_stream(out)  # runs the graph
    additions = [e for e in stream if e["__diff__"] == 1]
    assert sorted(e["double"] for e in additions) == [2, 4, 6]
    # no spurious retractions of unchanged rows
    assert all(e["__diff__"] == 1 for e in stream)
