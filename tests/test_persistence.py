"""Checkpoint/resume: input journal, replay, offset seek, crash recovery.

Mirrors the reference's persistence test surface: ``test_persistence.py`` unit level plus
the ``integration_tests/wordcount`` kill-and-restart rig (``base.py:320``) at small scale.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pathway_tpu as pw
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G


def _collect(table):
    rows = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = row
        else:
            rows.pop(key, None)

    pw.io.subscribe(table, on_change)
    return rows


def _build_static_pipeline():
    t = pw.debug.table_from_markdown(
        """
        word  | n
        cat   | 1
        dog   | 2
        cat   | 3
        """
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.sum(t.n))
    return _collect(counts)


def test_journal_replay_reproduces_state(tmp_path):
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(tmp_path / "pstore"))

    rows1 = _build_static_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    result1 = {tuple(sorted(r.items())) for r in rows1.values()}
    assert {dict(r)["word"] for r in result1} == {"cat", "dog"}

    # "restart": fresh graph + fresh runner over the same store — rows must come from
    # the journal (the static source is marked consumed by the restored offsets)
    G.clear()
    rows2 = _build_static_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    result2 = {tuple(sorted(r.items())) for r in rows2.values()}
    assert result2 == result1

    # journal only holds ONE copy of the input (no duplicate journaling on resume)
    from pathway_tpu.persistence.engine import PersistenceManager

    frames = PersistenceManager(cfg).load_journal(G._current.sig())
    total_rows = sum(len(d) for _, deltas, _ in frames for d in deltas.values())
    assert total_rows == 3


def test_streaming_resume_after_partial_run(tmp_path):
    """Simulated crash: stop mid-stream without finish(), resume, verify exact result."""

    class NumbersSubject:
        """Deterministically pushes 0..19; re-pushed events dedup via skip-count."""

        def run(self, source):
            for i in range(20):
                source.push({"v": i})

    def build():
        from pathway_tpu.engine.datasource import StreamingDataSource
        from pathway_tpu.internals import parse_graph as pg
        from pathway_tpu.internals.table import Table

        schema = pw.schema_builder({"v": int})
        source = StreamingDataSource(subject=NumbersSubject(), autocommit_ms=5)
        node = G.add_node(pg.InputNode(source=source, streaming=True, name="numbers"))
        t = Table(node, schema, name="numbers")
        total = t.reduce(total=pw.reducers.sum(t.v))
        return _collect(total)

    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(tmp_path / "ps"))

    rows1 = build()
    r1 = GraphRunner(G._current)
    r1.run(persistence_config=cfg, max_commits=3)  # stop early; finish() not called

    G.clear()
    rows2 = build()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert [r["total"] for r in rows2.values()] == [sum(range(20))]


def test_silent_replay_suppresses_sink_redelivery(tmp_path):
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(tmp_path / "ps"),
        persistence_mode="silent_replay",
    )
    rows1 = _build_static_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert len(rows1) == 2

    G.clear()
    rows2 = _build_static_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    # replayed history was not re-delivered to the sink, and no new data arrived
    assert rows2 == {}


_CRASH_SCRIPT = r"""
import os, sys
import pathway_tpu as pw

input_path, out_path, store = sys.argv[1], sys.argv[2], sys.argv[3]

class Sch(pw.Schema):
    word: str

t = pw.io.csv.read(input_path, schema=Sch, mode="streaming", autocommit_duration_ms=20)
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

import json
rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[repr(key)] = {k: int(v) if hasattr(v, "item") else v for k, v in row.items()}
    else:
        rows.pop(repr(key), None)
    with open(out_path + ".tmp", "w") as f:
        json.dump(list(rows.values()), f)
    os.replace(out_path + ".tmp", out_path)

pw.io.subscribe(counts, on_change)
cfg = pw.persistence.Config(
    pw.persistence.Backend.filesystem(store), snapshot_interval_ms=10
)
pw.run(persistence_config=cfg)
"""


def test_crash_kill_and_restart_wordcount(tmp_path):
    """The wordcount torture rig at small scale: kill -9 mid-run, restart, exact output."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    out_path = str(tmp_path / "out.json")
    store = str(tmp_path / "store")
    script = tmp_path / "prog.py"
    script.write_text(_CRASH_SCRIPT)

    (input_dir / "a.csv").write_text("word\n" + "\n".join(["cat"] * 5 + ["dog"] * 3) + "\n")

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, str(script), str(input_dir), out_path, store],
        env=env,
        cwd="/root/repo",
    )
    # wait for it to process the first file, then kill -9
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(out_path):
        time.sleep(0.1)
    assert os.path.exists(out_path), "pipeline never produced output"
    time.sleep(0.5)
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()

    # add more data while the pipeline is down
    (input_dir / "b.csv").write_text("word\n" + "\n".join(["cat"] * 2 + ["owl"] * 4) + "\n")

    # restart; it must resume (not double-count a.csv) and pick up b.csv
    proc = subprocess.Popen(
        [sys.executable, str(script), str(input_dir), out_path, store],
        env=env,
        cwd="/root/repo",
    )
    try:
        deadline = time.time() + 90
        expected = {"cat": 7, "dog": 3, "owl": 4}
        import json

        while time.time() < deadline:
            try:
                with open(out_path) as f:
                    rows = {r["word"]: r["total"] for r in json.load(f)}
            except Exception:
                rows = {}
            if rows == expected:
                break
            time.sleep(0.2)
        assert rows == expected, f"got {rows}, want {expected}"
    finally:
        proc.kill()
        proc.wait()


def _run_segmented(tmp_store, script, max_commits=None):
    """Build a pipeline over a scripted segment-pushing subject; return captured rows."""
    from pathway_tpu.engine.datasource import StreamingDataSource
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.internals.table import Table

    class ScriptedSubject:
        def __init__(self, steps):
            self.steps = steps
            self.folded = []

        def restore(self, state_deltas):
            self.folded = list(state_deltas)

        def run(self, source):
            for step in self.steps(self.folded):
                kind = step[0]
                if kind == "begin":
                    source.push_begin(step[1], step[2])
                elif kind == "row":
                    source.push(step[1], diff=step[2] if len(step) > 2 else 1)
                elif kind == "state":
                    source.push_state(step[1])
                elif kind == "barrier":
                    source.push_barrier()

    schema = pw.schema_builder({"v": int})
    subject = ScriptedSubject(script)
    source = StreamingDataSource(subject=subject, autocommit_ms=5)
    node = G.add_node(pg.InputNode(source=source, streaming=True, name="seg"))
    t = Table(node, schema, name="seg")
    rows = _collect(t)
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(tmp_store))
    GraphRunner(G._current).run(persistence_config=cfg, max_commits=max_commits)
    return rows


def test_segment_skip_on_unchanged_fingerprint(tmp_path):
    """Crash mid-segment; segment unchanged on resume → re-push deduped, no dupes."""
    store = tmp_path / "ps"

    def first_run(folded):
        yield ("begin", "fileA", "fp1")
        yield ("row", {"v": 1})
        yield ("state", {"file": "fileA"})
        yield ("begin", "fileB", "fp2")
        yield ("row", {"v": 10})
        yield ("row", {"v": 20})
        # crash before fileB's marker

    rows1 = _run_segmented(store, first_run, max_commits=30)
    assert sorted(r["v"] for r in rows1.values()) == [1, 10, 20]

    G.clear()

    def resume_run(folded):
        # subject deterministically re-pushes the unfinished segment
        assert folded == [{"file": "fileA"}]
        yield ("begin", "fileB", "fp2")
        yield ("row", {"v": 10})
        yield ("row", {"v": 20})
        yield ("row", {"v": 30})
        yield ("state", {"file": "fileB"})

    rows2 = _run_segmented(store, resume_run)
    assert sorted(r["v"] for r in rows2.values()) == [1, 10, 20, 30]


def test_segment_retract_on_changed_fingerprint(tmp_path):
    store = tmp_path / "ps"

    def first_run(folded):
        yield ("begin", "fileB", "fp_old")
        yield ("row", {"v": 10})
        yield ("row", {"v": 20})

    rows1 = _run_segmented(store, first_run, max_commits=30)
    assert sorted(r["v"] for r in rows1.values()) == [10, 20]

    G.clear()

    def resume_run(folded):
        # the segment changed while down: journaled 10/20 must be retracted
        yield ("begin", "fileB", "fp_new")
        yield ("row", {"v": 77})
        yield ("state", {"file": "fileB"})

    rows2 = _run_segmented(store, resume_run)
    assert sorted(r["v"] for r in rows2.values()) == [77]


def test_segment_vanished_barrier_retracts_tail(tmp_path):
    store = tmp_path / "ps"

    def first_run(folded):
        yield ("begin", "fileB", "fp")
        yield ("row", {"v": 10})

    _run_segmented(store, first_run, max_commits=30)

    G.clear()

    def resume_run(folded):
        # fileB is gone; a full scan pass without it must undo its journaled rows
        yield ("barrier",)

    rows2 = _run_segmented(store, resume_run)
    assert [r["v"] for r in rows2.values()] == []


def test_torn_journal_tail_is_truncated(tmp_path):
    store = tmp_path / "ps"
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))

    rows1 = _build_static_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert len(rows1) == 2

    # simulate a crash mid-frame-write: garbage tail bytes after the last valid frame
    journal = store / "journal.bin"
    with open(journal, "ab") as f:
        f.write(b"\x00\x00\x00\x00\x00\x00\x10\x00partialgarbage")

    G.clear()
    rows2 = _build_static_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    result2 = {tuple(sorted(r.items())) for r in rows2.values()}
    assert {dict(r)["word"] for r in result2} == {"cat", "dog"}

    # and the journal must be readable again on a third run (torn tail truncated)
    G.clear()
    rows3 = _build_static_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert {dict(tuple(sorted(r.items())))["word"] for r in rows3.values()} == {"cat", "dog"}


def test_fs_file_modified_while_down(tmp_path):
    """A fully-processed file modified during downtime is retracted and re-read."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    store = tmp_path / "ps"
    (input_dir / "a.csv").write_text("word\ncat\ncat\n")

    class Sch(pw.Schema):
        word: str

    def build():
        t = pw.io.csv.read(str(input_dir), schema=Sch, mode="static")
        counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
        return _collect(counts)

    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    rows1 = build()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert {r["word"]: r["total"] for r in rows1.values()} == {"cat": 2}

    time.sleep(0.05)
    (input_dir / "a.csv").write_text("word\nowl\nowl\nowl\n")
    os.utime(input_dir / "a.csv")

    G.clear()
    rows2 = build()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert {r["word"]: r["total"] for r in rows2.values()} == {"owl": 3}


def test_checkpoint_resume_and_journal_compaction(tmp_path):
    """Operator snapshots: state restored from checkpoint, journal compacted, sinks
    re-receive the restored state as a snapshot."""
    store = tmp_path / "ps"

    class NumbersSubject:
        def __init__(self, n):
            self.n = n

        def run(self, source):
            for i in range(self.n):
                source.push({"v": i})

    def build(n):
        from pathway_tpu.engine.datasource import StreamingDataSource
        from pathway_tpu.internals import parse_graph as pg
        from pathway_tpu.internals.table import Table

        schema = pw.schema_builder({"v": int})
        source = StreamingDataSource(subject=NumbersSubject(n), autocommit_ms=5)
        node = G.add_node(pg.InputNode(source=source, streaming=True, name="numbers"))
        t = Table(node, schema, name="numbers")
        total = t.reduce(total=pw.reducers.sum(t.v))
        return _collect(total)

    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(store), snapshot_interval_ms=1
    )
    rows1 = build(10)
    GraphRunner(G._current).run(persistence_config=cfg)
    assert [r["total"] for r in rows1.values()] == [sum(range(10))]
    assert (store / "checkpoint.pkl").exists()
    # compaction kept the journal small (some frames may follow the last checkpoint)
    journal_size_after_run1 = (store / "journal.bin").stat().st_size

    # resume: subject pushes 15 values now; first 10 journaled/checkpointed, deduped
    G.clear()
    rows2 = build(15)
    GraphRunner(G._current).run(persistence_config=cfg)
    assert [r["total"] for r in rows2.values()] == [sum(range(15))]
    assert journal_size_after_run1 < 10_000


def test_checkpoint_groupby_state_survives_compaction(tmp_path):
    """After compaction the journal no longer holds history; accumulators must come
    from the operator snapshot."""
    store = tmp_path / "ps"
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    (input_dir / "a.csv").write_text("word\ncat\ncat\ndog\n")

    class Sch(pw.Schema):
        word: str

    def build():
        t = pw.io.csv.read(str(input_dir), schema=Sch, mode="static")
        counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
        return _collect(counts)

    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(store), snapshot_interval_ms=1
    )
    rows1 = build()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert {r["word"]: r["total"] for r in rows1.values()} == {"cat": 2, "dog": 1}

    # new file while down; groupby must ADD to checkpointed accumulators
    (input_dir / "b.csv").write_text("word\ncat\nowl\n")

    G.clear()
    rows2 = build()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert {r["word"]: r["total"] for r in rows2.values()} == {
        "cat": 3,
        "dog": 1,
        "owl": 1,
    }


def test_double_crash_mid_segment_skip_width(tmp_path):
    """Crash, resume, crash again before the marker: the second resume must skip the
    full re-pushed prefix (regression: emitted restarted at 0 after an fp-matched
    resume, undercounting the skip)."""
    store = tmp_path / "ps"

    def run1(folded):
        yield ("begin", "fileB", "fp")
        yield ("row", {"v": 10})
        yield ("row", {"v": 20})

    _run_segmented(store, run1, max_commits=30)

    G.clear()

    def run2(folded):
        yield ("begin", "fileB", "fp")
        yield ("row", {"v": 10})
        yield ("row", {"v": 20})
        yield ("row", {"v": 30})
        # crash again before the marker

    _run_segmented(store, run2, max_commits=30)

    G.clear()

    def run3(folded):
        yield ("begin", "fileB", "fp")
        yield ("row", {"v": 10})
        yield ("row", {"v": 20})
        yield ("row", {"v": 30})
        yield ("row", {"v": 40})
        yield ("state", {"file": "fileB"})

    rows = _run_segmented(store, run3)
    assert sorted(r["v"] for r in rows.values()) == [10, 20, 30, 40]


def test_nondet_udf_memo_survives_checkpoint(tmp_path):
    """A deterministic=False UDF's replay memo rides operator snapshots: after a
    restore-from-checkpoint (journal compacted, history not re-run), a retraction
    of a pre-checkpoint row must replay the ORIGINAL value, not re-invoke."""
    store = tmp_path / "ps"
    calls = []

    def nondet(x: str) -> str:
        calls.append(x)
        return f"{x}#{len(calls)}"

    class Subject:
        def __init__(self, rows):
            self.rows = rows

        def run(self, source):
            from pathway_tpu.internals.keys import pointer_from

            for key, value, diff in self.rows:
                source.push({"k": value}, key=pointer_from(key), diff=diff)

    def build(rows):
        from pathway_tpu.engine.datasource import StreamingDataSource
        from pathway_tpu.internals import parse_graph as pg
        from pathway_tpu.internals.table import Table

        schema = pw.schema_builder({"k": str})
        source = StreamingDataSource(subject=Subject(rows), autocommit_ms=5)
        node = G.add_node(pg.InputNode(source=source, streaming=True, name="s"))
        t = Table(node, schema, name="s")
        udf = pw.udf(nondet, deterministic=False)
        res = t.select(t.k, v=udf(t.k))
        events = []
        pw.io.subscribe(
            res,
            on_batch=lambda keys, diffs, columns, time: events.extend(
                zip(columns["v"].tolist(), diffs.tolist())
            ),
        )
        return events

    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(store), snapshot_interval_ms=1
    )
    ev1 = build([("a", "a", 1), ("b", "b", 1)])
    GraphRunner(G._current).run(persistence_config=cfg)
    a_value = next(v for v, d in ev1 if d == 1 and v.startswith("a#"))
    assert (store / "checkpoint.pkl").exists()

    # restart: source replays its first two rows (deduped by the journal) and
    # then retracts "a" — the retraction must carry a_value verbatim
    G.clear()
    ev2 = build([("a", "a", 1), ("b", "b", 1), ("a", "a", -1)])
    GraphRunner(G._current).run(persistence_config=cfg)
    retractions = [v for v, d in ev2 if d < 0]
    assert retractions == [a_value]


# -- format versioning (PR 1 satellites) ---------------------------------------


def test_v1_journal_magic_refused(tmp_path):
    """A journal from the pre-splitmix build must fail LOUDLY: its stored row
    keys no longer match keys this build derives for the same values."""
    import os

    import pytest

    from pathway_tpu.persistence.engine import PersistenceManager

    store = tmp_path / "ps_v1"
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    mgr = PersistenceManager(cfg)
    os.makedirs(mgr.root, exist_ok=True)
    with open(os.path.join(str(mgr.root), "journal.bin"), "wb") as f:
        f.write(b"PWTPUJ1\nsome-graph-sig\n")
    with pytest.raises(ValueError, match="incompatible earlier build"):
        mgr.load_journal("some-graph-sig")


def test_worker_count_mismatch_refused(tmp_path):
    """A store written under -n 2 reopened single-process must raise instead of
    silently resuming from an empty root shard (the shard layout differs)."""
    from dataclasses import replace

    import pytest

    from pathway_tpu.internals import config as config_mod
    from pathway_tpu.persistence.engine import PersistenceManager

    store = tmp_path / "ps_workers"
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    base = config_mod.PathwayConfig.from_env()
    config_mod.set_thread_config(replace(base, processes=2, process_id=0))
    try:
        writer = PersistenceManager(cfg)
        writer.load_journal("sig")
        writer.open_for_append("sig")
        writer.close()
    finally:
        config_mod.set_thread_config(None)
    reader = PersistenceManager(cfg)  # single-process reopen
    with pytest.raises(ValueError, match="worker process"):
        reader.open_for_append("sig")


def test_same_worker_count_reopens_cleanly(tmp_path):
    """The guard must not fire on a faithful reopen."""
    from pathway_tpu.persistence.engine import PersistenceManager

    store = tmp_path / "ps_ok"
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    writer = PersistenceManager(cfg)
    writer.load_journal("sig")
    writer.open_for_append("sig")
    writer.record_commit(0, {}, {})
    writer.close()
    reader = PersistenceManager(cfg)
    frames = reader.load_journal("sig")
    reader.open_for_append("sig")
    reader.close()
    assert len(frames) == 1


def test_fs_state_markers_not_duplicated_in_journal(tmp_path):
    """TODO item fixed this PR: fs per-file state deltas used to carry the
    full row payload ALONGSIDE the same rows' input deltas in the same frame
    (~2x journal size). Markers are now slim (file, mtime, n_rows) and the
    restore path re-derives rows from the frames' input deltas — asserted
    both structurally (no ``rows`` key journaled) and by byte count (the
    journal stays close to one copy of the corpus, not two)."""
    import pickle

    from pathway_tpu.persistence.engine import PersistenceManager

    input_dir = tmp_path / "in"
    input_dir.mkdir()
    store = tmp_path / "ps"
    payload = "word\n" + "\n".join(f"word-{i:05d}-{'x' * 64}" for i in range(500))
    (input_dir / "a.csv").write_text(payload)

    class Sch(pw.Schema):
        word: str

    def build():
        t = pw.io.csv.read(str(input_dir), schema=Sch, mode="static")
        counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
        return _collect(counts)

    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    rows1 = build()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert len(rows1) == 500

    sig = G._current.sig()
    frames = PersistenceManager(cfg).load_journal(sig)
    markers = [
        d
        for _cid, _deltas, offs in frames
        for o in offs.values()
        for d in o.get("state_deltas", [])
    ]
    assert markers, "the fs completion marker must still be journaled"
    assert all("rows" not in d for d in markers), markers
    assert all(d.get("n_rows") == 500 for d in markers if not d.get("deleted"))

    # byte honesty: the journal holds ~one copy of the corpus. The OLD
    # behavior (marker carrying the rows) would add a second full copy —
    # simulate it from the journaled input deltas and assert the real journal
    # is well under journal+copy.
    journal_bytes = (store / "journal.bin").stat().st_size
    one_copy = sum(
        len(pickle.dumps({n: c[i] for n, c in d.columns.items()}))
        for _cid, deltas, _offs in frames
        for d in deltas.values()
        for i in range(len(d))
    )
    # measured ~1.04x one copy after the fix; the duplicated-rows behavior
    # was >= 2x by construction (rows in the delta AND in the marker)
    assert journal_bytes < 1.5 * one_copy, (journal_bytes, one_copy)

    # the resume path must rehydrate emitted rows well enough that a file
    # changed during downtime is retracted exactly (the behavioral half)
    time.sleep(0.05)
    (input_dir / "a.csv").write_text("word\nfresh\nfresh\n")
    os.utime(input_dir / "a.csv")
    G.clear()
    rows2 = build()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert {r["word"]: r["total"] for r in rows2.values()} == {"fresh": 2}
