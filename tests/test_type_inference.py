"""Static type inference over the expression AST and through windows.

Parity: reference ``internals/type_interpreter.py`` (686 LoC of dtype
propagation) — the inferred schema drives the engine's typed-column fast
paths, so windows/temporal outputs must not silently demote to ANY/object.
"""

from __future__ import annotations

import datetime

import numpy as np

import pathway_tpu as pw
import pathway_tpu.stdlib.temporal as temporal
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.type_interpreter import eval_type

from .utils import T


def _table():
    return pw.debug.table_from_rows(
        pw.schema_builder({"i": int, "f": float, "s": str, "b": bool}),
        [(1, 1.5, "x", True)],
    )


def test_arithmetic_and_comparison_dtypes():
    t = _table()
    assert eval_type(t.i + t.i) == dt.INT
    assert eval_type(t.i * t.f) == dt.FLOAT
    assert eval_type(t.i / t.i) == dt.FLOAT  # truediv always floats
    assert eval_type(t.i // t.i) == dt.INT
    assert eval_type(t.i > t.f) == dt.BOOL
    assert eval_type(t.s == t.s) == dt.BOOL
    assert eval_type(t.s + t.s) == dt.STR
    assert eval_type(~(t.i > 0)) == dt.BOOL
    assert eval_type((t.b & (t.i > 1))) == dt.BOOL


def test_ifelse_coalesce_and_optional():
    t = _table()
    assert eval_type(pw.if_else(t.b, t.i, t.i)) == dt.INT
    assert eval_type(pw.if_else(t.b, t.i, t.f)) in (dt.FLOAT, dt.ANY)
    assert eval_type(pw.coalesce(t.i, 0)) == dt.INT
    assert eval_type(pw.cast(float, t.i)) == dt.FLOAT
    tup = pw.make_tuple(t.i, t.s)
    got = eval_type(tup)
    assert isinstance(got, dt.Tuple_) and got.args == (dt.INT, dt.STR)
    assert eval_type(tup[0]) == dt.INT
    assert eval_type(tup[1]) == dt.STR


def test_select_propagates_inferred_schema():
    t = _table()
    out = t.select(a=t.i + 1, b=t.f * 2.0, c=t.i > 3, d=t.s + "!")
    cols = out._schema.columns()
    assert cols["a"].dtype == dt.INT
    assert cols["b"].dtype == dt.FLOAT
    assert cols["c"].dtype == dt.BOOL
    assert cols["d"].dtype == dt.STR


def test_tumbling_window_columns_typed_int():
    t = T(
        """
        t  | v
        1  | 10
        12 | 30
        """
    )
    w = t.windowby(t.t, window=temporal.tumbling(duration=10)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        s=pw.reducers.sum(pw.this.v),
    )
    cols = w._schema.columns()
    assert cols["start"].dtype == dt.INT, cols["start"].dtype
    assert cols["end"].dtype == dt.INT
    # the materialized output is a TYPED array, not object dtype
    df = pw.debug.table_to_pandas(w)
    assert df["start"].dtype.kind in "i", df["start"].dtype
    assert sorted(df["start"]) == [0, 10]


def test_sliding_window_columns_typed_through_flatten():
    t = T(
        """
        t  | v
        4  | 10
        """
    )
    w = t.windowby(t.t, window=temporal.sliding(hop=2, duration=6)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    cols = w._schema.columns()
    assert cols["start"].dtype == dt.INT, cols["start"].dtype
    assert cols["end"].dtype == dt.INT
    df = pw.debug.table_to_pandas(w)
    assert df["start"].dtype.kind in "i"
    assert sorted(df["start"]) == [0, 2, 4]


def test_session_window_columns_typed():
    t = T(
        """
        t   | v
        1   | 1
        2   | 1
        30  | 1
        """
    )
    w = t.windowby(t.t, window=temporal.session(max_gap=5)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    cols = w._schema.columns()
    assert cols["start"].dtype == dt.INT, cols["start"].dtype
    assert cols["end"].dtype == dt.INT
    df = pw.debug.table_to_pandas(w)
    assert df["start"].dtype.kind in "i"
    assert sorted(zip(df["start"], df["end"])) == [(1, 2), (30, 30)]


def test_datetime_window_columns_typed():
    base = datetime.datetime(2025, 1, 1)
    t = pw.debug.table_from_rows(
        pw.schema_builder({"ts": dt.DATE_TIME_NAIVE, "v": int}),
        [(base + datetime.timedelta(minutes=m), m) for m in (0, 5, 25)],
    )
    w = t.windowby(
        t.ts, window=temporal.tumbling(duration=datetime.timedelta(minutes=10))
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    assert w._schema.columns()["start"].dtype == dt.DATE_TIME_NAIVE
    df = pw.debug.table_to_pandas(w)
    assert sorted(df["start"]) == [base, base + datetime.timedelta(minutes=20)]
