"""Kafka + S3 connectors against injected fake clients (VERDICT r2 item 6: real
client code paths, unit-tested with fakes — reference ``data_storage.rs:692,1258``,
``scanner/s3.rs``)."""

from __future__ import annotations

import json

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg

from .utils import capture_rows


# -- fakes ------------------------------------------------------------------------


class FakeKafkaError:
    def __init__(self, code: str):
        self._code = code

    def code(self):
        return self._code


class FakeMessage:
    def __init__(self, topic, partition, offset, value, key=None, error=None):
        self._topic, self._partition, self._offset = topic, partition, offset
        self._value, self._key, self._error = value, key, error

    def topic(self):
        return self._topic

    def partition(self):
        return self._partition

    def offset(self):
        return self._offset

    def value(self):
        return self._value

    def key(self):
        return self._key

    def error(self):
        return self._error


class FakeConsumer:
    """confluent_kafka.Consumer surface: poll/subscribe/assign/commit/close."""

    def __init__(self, messages):
        self._queue = list(messages)
        self.subscribed: list = []
        self.assigned: list = []
        self.commits = 0
        self.closed = False

    def subscribe(self, topics):
        self.subscribed = list(topics)

    def assign(self, partitions):
        self.assigned = list(partitions)

    def assignment(self):
        parts = {(m.topic(), m.partition()) for m in self._queue} or {("t", 0)}
        return list(parts)

    def poll(self, timeout):
        if self._queue:
            return self._queue.pop(0)
        return None

    def commit(self, asynchronous=True):
        self.commits += 1

    def close(self):
        self.closed = True


class FakeProducer:
    def __init__(self):
        self.produced: list = []
        self.flushed = 0

    def produce(self, topic, value=None, key=None):
        self.produced.append((topic, key, value))

    def poll(self, timeout):
        return 0

    def flush(self):
        self.flushed += 1


class FakeS3Body:
    def __init__(self, data: bytes):
        self._data = data

    def read(self):
        return self._data


class FakeS3Client:
    """boto3 S3 client surface: list_objects_v2/get_object/put_object."""

    def __init__(self, objects: dict[str, bytes], page_size: int = 2):
        self.objects = dict(objects)
        self.page_size = page_size
        self.puts: list = []

    def list_objects_v2(self, Bucket, Prefix, ContinuationToken=None):
        keys = sorted(k for k in self.objects if k.startswith(Prefix))
        start = int(ContinuationToken) if ContinuationToken else 0
        page = keys[start : start + self.page_size]
        truncated = start + self.page_size < len(keys)
        return {
            "Contents": [
                {"Key": k, "ETag": f"etag-{hash(self.objects[k])}", "Size": len(self.objects[k])}
                for k in page
            ],
            "IsTruncated": truncated,
            "NextContinuationToken": str(start + self.page_size),
        }

    def get_object(self, Bucket, Key):
        return {"Body": FakeS3Body(self.objects[Key])}

    def put_object(self, Bucket, Key, Body):
        self.objects[Key] = Body
        self.puts.append((Bucket, Key, Body))


def _eof(topic, partition):
    return FakeMessage(topic, partition, -1, None, error=FakeKafkaError("_PARTITION_EOF"))


# -- kafka read -------------------------------------------------------------------


def test_kafka_read_json():
    pg.G.clear()
    msgs = [
        FakeMessage("orders", 0, 0, json.dumps({"item": "ham", "qty": 2}).encode()),
        FakeMessage("orders", 0, 1, json.dumps({"item": "eggs", "qty": 12}).encode()),
        FakeMessage("orders", 1, 0, json.dumps({"item": "jam", "qty": 1}).encode()),
        _eof("orders", 0),
        _eof("orders", 1),
    ]
    consumer = FakeConsumer(msgs)
    t = pw.io.kafka.read(
        {"bootstrap.servers": "fake:9092", "group.id": "g"},
        topic="orders",
        schema=pw.schema_builder({"item": str, "qty": int}),
        format="json",
        mode="static",
        _consumer_factory=lambda settings: consumer,
    )
    rows = sorted(
        ((r["item"], r["qty"]) for r in capture_rows(t)), key=repr
    )
    assert rows == sorted([("ham", 2), ("eggs", 12), ("jam", 1)], key=repr)
    assert consumer.subscribed == ["orders"]
    assert consumer.closed


def test_kafka_read_raw_with_metadata():
    pg.G.clear()
    msgs = [
        FakeMessage("t", 0, 7, b"payload", key=b"k1"),
        _eof("t", 0),
    ]
    t = pw.io.kafka.read(
        {"bootstrap.servers": "fake:9092"},
        topic="t",
        format="raw",
        mode="static",
        with_metadata=True,
        _consumer_factory=lambda s: FakeConsumer(msgs),
    )
    rows = capture_rows(t)
    assert rows[0]["data"] == b"payload"
    meta = rows[0]["_metadata"].value
    assert (meta["topic"], meta["partition"], meta["offset"], meta["key"]) == ("t", 0, 7, "k1")


def test_kafka_offsets_restore_seeks():
    """A restored subject assigns consumer positions from the checkpointed offsets."""
    from pathway_tpu.io.kafka import _KafkaSubject

    consumer = FakeConsumer([_eof("t", 0)])
    subject = _KafkaSubject(
        lambda s: consumer, {}, ["t"], "raw", None, False, mode="static"
    )
    subject.restore(
        [{"topic": "t", "partition": 0, "next_offset": 42},
         {"topic": "t", "partition": 1, "next_offset": 7}]
    )
    folded = subject.fold_state_deltas(
        [{"topic": "t", "partition": 0, "next_offset": 41},
         {"topic": "t", "partition": 0, "next_offset": 42}]
    )
    assert folded == [{"topic": "t", "partition": 0, "next_offset": 42}]

    class Src:  # minimal source stub: subject must not push anything here
        def push(self, *a, **k):
            raise AssertionError("no data expected")

        def push_state(self, *a, **k):
            pass

    subject.run(Src())
    assert sorted(subject.offsets.items()) == [(("t", 0), 42), (("t", 1), 7)]
    assert sorted(consumer.assigned) == [("t", 0, 42), ("t", 1, 7)]


def test_kafka_write_json_update_stream():
    pg.G.clear()
    producer = FakeProducer()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"word": str, "n": int}), [("a", 1), ("b", 2)]
    )
    pw.io.kafka.write(
        t,
        {"bootstrap.servers": "fake:9092"},
        topic_name="out",
        key=t.word,
        _producer_factory=lambda s: producer,
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert producer.flushed == 1
    got = sorted(
        (topic, key, json.loads(value)) for topic, key, value in producer.produced
    )
    assert [(t_, k, (v["word"], v["n"], v["diff"])) for t_, k, v in got] == [
        ("out", b"a", ("a", 1, 1)),
        ("out", b"b", ("b", 2, 1)),
    ]


def test_kafka_missing_client_raises():
    pg.G.clear()
    with pytest.raises(ImportError, match="confluent_kafka"):
        pw.io.kafka.read({"bootstrap.servers": "x"}, topic="t", format="raw", mode="static")


# -- s3 ---------------------------------------------------------------------------


def test_s3_read_jsonlines_paginated():
    pg.G.clear()
    client = FakeS3Client(
        {
            "data/a.jsonl": b'{"v": 1}\n{"v": 2}\n',
            "data/b.jsonl": b'{"v": 3}\n',
            "data/c.jsonl": b'{"v": 4}\n',
            "other/x.jsonl": b'{"v": 99}\n',
        },
        page_size=2,  # forces list_objects_v2 pagination
    )
    t = pw.io.s3.read(
        "s3://bucket/data/",
        format="json",
        schema=pw.schema_builder({"v": int}),
        mode="static",
        _client_factory=lambda settings: client,
    )
    assert sorted(r["v"] for r in capture_rows(t)) == [1, 2, 3, 4]


def test_s3_read_plaintext_with_metadata():
    pg.G.clear()
    client = FakeS3Client({"logs/one.txt": b"hello\nworld\n"})
    t = pw.io.s3.read(
        "s3://bucket/logs/",
        format="plaintext",
        mode="static",
        with_metadata=True,
        _client_factory=lambda settings: client,
    )
    rows = capture_rows(t)
    assert sorted(r["data"] for r in rows) == ["hello", "world"]
    assert rows[0]["_metadata"].value["path"] == "s3://bucket/logs/one.txt"


def test_s3_streaming_change_retracts_and_replaces():
    """Changed ETag retracts the old rows and emits the new ones (update stream)."""
    pg.G.clear()
    client = FakeS3Client({"d/a.jsonl": b'{"v": 1}\n'})
    t = pw.io.s3.read(
        "s3://bucket/d/",
        format="json",
        schema=pw.schema_builder({"v": int}),
        mode="streaming",
        autocommit_duration_ms=10,
        _client_factory=lambda settings: client,
    )
    got: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            got[row["v"]] = got.get(row["v"], 0) + 1
        else:
            got[row["v"]] = got.get(row["v"], 0) - 1

    pw.io.subscribe(t, on_change)
    from pathway_tpu.engine.runner import GraphRunner
    import threading, time as time_mod

    runner = GraphRunner(pg.G._current)

    def change_later():
        time_mod.sleep(1.2)
        client.objects["d/a.jsonl"] = b'{"v": 5}\n{"v": 6}\n'
        time_mod.sleep(1.6)
        runner._stop_requested = True

    threading.Thread(target=change_later, daemon=True).start()
    runner.setup(monitoring_level=None)
    deadline = time_mod.monotonic() + 12
    while time_mod.monotonic() < deadline:
        runner.step()
        live = {v for v, c in got.items() if c > 0}
        if live == {5, 6}:
            break
        time_mod.sleep(0.02)
    live = {v for v, c in got.items() if c > 0}
    assert live == {5, 6}, got


def test_s3_write_parts():
    pg.G.clear()
    client = FakeS3Client({})
    t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,), (2,)])
    pw.io.s3.write(
        t, "s3://bucket/out", _client_factory=lambda settings: client
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(client.puts) == 1
    bucket, key, body = client.puts[0]
    assert bucket == "bucket" and key.startswith("out/part-")
    recs = [json.loads(l) for l in body.decode().splitlines()]
    assert sorted(r["v"] for r in recs) == [1, 2]
    assert all(r["diff"] == 1 for r in recs)


def test_s3_missing_client_raises():
    pg.G.clear()
    with pytest.raises(ImportError, match="boto3"):
        pw.io.s3.read("s3://bucket/x", format="plaintext", mode="static")
