"""Debezium-over-Kafka (offset seek, upsert semantics) and cross-graph
ExportedTable handoff (VERDICT r2 §2.1: 'no debezium seek', 'no ExportedTable
cross-graph handoff' — reference ``data_format.rs:1053``, ``graph.rs:630``)."""

from __future__ import annotations

import json
import threading
import time as time_mod

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg

from .test_kafka_s3 import FakeConsumer, FakeKafkaError, FakeMessage


def _envelope(op, before=None, after=None):
    return json.dumps(
        {"payload": {"op": op, "before": before, "after": after}}
    ).encode()


class Sch(pw.Schema):
    id: int = pw.column_definition(primary_key=True)
    name: str


def test_debezium_read_upserts_by_primary_key():
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "a"})),
        FakeMessage("cdc", 0, 1, _envelope("c", after={"id": 2, "name": "b"})),
        FakeMessage("cdc", 0, 2, _envelope("u", before={"id": 1, "name": "a"}, after={"id": 1, "name": "a2"})),
        FakeMessage("cdc", 0, 3, _envelope("d", before={"id": 2, "name": "b"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    state = {}
    pw.io.subscribe(
        t,
        lambda key, row, time, is_addition: (
            state.__setitem__(key, row) if is_addition else state.pop(key, None)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    rows = sorted((r["id"], r["name"]) for r in state.values())
    assert rows == [(1, "a2")]  # id=1 updated in place, id=2 deleted


def test_debezium_read_checkpoints_offsets():
    """Offsets ride segment state exactly like the raw kafka reader."""
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "x"})),
        FakeMessage("cdc", 0, 1, _envelope("c", after={"id": 2, "name": "y"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    pw.io.subscribe(t, lambda *a, **kw: None)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    node = next(n for n in pg.G._current.nodes if n.kind == "input")
    subject = node.config["source"].subject
    # consumed through offset 1 -> next poll resumes at 2 (the seek position)
    assert subject.offsets[("cdc", 0)] == 2
    folded = subject.fold_state_deltas(
        node.config["source"].checkpoint_state_deltas() or []
    )
    assert any(
        d.get("topic") == "cdc" and d.get("next_offset") == 2 for d in folded
    )


def test_export_import_cross_graph_handoff():
    """Graph A (background) exports; graph B imports snapshot + live updates."""
    pg.G.clear()
    rows = [
        ("a", 1, 0, 1),
        ("b", 2, 2, 1),
        ("a", 1, 4, -1),  # retraction must propagate into the importing graph
        ("c", 3, 4, 1),
    ]
    src = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "v": int}), rows, is_stream=True
    )
    exported = pw.io.export_table(src)
    graph_a = pg.G._current

    from pathway_tpu.engine.runner import GraphRunner

    ta = threading.Thread(
        target=lambda: GraphRunner(graph_a).run(
            monitoring_level=pw.MonitoringLevel.NONE
        )
    )
    ta.start()
    ta.join(timeout=30)
    assert not ta.is_alive()
    assert exported.frontier() >= 0
    snap = exported.snapshot_at(exported.frontier())
    assert sorted((r["k"], r["v"]) for _p, r in snap) == [("b", 2), ("c", 3)]

    # importing graph: mounts the finished export (snapshot then stream end)
    pg.G.clear()
    imported = pw.io.import_table(exported)
    total = imported.reduce(s=pw.reducers.sum(pw.this.v))
    got = []
    pw.io.subscribe(
        total,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["s"].tolist(), diffs.tolist())
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    live = [v for v, d in got if d > 0][-1]
    assert live == 5  # b + c

    # original row keys preserved across the handoff
    keys_a = {repr(p) for p, _r in snap}
    pg.G.clear()
    imported2 = pw.io.import_table(exported)
    seen_keys = set()
    pw.io.subscribe(
        imported2,
        lambda key, row, time, is_addition: seen_keys.add(repr(key)),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert keys_a <= seen_keys


def test_export_live_streaming_updates():
    """An importer subscribed BEFORE the exporter finishes sees live deltas."""
    pg.G.clear()
    src = pw.debug.table_from_rows(
        pw.schema_builder({"v": int}),
        [(1, 0, 1), (2, 2, 1), (3, 4, 1)],
        is_stream=True,
    )
    exported = pw.io.export_table(src)
    graph_a = pg.G._current

    events = []
    done = threading.Event()

    def listener(batch, time):
        if batch is None:
            done.set()
        else:
            events.extend(batch)

    exported.subscribe(listener)

    from pathway_tpu.engine.runner import GraphRunner

    GraphRunner(graph_a).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert done.wait(timeout=10)
    assert sorted(r["v"] for _p, r, d in events if d > 0) == [1, 2, 3]


def test_debezium_update_with_null_before_keys_by_after_pk():
    """Postgres REPLICA IDENTITY DEFAULT ships before=null on updates: the
    retraction must still key by the pk from `after` (review finding)."""
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "a"})),
        FakeMessage("cdc", 0, 1, _envelope("u", before=None, after={"id": 1, "name": "a2"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    state = {}
    pw.io.subscribe(
        t,
        lambda key, row, time, is_addition: (
            state.__setitem__(key, row) if is_addition else state.pop(key, None)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    rows = [(r["id"], r["name"]) for r in state.values()]
    assert rows == [(1, "a2")]  # single live row, updated in place


def test_export_failure_propagates_to_importer():
    """A failing exporting graph must NOT look like a clean close to importers."""
    import pytest

    pg.G.clear()
    src = pw.debug.table_from_rows(
        pw.schema_builder({"v": int}), [(1, 0, 1), (2, 2, 1)], is_stream=True
    )
    def boom(x: int) -> int:
        raise RuntimeError("exporter exploded")
    bad = src.select(v=pw.udf(boom)(pw.this.v))
    exported = pw.io.export_table(bad)
    graph_a = pg.G._current

    from pathway_tpu.engine.runner import GraphRunner

    with pytest.raises(Exception, match="exporter exploded"):
        GraphRunner(graph_a).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert exported.failed()

    pg.G.clear()
    imported = pw.io.import_table(exported)
    pw.io.subscribe(imported, lambda *a, **kw: None)
    with pytest.raises(Exception, match="exporting graph failed"):
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)


def test_debezium_null_before_retracts_original_values():
    """Review-confirmed repro: a null-before update must retract the VALUES that
    were originally inserted (upsert-session cache), or value-based downstream
    state corrupts — groupby on `name` must end with {'a2': 1}, not {'a': 1,
    'a2': 1} plus a phantom all-None row."""
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "a"})),
        FakeMessage("cdc", 0, 1, _envelope("u", before=None, after={"id": 1, "name": "a2"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    by_name = t.groupby(t.name).reduce(t.name, cnt=pw.reducers.count())
    state = {}
    pw.io.subscribe(
        by_name,
        lambda key, row, time, is_addition: (
            state.__setitem__(row["name"], row["cnt"])
            if is_addition
            else state.pop(row["name"], None)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert state == {"a2": 1}


def test_debezium_upsert_cache_survives_fold_restore():
    """The last-values cache rides offset markers: fold + restore rebuilds it so
    a post-resume null-before update still resolves the retracted values."""
    from pathway_tpu.io.debezium import read as dbz_read

    msgs1 = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "x"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = dbz_read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs1),
    )
    pw.io.subscribe(t, lambda *a, **kw: None)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    node = next(n for n in pg.G._current.nodes if n.kind == "input")
    subject = node.config["source"].subject
    deltas = node.config["source"].checkpoint_state_deltas() or []
    folded = type(subject).fold_state_deltas(deltas)
    assert any((d.get("upserts") or {}).get((1,)) == {"id": 1, "name": "x"} for d in folded)

    # fresh subject restores the cache and resolves a null-before retraction
    pg.G.clear()
    msgs2 = [
        FakeMessage("cdc", 0, 1, _envelope("u", before=None, after={"id": 1, "name": "x2"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    t2 = dbz_read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs2),
    )
    node2 = next(n for n in pg.G._current.nodes if n.kind == "input")
    node2.config["source"].subject.restore(folded)
    assert node2.config["source"].subject.offsets[("cdc", 0)] == 1
    events = []
    pw.io.subscribe(
        t2,
        lambda key, row, time, is_addition: events.append(
            (row["name"], 1 if is_addition else -1)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert ("x", -1) in events and ("x2", 1) in events


def test_export_listener_may_reenter_public_api():
    """Listeners run under the export lock but the lock is reentrant: calling
    frontier()/snapshot_at() from inside a listener must not deadlock."""
    pg.G.clear()
    src = pw.debug.table_from_rows(
        pw.schema_builder({"v": int}), [(1, 0, 1), (2, 2, 1)], is_stream=True
    )
    exported = pw.io.export_table(src)
    frontiers = []

    def listener(batch, time):
        frontiers.append(exported.frontier())  # re-entrant call under the lock

    exported.subscribe(listener)
    from pathway_tpu.engine.runner import GraphRunner

    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(frontiers) >= 2


def test_debezium_pk_only_before_delete_retracts_cached_values():
    """REPLICA IDENTITY DEFAULT ships deletes with pk-only before images; the
    retraction must carry the CACHED full values, not {pk, None...}."""
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "a"})),
        FakeMessage("cdc", 0, 1, _envelope("d", before={"id": 1})),  # name absent
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    by_name = t.groupby(t.name).reduce(t.name, cnt=pw.reducers.count())
    state = {}
    pw.io.subscribe(
        by_name,
        lambda key, row, time, is_addition: (
            state.__setitem__(row["name"], row["cnt"])
            if is_addition
            else state.pop(row["name"], None)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert state == {}  # the 'a' group fully retracted; no phantom None group


def test_export_reentrant_subscribe_no_double_delivery():
    """A listener subscribing ANOTHER listener mid-batch must not double-deliver
    the in-flight batch to the newcomer (snapshot already includes it)."""
    pg.G.clear()
    src = pw.debug.table_from_rows(
        pw.schema_builder({"v": int}), [(1, 0, 1), (2, 2, 1)], is_stream=True
    )
    exported = pw.io.export_table(src)
    second_events = []

    def second(batch, time):
        if batch is not None:
            second_events.extend(batch)

    subscribed = []

    def first(batch, time):
        if batch is not None and not subscribed:
            subscribed.append(True)
            exported.subscribe(second)

    exported.subscribe(first)
    from pathway_tpu.engine.runner import GraphRunner

    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    # each row delivered exactly once to the late subscriber
    vals = sorted(r["v"] for _p, r, d in second_events if d > 0)
    assert vals == [1, 2]


def test_export_snapshot_future_frontier_in_listener_raises():
    import pytest

    pg.G.clear()
    src = pw.debug.table_from_rows(
        pw.schema_builder({"v": int}), [(1, 0, 1)], is_stream=True
    )
    exported = pw.io.export_table(src)
    caught = []

    def listener(batch, time):
        if batch is not None:
            try:
                exported.snapshot_at(time + 1000)
            except RuntimeError as exc:
                caught.append(str(exc))

    exported.subscribe(listener)
    from pathway_tpu.engine.runner import GraphRunner

    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert caught and "deadlock" in caught[0]
