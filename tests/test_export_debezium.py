"""Debezium-over-Kafka (offset seek, upsert semantics) and cross-graph
ExportedTable handoff (VERDICT r2 §2.1: 'no debezium seek', 'no ExportedTable
cross-graph handoff' — reference ``data_format.rs:1053``, ``graph.rs:630``)."""

from __future__ import annotations

import json
import threading
import time as time_mod

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg

from .test_kafka_s3 import FakeConsumer, FakeKafkaError, FakeMessage


def _envelope(op, before=None, after=None):
    return json.dumps(
        {"payload": {"op": op, "before": before, "after": after}}
    ).encode()


class Sch(pw.Schema):
    id: int = pw.column_definition(primary_key=True)
    name: str


def test_debezium_read_upserts_by_primary_key():
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "a"})),
        FakeMessage("cdc", 0, 1, _envelope("c", after={"id": 2, "name": "b"})),
        FakeMessage("cdc", 0, 2, _envelope("u", before={"id": 1, "name": "a"}, after={"id": 1, "name": "a2"})),
        FakeMessage("cdc", 0, 3, _envelope("d", before={"id": 2, "name": "b"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    state = {}
    pw.io.subscribe(
        t,
        lambda key, row, time, is_addition: (
            state.__setitem__(key, row) if is_addition else state.pop(key, None)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    rows = sorted((r["id"], r["name"]) for r in state.values())
    assert rows == [(1, "a2")]  # id=1 updated in place, id=2 deleted


def test_debezium_read_checkpoints_offsets():
    """Offsets ride segment state exactly like the raw kafka reader."""
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "x"})),
        FakeMessage("cdc", 0, 1, _envelope("c", after={"id": 2, "name": "y"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    pw.io.subscribe(t, lambda *a, **kw: None)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    node = next(n for n in pg.G._current.nodes if n.kind == "input")
    subject = node.config["source"].subject
    # consumed through offset 1 -> next poll resumes at 2 (the seek position)
    assert subject.offsets[("cdc", 0)] == 2
    folded = subject.fold_state_deltas(
        node.config["source"].checkpoint_state_deltas() or []
    )
    assert {"topic": "cdc", "partition": 0, "next_offset": 2} in folded


def test_export_import_cross_graph_handoff():
    """Graph A (background) exports; graph B imports snapshot + live updates."""
    pg.G.clear()
    rows = [
        ("a", 1, 0, 1),
        ("b", 2, 2, 1),
        ("a", 1, 4, -1),  # retraction must propagate into the importing graph
        ("c", 3, 4, 1),
    ]
    src = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "v": int}), rows, is_stream=True
    )
    exported = pw.io.export_table(src)
    graph_a = pg.G._current

    from pathway_tpu.engine.runner import GraphRunner

    ta = threading.Thread(
        target=lambda: GraphRunner(graph_a).run(
            monitoring_level=pw.MonitoringLevel.NONE
        )
    )
    ta.start()
    ta.join(timeout=30)
    assert not ta.is_alive()
    assert exported.frontier() >= 0
    snap = exported.snapshot_at(exported.frontier())
    assert sorted((r["k"], r["v"]) for _p, r in snap) == [("b", 2), ("c", 3)]

    # importing graph: mounts the finished export (snapshot then stream end)
    pg.G.clear()
    imported = pw.io.import_table(exported)
    total = imported.reduce(s=pw.reducers.sum(pw.this.v))
    got = []
    pw.io.subscribe(
        total,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["s"].tolist(), diffs.tolist())
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    live = [v for v, d in got if d > 0][-1]
    assert live == 5  # b + c

    # original row keys preserved across the handoff
    keys_a = {repr(p) for p, _r in snap}
    pg.G.clear()
    imported2 = pw.io.import_table(exported)
    seen_keys = set()
    pw.io.subscribe(
        imported2,
        lambda key, row, time, is_addition: seen_keys.add(repr(key)),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert keys_a <= seen_keys


def test_export_live_streaming_updates():
    """An importer subscribed BEFORE the exporter finishes sees live deltas."""
    pg.G.clear()
    src = pw.debug.table_from_rows(
        pw.schema_builder({"v": int}),
        [(1, 0, 1), (2, 2, 1), (3, 4, 1)],
        is_stream=True,
    )
    exported = pw.io.export_table(src)
    graph_a = pg.G._current

    events = []
    done = threading.Event()

    def listener(batch, time):
        if batch is None:
            done.set()
        else:
            events.extend(batch)

    exported.subscribe(listener)

    from pathway_tpu.engine.runner import GraphRunner

    GraphRunner(graph_a).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert done.wait(timeout=10)
    assert sorted(r["v"] for _p, r, d in events if d > 0) == [1, 2, 3]


def test_debezium_update_with_null_before_keys_by_after_pk():
    """Postgres REPLICA IDENTITY DEFAULT ships before=null on updates: the
    retraction must still key by the pk from `after` (review finding)."""
    msgs = [
        FakeMessage("cdc", 0, 0, _envelope("c", after={"id": 1, "name": "a"})),
        FakeMessage("cdc", 0, 1, _envelope("u", before=None, after={"id": 1, "name": "a2"})),
        FakeMessage("cdc", 0, -1, None, error=FakeKafkaError("_PARTITION_EOF")),
    ]
    pg.G.clear()
    t = pw.io.debezium.read(
        {"bootstrap.servers": "fake"},
        topic_name="cdc",
        schema=Sch,
        mode="static",
        _consumer_factory=lambda settings: FakeConsumer(msgs),
    )
    state = {}
    pw.io.subscribe(
        t,
        lambda key, row, time, is_addition: (
            state.__setitem__(key, row) if is_addition else state.pop(key, None)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    rows = [(r["id"], r["name"]) for r in state.values()]
    assert rows == [(1, "a2")]  # single live row, updated in place


def test_export_failure_propagates_to_importer():
    """A failing exporting graph must NOT look like a clean close to importers."""
    import pytest

    pg.G.clear()
    src = pw.debug.table_from_rows(
        pw.schema_builder({"v": int}), [(1, 0, 1), (2, 2, 1)], is_stream=True
    )
    def boom(x: int) -> int:
        raise RuntimeError("exporter exploded")
    bad = src.select(v=pw.udf(boom)(pw.this.v))
    exported = pw.io.export_table(bad)
    graph_a = pg.G._current

    from pathway_tpu.engine.runner import GraphRunner

    with pytest.raises(Exception, match="exporter exploded"):
        GraphRunner(graph_a).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert exported.failed()

    pg.G.clear()
    imported = pw.io.import_table(exported)
    pw.io.subscribe(imported, lambda *a, **kw: None)
    with pytest.raises(Exception, match="exporting graph failed"):
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
