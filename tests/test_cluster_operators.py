"""Multi-process coverage for the operators de-blocked in round 4: rowkey-exchanged
(update_rows, intersect), instance-routed (deduplicate), and centralized
(sort, buffer/forget behind windowby behaviors) — VERDICT r3 item 5.

Reference model: every operator participates in timely's exchange
(``src/engine/dataflow.rs``); temporal/ordering operators centralize on one worker
(``src/engine/dataflow/operators/time_column.rs:48-51``)."""

from __future__ import annotations

import collections
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(n: int, program: str, tmp_path, first_port: int) -> None:
    prog = tmp_path / "prog.py"
    prog.write_text(program)
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    out = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", str(n), "--first-port", str(first_port + os.getpid() % 500 * 4),
            sys.executable, str(prog),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, f"spawn failed:\nstdout={out.stdout}\nstderr={out.stderr}"


def _merge_counting(dumps: list[list]) -> dict:
    """Merge per-process (row, diff) event lists into the net final multiset."""
    net: collections.Counter = collections.Counter()
    for events in dumps:
        for *row, diff in events:
            net[tuple(row)] += diff
    return {k: v for k, v in net.items() if v != 0}


SORT_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    vals = json.load(open(os.path.join(tmp, f"input_{pid}.json")))
    tbl = pw.debug.table_from_rows(pw.schema_builder({"a": int}), [(v,) for v in vals])
    s = tbl.sort(tbl.a)
    sort_rows, base_rows = [], []
    pw.io.subscribe(
        s,
        lambda key, row, time, is_addition: sort_rows.append(
            [str(key), str(row["prev"]), str(row["next"]), 1 if is_addition else -1]
        ),
    )
    pw.io.subscribe(
        tbl,
        lambda key, row, time, is_addition: base_rows.append(
            [str(key), row["a"], 1 if is_addition else -1]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(
        {"sort": sort_rows, "base": base_rows},
        open(os.path.join(tmp, f"out_{pid}.json"), "w"),
    )
    """
)


def test_spawn_sort_exact_global_chain(tmp_path):
    """sort at -n 2 centralizes on process 0 and must produce ONE global
    prev/next chain in value order spanning both processes' rows."""
    shards = {0: [30, 10, 50, 70], 1: [20, 60, 40, 80]}
    for pid, vals in shards.items():
        (tmp_path / f"input_{pid}.json").write_text(json.dumps(vals))
    _spawn(2, SORT_PROG, tmp_path, 23000)

    outs = [json.loads((tmp_path / f"out_{p}.json").read_text()) for p in range(2)]
    # base rows surface per producing process: map key -> value
    key_to_val: dict = {}
    for o in outs:
        for key, a, d in o["base"]:
            assert d == 1
            key_to_val[key] = a
    assert sorted(key_to_val.values()) == sorted(v for s in shards.values() for v in s)

    # sort output lands ONLY on the centralizing process
    assert outs[1]["sort"] == [], "sort output leaked to a non-root process"
    links = _merge_counting([o["sort"] for o in outs])
    assert len(links) == len(key_to_val)
    chain = {key: (prev, nxt) for key, prev, nxt in links}
    heads = [k for k, (p, _) in chain.items() if p == "None"]
    assert len(heads) == 1, f"expected one global chain, got heads {heads}"
    walked = []
    cur = heads[0]
    while cur != "None":
        walked.append(key_to_val[cur])
        cur = chain[cur][1]
    assert walked == sorted(key_to_val.values())


WINDOW_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    rows = [tuple(r) for r in json.load(open(os.path.join(tmp, f"input_{pid}.json")))]
    tbl = pw.debug.table_from_rows(
        pw.schema_builder({"sensor": int, "t": int, "value": int}), rows, is_stream=True
    )
    win = tbl.windowby(
        tbl.t,
        window=pw.temporal.tumbling(duration=25),
        instance=tbl.sensor,
        behavior=pw.temporal.common_behavior(delay=5, cutoff=40, keep_results=True),
    ).reduce(
        sensor=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.value),
        n=pw.reducers.count(),
    )
    got = []
    pw.io.subscribe(
        win,
        lambda key, row, time, is_addition: got.append(
            [row["sensor"], row["start"], row["total"], row["n"], 1 if is_addition else -1]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)


def test_spawn_windowed_aggregation_with_behavior_exact(tmp_path):
    """A behavior-backed windowed aggregation (buffer + forget centralize on
    process 0, groupby re-exchanges) at -n 2 must equal the single-process run
    on the merged stream."""
    # (sensor, t, value, commit_time, diff): same commit schedule on both shards
    shards = {
        0: [
            (0, 3, 1, 0, 1), (1, 7, 2, 0, 1),
            (0, 30, 3, 2, 1), (1, 28, 4, 2, 1),
            (0, 55, 5, 4, 1), (0, 2, 7, 4, 1),   # late row for window 0
            (1, 80, 6, 6, 1),
        ],
        1: [
            (1, 5, 10, 0, 1), (0, 12, 20, 0, 1),
            (1, 33, 30, 2, 1), (0, 44, 40, 2, 1),
            (1, 58, 50, 4, 1), (1, 4, 70, 4, 1),  # late row for window 0
            (0, 77, 60, 6, 1),
        ],
    }
    for pid, rows in shards.items():
        (tmp_path / f"input_{pid}.json").write_text(json.dumps(rows))
    _spawn(2, WINDOW_PROG, tmp_path, 23200)
    outs = [json.loads((tmp_path / f"out_{p}.json").read_text()) for p in range(2)]
    got = _merge_counting(outs)

    # single-process truth on the merged stream (same commit schedule)
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg

    pg.G.clear()
    merged_rows = sorted(
        shards[0] + shards[1], key=lambda r: r[3]
    )  # by commit time; within-commit order is irrelevant to the window result
    tbl = pw.debug.table_from_rows(
        pw.schema_builder({"sensor": int, "t": int, "value": int}),
        merged_rows,
        is_stream=True,
    )
    win = tbl.windowby(
        tbl.t,
        window=pw.temporal.tumbling(duration=25),
        instance=tbl.sensor,
        behavior=pw.temporal.common_behavior(delay=5, cutoff=40, keep_results=True),
    ).reduce(
        sensor=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.value),
        n=pw.reducers.count(),
    )
    expected_events: list = []
    pw.io.subscribe(
        win,
        lambda key, row, time, is_addition: expected_events.append(
            [row["sensor"], row["start"], row["total"], row["n"], 1 if is_addition else -1]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    expected = _merge_counting([expected_events])
    assert got == expected
    assert got, "window produced no output at all"


UPDATE_ROWS_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    data = json.load(open(os.path.join(tmp, f"input_{pid}.json")))
    schema = pw.schema_builder({
        "k": pw.column_definition(dtype=str, primary_key=True),
        "v": pw.column_definition(dtype=int),
    })
    base = pw.debug.table_from_rows(schema, [tuple(r) for r in data["base"]])
    patch = pw.debug.table_from_rows(schema, [tuple(r) for r in data["patch"]])
    upd = base.update_rows(patch)
    inter = base.intersect(patch)
    u_rows, i_rows = [], []
    pw.io.subscribe(
        upd,
        lambda key, row, time, is_addition: u_rows.append(
            [row["k"], row["v"], 1 if is_addition else -1]
        ),
    )
    pw.io.subscribe(
        inter,
        lambda key, row, time, is_addition: i_rows.append(
            [row["k"], row["v"], 1 if is_addition else -1]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(
        {"update": u_rows, "intersect": i_rows},
        open(os.path.join(tmp, f"out_{pid}.json"), "w"),
    )
    """
)


def test_spawn_update_rows_and_intersect_exact(tmp_path):
    """update_rows/intersect at -n 2: base and patch rows for the SAME primary key
    live on different processes — the rowkey exchange must bring them together."""
    # keys deliberately split so base(k) and patch(k) never share a process
    shards = {
        0: {"base": [["a", 1], ["b", 2], ["c", 3]], "patch": [["d", 40]]},
        1: {"base": [["d", 4], ["e", 5]], "patch": [["a", 10], ["e", 50], ["x", 99]]},
    }
    for pid, data in shards.items():
        (tmp_path / f"input_{pid}.json").write_text(json.dumps(data))
    _spawn(2, UPDATE_ROWS_PROG, tmp_path, 23400)
    outs = [json.loads((tmp_path / f"out_{p}.json").read_text()) for p in range(2)]

    got_update = _merge_counting([o["update"] for o in outs])
    # global truth: patch wins per key; patch-only keys appear too
    assert got_update == {
        ("a", 10): 1, ("b", 2): 1, ("c", 3): 1, ("d", 40): 1, ("e", 50): 1, ("x", 99): 1,
    }
    got_inter = _merge_counting([o["intersect"] for o in outs])
    # intersect keeps base rows whose key exists in patch (base values)
    assert got_inter == {("a", 1): 1, ("d", 4): 1, ("e", 5): 1}

    # each surviving key must be owned by exactly one process
    for section in ("update", "intersect"):
        owners: collections.Counter = collections.Counter()
        for p, o in enumerate(outs):
            for k, _v, d in o[section]:
                if d > 0:
                    owners[k] += 0  # touch
        # ownership check via positive net per process
        per_proc = [
            {k for k, v in _merge_counting([o[section]]).items()} for o in outs
        ]
        assert not (set(per_proc[0]) & set(per_proc[1]))


DEDUP_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    rows = [tuple(r) for r in json.load(open(os.path.join(tmp, f"input_{pid}.json")))]
    tbl = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "v": int}), rows, is_stream=True
    )
    ded = tbl.deduplicate(
        value=pw.this.v, instance=pw.this.k, acceptor=lambda new, old: new > old
    )
    got = []
    pw.io.subscribe(
        ded,
        lambda key, row, time, is_addition: got.append(
            [row["k"], row["v"], 1 if is_addition else -1]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)


def test_spawn_deduplicate_instance_routed(tmp_path):
    """deduplicate at -n 2 routes rows to their instance's owner: the running max
    per instance must see BOTH processes' rows (commit order fixes the outcome)."""
    # commits strictly increase per instance so the accepted value is
    # order-independent within the exchange merge
    shards = {
        0: [("a", 1, 0, 1), ("b", 9, 0, 1), ("a", 5, 2, 1), ("b", 3, 4, 1)],
        1: [("a", 3, 0, 1), ("b", 2, 2, 1), ("a", 7, 4, 1)],
    }
    for pid, rows in shards.items():
        (tmp_path / f"input_{pid}.json").write_text(json.dumps(rows))
    _spawn(2, DEDUP_PROG, tmp_path, 23600)
    outs = [json.loads((tmp_path / f"out_{p}.json").read_text()) for p in range(2)]
    got = _merge_counting(outs)
    # per instance: max over ALL rows (acceptor keeps increases only)
    assert got == {("a", 7): 1, ("b", 9): 1}
    # each instance's output is owned by exactly one process
    per_proc = [set(_merge_counting([o])) for o in outs]
    assert not (per_proc[0] & per_proc[1])


KNN_PROG = textwrap.dedent(
    """
    import json, os
    import numpy as np
    import pathway_tpu as pw
    from pathway_tpu.stdlib.ml.index import KNNIndex

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    data = json.load(open(os.path.join(tmp, f"input_{pid}.json")))
    docs = pw.debug.table_from_rows(
        pw.schema_builder({"name": str, "vec": np.ndarray}),
        [(n, np.asarray(v, dtype=np.float32)) for n, v in data["docs"]],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"qname": str, "qvec": np.ndarray}),
        [(n, np.asarray(v, dtype=np.float32)) for n, v in data["queries"]],
    )
    res = KNNIndex(docs.vec, docs, n_dimensions=4).get_nearest_items(
        queries.qvec, k=2
    )
    got = []
    pw.io.subscribe(
        res,
        lambda key, row, time, is_addition: got.append(
            [sorted(row["name"]), 1 if is_addition else -1]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)


def test_spawn_external_index_replicated_exact(tmp_path):
    """The external-index operator at -n 2: the data side is broadcast so each
    process's local queries see the FULL corpus — a query on process 0 must
    retrieve nearest neighbors ingested on process 1."""
    # four distinct corners of the plane; docs split across processes
    docs = {
        0: [["n00", [10, 0, 0, 0]], ["n01", [0, 10, 0, 0]]],
        1: [["n10", [0, 0, 10, 0]], ["n11", [0, 0, 0, 10]]],
    }
    # each process queries a corner owned by the OTHER process
    queries = {
        0: [["q0", [0, 0, 9, 1]]],   # nearest: n10 then n11 (both on p1)
        1: [["q1", [9, 1, 0, 0]]],   # nearest: n00 then n01 (both on p0)
    }
    for pid in (0, 1):
        (tmp_path / f"input_{pid}.json").write_text(
            json.dumps({"docs": docs[pid], "queries": queries[pid]})
        )
    _spawn(2, KNN_PROG, tmp_path, 23800)
    outs = [json.loads((tmp_path / f"out_{p}.json").read_text()) for p in range(2)]
    # queries answer on their local process, against the replicated corpus
    assert [g for g, d in outs[0] if d > 0] == [["n10", "n11"]]
    assert [g for g, d in outs[1] if d > 0] == [["n00", "n01"]]


IX_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    data = json.load(open(os.path.join(tmp, f"input_{pid}.json")))
    target = pw.debug.table_from_rows(
        pw.schema_builder({
            "k": pw.column_definition(dtype=str, primary_key=True),
            "v": pw.column_definition(dtype=int),
        }),
        [tuple(r) for r in data["target"]],
    )
    src = pw.debug.table_from_rows(
        pw.schema_builder({"name": str, "ref": str}), [tuple(r) for r in data["src"]]
    )
    res = src.select(src.name, v=target.ix(target.pointer_from(src.ref)).v)
    got = []
    pw.io.subscribe(
        res,
        lambda key, row, time, is_addition: got.append(
            [row["name"], row["v"], 1 if is_addition else -1]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)


def test_spawn_ix_replicated_target_exact(tmp_path):
    """ix at -n 2: the target side broadcasts into a per-process replica, so a
    source row on process 0 resolves a pointer to a target row ingested on
    process 1 (and vice versa), with output rows staying source-local."""
    shards = {
        0: {"target": [["a", 1], ["b", 2]], "src": [["s0", "c"], ["s1", "d"]]},
        1: {"target": [["c", 3], ["d", 4]], "src": [["s2", "a"], ["s3", "b"]]},
    }
    for pid, data in shards.items():
        (tmp_path / f"input_{pid}.json").write_text(json.dumps(data))
    _spawn(2, IX_PROG, tmp_path, 24200)
    outs = [json.loads((tmp_path / f"out_{p}.json").read_text()) for p in range(2)]
    # source rows answer on their OWN process against the replicated target
    assert _merge_counting([outs[0]]) == {("s0", 3): 1, ("s1", 4): 1}
    assert _merge_counting([outs[1]]) == {("s2", 1): 1, ("s3", 2): 1}
