"""CLI tests: spawn multi-process partitioned ingest, record/replay flow.

Mirrors the reference's CLI contract (cli.py spawn/-t/-n env vars, record/replay)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pathway_tpu as pw
from pathway_tpu.internals.config import PathwayConfig


def _env():
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def test_config_from_env(monkeypatch):
    monkeypatch.setenv("PATHWAY_THREADS", "4")
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    monkeypatch.setenv("PATHWAY_SNAPSHOT_ACCESS", "replay")
    cfg = PathwayConfig.from_env()
    assert (cfg.threads, cfg.processes, cfg.process_id) == (4, 2, 1)
    assert cfg.continue_after_replay is False
    monkeypatch.setenv("PATHWAY_CONTINUE_AFTER_REPLAY", "true")
    assert PathwayConfig.from_env().continue_after_replay is True


_SPAWN_PROG = r"""
import os, sys, json
import pathway_tpu as pw

input_dir, out_prefix = sys.argv[1], sys.argv[2]

class Sch(pw.Schema):
    word: str

t = pw.io.csv.read(input_dir, schema=Sch, mode="static")
rows = []
pw.io.subscribe(t, lambda key, row, time, is_addition: rows.append(row["word"]))
pw.run()
pid = os.environ.get("PATHWAY_PROCESS_ID", "0")
with open(f"{out_prefix}.{pid}", "w") as f:
    json.dump(sorted(rows), f)
"""


def test_spawn_two_processes_partition_files(tmp_path):
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    for i in range(8):
        (input_dir / f"f{i}.csv").write_text(f"word\nw{i}\n")
    prog = tmp_path / "prog.py"
    prog.write_text(_SPAWN_PROG)
    out_prefix = str(tmp_path / "out")

    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "pathway_tpu.cli",
            "spawn",
            "-n",
            "2",
            sys.executable,
            str(prog),
            str(input_dir),
            out_prefix,
        ],
        env=_env(),
        cwd="/root/repo",
        capture_output=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr.decode()
    words0 = json.load(open(out_prefix + ".0"))
    words1 = json.load(open(out_prefix + ".1"))
    # disjoint partition covering all files
    assert set(words0) & set(words1) == set()
    assert set(words0) | set(words1) == {f"w{i}" for i in range(8)}
    assert words0 and words1  # both processes got a share (8 files, hash split)


_RECORD_PROG = r"""
import os, sys, json
import pathway_tpu as pw

input_dir, out_path = sys.argv[1], sys.argv[2]

class Sch(pw.Schema):
    word: str

t = pw.io.csv.read(input_dir, schema=Sch, mode="static")
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[repr(key)] = dict(word=row["word"], total=int(row["total"]))
    else:
        rows.pop(repr(key), None)
pw.io.subscribe(counts, on_change)
pw.run()
with open(out_path, "w") as f:
    json.dump(sorted((r["word"], r["total"]) for r in rows.values()), f)
"""


def test_record_then_replay(tmp_path):
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    (input_dir / "a.csv").write_text("word\ncat\ncat\ndog\n")
    prog = tmp_path / "prog.py"
    prog.write_text(_RECORD_PROG)
    record_path = str(tmp_path / "recording")

    res = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "--record", "--record-path", record_path,
            sys.executable, str(prog), str(input_dir), str(tmp_path / "out1.json"),
        ],
        env=_env(), cwd="/root/repo", capture_output=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr.decode()
    out1 = json.load(open(tmp_path / "out1.json"))
    assert out1 == [["cat", 2], ["dog", 1]]

    # replay from the recording with the INPUT GONE — results must come from the journal
    (input_dir / "a.csv").unlink()
    res = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "replay",
            "--record-path", record_path, "--mode", "batch",
            sys.executable, str(prog), str(input_dir), str(tmp_path / "out2.json"),
        ],
        env=_env(), cwd="/root/repo", capture_output=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr.decode()
    out2 = json.load(open(tmp_path / "out2.json"))
    assert out2 == out1
