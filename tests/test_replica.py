"""Read-replica serving fleet (``parallel/replica.py`` +
``persistence/replica_feed.py``).

What the suite proves, layer by layer:

- **feed round-trip** — a replica bootstrapped from the primary's
  read-back-verified export and caught up through the frame tail answers
  BITWISE-identically to the primary at the same commit id (the ``bench.py
  replicas`` honesty key);
- **bounded bootstrap** — the export streams in bounded row fragments, so
  a replica's peak install memory is one fragment, never the corpus;
- **typed refusal** — a torn bootstrap (chaos ``replica_torn_bootstrap``)
  refuses with ``ReplicaBootstrapError`` and stays OUT of rotation; it
  never serves from a half-installed index;
- **exactly-once apply** — a frame re-listed across polls is skipped (the
  double-apply guard ``replica_follow_model`` explores interleavings of);
- **bounded staleness** — ``max_staleness_s`` sheds typed in-process and as
  HTTP 429 with an RFC-9110 integer ``Retry-After`` over the wire;
- **kill-invisible failover** — the router absorbs dead/refusing/stale
  replicas and falls back to the primary: zero client-visible errors, even
  with a chaos-SIGKILL'd replica in the fleet (the spawn acceptance);
- **fleet supervision** — post-mortems attribute replica deaths (exit
  cause, last applied commit, staleness at death) and flight dumps survive
  supervise-dir cleanup;
- **independent autoscaling** — ``_fleet_signals`` + the replica-flavored
  pure controller grow the fleet on query load without touching ingest.

Spawn-convergence acceptances budget 240 s (CI worst case); they converge
in seconds on an idle machine.
"""

import json
import os
import re
import shutil
import sys
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pathway_tpu.ops.knn import BruteForceKnnIndex
from pathway_tpu.parallel.replica import (
    ReplicaFleet,
    ReplicaFollower,
    ReplicaNotServingError,
    ReplicaRouter,
    ReplicaServer,
    ReplicaStaleError,
    ReplicaUnavailableError,
    default_index_factory,
    read_replica_statuses,
)
from pathway_tpu.persistence.replica_feed import (
    ReplicaBootstrapError,
    ReplicaFeed,
)

pytestmark = pytest.mark.replicas

DIM = 8


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


def _vectors(n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, DIM)).astype(np.float32)


def _primary(n: int = 12, seed: int = 0) -> BruteForceKnnIndex:
    index = BruteForceKnnIndex(DIM)
    vecs = _vectors(n, seed)
    index.add_many([f"k{i}" for i in range(n)], vecs)
    for i in range(n):
        index.filter_data[f"k{i}"] = {"tag": "even" if i % 2 == 0 else "odd"}
    return index


def _assert_bitwise_parity(primary, follower, queries, k=4, filters=None):
    want = primary.search_many(list(queries), [k] * len(queries), filters)
    _, got = follower.search_many(list(queries), [k] * len(queries), filter_exprs=filters)
    assert got == want  # keys AND float scores, exact equality


# -- feed round-trip + parity ---------------------------------------------------


def test_bootstrap_and_follow_bitwise_parity(tmp_path):
    """Bootstrap at commit 3, tail frames 4 (upsert) and 5 (removal +
    re-upsert): the replica answers bitwise-identically to the primary."""
    primary = _primary(12)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(3, primary)

    extra = _vectors(3, seed=7)
    primary.add_many(["n0", "n1", "n2"], extra)
    feed.record_commit(4, ["n0", "n1", "n2"], extra)

    primary.remove("k1")
    moved = _vectors(1, seed=9)
    primary.add_many(["k2"], moved)  # upsert: k2 moves
    primary.filter_data["k2"] = {"tag": "moved"}
    feed.record_commit(
        5, ["k2"], moved, removals=["k1"], filter_data={"k2": {"tag": "moved"}}
    )

    follower = ReplicaFollower(feed, default_index_factory)
    assert follower.bootstrap() == 3
    assert follower.state == "following"
    assert follower.poll_frames() == 2
    assert follower.applied_commit == 5

    queries = _vectors(5, seed=3)
    _assert_bitwise_parity(primary, follower, queries)
    commit, rows = follower.search_many(list(queries[:1]), [12])
    assert commit == 5
    keys = {key for key, _ in rows[0]}
    assert "k1" not in keys and "n0" in keys
    # filter data survives bootstrap + frame apply (k2's tag moved)
    _assert_bitwise_parity(
        primary, follower, queries[:2], filters=["tag == 'moved'"] * 2
    )


def test_bootstrap_streams_bounded_fragments(tmp_path):
    """A 10-row export at rows_per_fragment=4 lands as 3 fragments and every
    install call stays within the bound — flat peak memory by construction."""
    primary = _primary(10)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    manifest = feed.export_bootstrap(1, primary, rows_per_fragment=4)
    assert len(manifest["fragments"]) == 3
    assert manifest["rows"] == 10
    assert [f["rows"] for f in manifest["fragments"]] == [4, 4, 2]

    sizes = []
    holder = {}

    def install_header(header):
        index = default_index_factory(header)
        index.install_descriptor_header(header)
        holder["index"] = index

    def install_fragment(keys, vectors):
        sizes.append(len(keys))
        holder["index"].install_descriptor_rows(keys, vectors)

    assert (
        feed.load_bootstrap(
            install_header=install_header, install_fragment=install_fragment
        )
        == 1
    )
    assert sizes == [4, 4, 2]
    want = primary.search_many(list(_vectors(3, 5)), [3] * 3)
    assert holder["index"].search_many(list(_vectors(3, 5)), [3] * 3) == want


@pytest.mark.chaos
def test_torn_bootstrap_is_typed_refusal(tmp_path, monkeypatch):
    """Chaos-torn bootstrap: a TYPED ``ReplicaBootstrapError`` refusal; the
    replica reports ``refused`` and every query raises
    ``ReplicaNotServingError`` — it never serves a half-installed index."""
    from pathway_tpu.internals.chaos import reset_chaos

    primary = _primary(8)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps(
            {"replica": [{"op": "replica_torn_bootstrap", "replica": 0}]}
        ),
    )
    reset_chaos()
    try:
        follower = ReplicaFollower(feed, default_index_factory)
        with pytest.raises(ReplicaBootstrapError, match="checksum mismatch"):
            follower.bootstrap()
        assert follower.state == "refused"
        snap = follower.snapshot()
        assert snap["state"] == "refused"
        assert "checksum" in snap["refusal"]
        with pytest.raises(ReplicaNotServingError) as exc_info:
            follower.search_many(list(_vectors(1)), [3])
        assert exc_info.value.state == "refused"
        # a refusal is sticky but not fatal: the same process can re-bootstrap
        # once the fault clears (operator repaired / re-exported)
        monkeypatch.setenv("PATHWAY_CHAOS_PLAN", "{}")
        reset_chaos()
        assert follower.bootstrap() == 1
        assert follower.state == "following"
    finally:
        reset_chaos()


def test_double_apply_guard_skips_relisted_frame(tmp_path, monkeypatch):
    """A frame re-listed by a stale directory scan is a no-op: the applied
    commit id never regresses and results stay bitwise-stable (the
    ``replica_follow_model`` invariant, exercised live)."""
    primary = _primary(6)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    extra = _vectors(2, seed=11)
    primary.add_many(["a0", "a1"], extra)
    feed.record_commit(2, ["a0", "a1"], extra)

    follower = ReplicaFollower(feed, default_index_factory)
    follower.bootstrap()
    assert follower.poll_frames() == 1
    assert follower.applied_commit == 2
    queries = list(_vectors(3, seed=2))
    _, before = follower.search_many(queries, [8] * 3)

    # an idle re-poll applies nothing
    assert follower.poll_frames() == 0

    # simulate a stale listing that re-offers the already-applied frame
    real_frames_after = feed.frames_after
    monkeypatch.setattr(
        feed, "frames_after", lambda floor: real_frames_after(floor - 1)
    )
    assert follower.poll_frames() == 0
    assert follower.applied_commit == 2
    _, after = follower.search_many(queries, [8] * 3)
    assert after == before


# -- bounded staleness ----------------------------------------------------------


def test_staleness_shed_typed_and_recovery(tmp_path):
    clock = FakeClock()
    primary = _primary(6)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    follower = ReplicaFollower(feed, default_index_factory, clock=clock)
    assert follower.staleness_s() == float("inf")  # before bootstrap
    follower.bootstrap()
    assert follower.staleness_s() == 0.0

    clock.advance(5.0)
    with pytest.raises(ReplicaStaleError) as exc_info:
        follower.search_many(list(_vectors(1)), [3], max_staleness_s=1.0)
    err = exc_info.value
    assert err.staleness_s == pytest.approx(5.0)
    assert err.retry_after_s > 0.0
    assert follower.snapshot()["shed_total"] == 1

    # a generous bound (and no bound at all) still serves
    commit, _ = follower.search_many(
        list(_vectors(1)), [3], max_staleness_s=10.0
    )
    assert commit == 1
    follower.search_many(list(_vectors(1)), [3])

    # catching up with the tail resets freshness: the tight bound serves again
    extra = _vectors(1, seed=4)
    primary.add_many(["z0"], extra)
    feed.record_commit(2, ["z0"], extra)
    follower.poll_frames()
    assert follower.staleness_s() == 0.0
    commit, _ = follower.search_many(
        list(_vectors(1)), [3], max_staleness_s=1.0
    )
    assert commit == 2


def test_retry_estimate_scales_with_backlog(tmp_path):
    primary = _primary(4)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    follower = ReplicaFollower(feed, default_index_factory, poll_s=0.5)
    follower.bootstrap()
    idle = follower.retry_estimate_s()
    assert idle == pytest.approx(0.5)  # one poll in flight, no backlog
    for commit in (2, 3, 4):
        feed.record_commit(commit, ["b"], _vectors(1, seed=commit))
    assert follower.pending_frames() == 3
    assert follower.retry_estimate_s() == pytest.approx(2.0)  # (3 + 1) polls


# -- the HTTP surface -----------------------------------------------------------


def _post_retrieve(port, payload, timeout=10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/retrieve",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def test_server_serves_sheds_429_integer_retry_after(tmp_path):
    """The live shed is RFC-9110 honest: HTTP 429 with ``Retry-After`` a
    base-10 non-negative integer (no float, no units) — satellite audit's
    live leg for the replica path."""
    clock = FakeClock()
    primary = _primary(6)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    follower = ReplicaFollower(feed, default_index_factory, clock=clock)
    follower.bootstrap()
    server = ReplicaServer(follower)
    try:
        queries = [[float(x) for x in v] for v in _vectors(2, seed=6)]
        status, _, body = _post_retrieve(
            server.port, {"vectors": queries, "k": 3}
        )
        assert status == 200
        assert body["commit"] == 1
        want = primary.search_many(list(_vectors(2, seed=6)), [3, 3])
        got = [[(key, score) for key, score in row] for row in body["results"]]
        assert got == want

        clock.advance(30.0)
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_retrieve(
                server.port,
                {"vectors": queries, "k": 3, "max_staleness_s": 0.5},
            )
        err = exc_info.value
        assert err.code == 429
        retry_after = err.headers.get("Retry-After")
        assert re.fullmatch(r"[0-9]+", retry_after), retry_after
        assert int(retry_after) >= 1
        assert json.loads(err.read())["error"] == "stale"

        # healthz carries the serving state + applied commit + staleness
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=10
        ) as resp:
            health = json.loads(resp.read())
        assert health["state"] == "following"
        assert health["applied_commit"] == 1
        assert health["staleness_s"] == pytest.approx(30.0)
        assert health["alive"] is True
    finally:
        server.close()


def test_server_503_before_bootstrap(tmp_path):
    feed = ReplicaFeed(str(tmp_path / "feed"))
    follower = ReplicaFollower(feed, default_index_factory)
    server = ReplicaServer(follower)
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_retrieve(server.port, {"vectors": [[0.0] * DIM], "k": 1})
        assert exc_info.value.code == 503
        body = json.loads(exc_info.value.read())
        assert body == {"error": "not_serving", "state": "init"}
    finally:
        server.close()


@pytest.mark.trace
def test_replica_serve_links_originating_commit_trace(tmp_path, monkeypatch):
    """ISSUE 20 acceptance, replica leg: the primary's commit-span context
    rides the feed frame, the replica's ``replica_apply`` span joins the
    commit's trace as a CHILD, and a served read parents to the CALLER's
    header while LINKING the applied commit span — `cli trace` can walk from
    a client query back to the ingest commit whose data answered it."""
    from pathway_tpu.engine.tracing import (
        TRACE_HEADER,
        commit_trace_context,
        format_trace_header,
        get_tracer,
        new_trace_context,
        parse_trace_header,
        reset_tracing,
    )

    monkeypatch.setenv("PATHWAY_TRACE", "on")
    monkeypatch.setenv("PATHWAY_TRACE_SAMPLE", "1.0")
    reset_tracing()
    tracer = get_tracer()
    try:
        primary = _primary(6)
        feed = ReplicaFeed(str(tmp_path / "feed"))
        feed.export_bootstrap(1, primary)
        extra = _vectors(2, seed=5)
        primary.add_many(["n0", "n1"], extra)
        commit_ctx = commit_trace_context(0, 2, rank=0)
        with tracer.trace_span("commit", "commit 2", self_ctx=commit_ctx):
            feed.record_commit(2, ["n0", "n1"], extra)

        follower = ReplicaFollower(feed, default_index_factory)
        assert follower.bootstrap() == 1
        assert follower.poll_frames() == 1
        spans = tracer.recent_spans(limit=256)
        apply_span = next(s for s in spans if s["kind"] == "replica_apply")
        # the rider made the apply a CHILD of the primary's commit span
        assert apply_span["trace_id"] == commit_ctx.trace_id
        assert apply_span["parent_id"] == commit_ctx.span_id

        server = ReplicaServer(follower)
        try:
            caller = new_trace_context(sampled=True)
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/retrieve",
                data=json.dumps(
                    {"vectors": [[0.0] * DIM], "k": 2}
                ).encode(),
                headers={
                    "Content-Type": "application/json",
                    TRACE_HEADER: format_trace_header(caller),
                },
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
                echoed = parse_trace_header(resp.headers.get(TRACE_HEADER))
            # response echoes the caller's trace with the serve span's id
            assert echoed is not None
            assert echoed.trace_id == caller.trace_id
            assert echoed.span_id != caller.span_id
            serve = next(
                s for s in tracer.recent_spans(limit=256)
                if s["kind"] == "replica_serve"
            )
            assert serve["trace_id"] == caller.trace_id
            assert serve["parent_id"] == caller.span_id
            assert serve["attrs"]["status"] == 200
            assert serve["attrs"]["commit"] == 2
            # ... and LINKS the applied commit span: query -> ingest edge
            linked = {link["span_id"] for link in serve["links"]}
            assert commit_ctx.span_id in linked, serve["links"]
        finally:
            server.close()
    finally:
        # env is still monkeypatched "on" here, so a bare reset would leave
        # the process-wide tracer live for the rest of the suite
        reset_tracing()
        get_tracer().enabled = False


# -- the router: kill-invisible failover ---------------------------------------


def _primary_closure(primary, tip_commit):
    def serve(vectors, k, filters):
        return tip_commit, primary.search_many(
            list(vectors), [k] * len(vectors), filters
        )

    return serve


def test_router_failover_is_client_invisible(tmp_path):
    """Kill one replica server, then both: every query still succeeds —
    first via the surviving replica, then via the primary fallback. The
    client never sees an error."""
    primary = _primary(8)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    followers = [
        ReplicaFollower(feed, default_index_factory, replica_id=i)
        for i in range(2)
    ]
    for f in followers:
        f.bootstrap()
    servers = [ReplicaServer(f) for f in followers]
    try:
        router = ReplicaRouter(
            [f"http://127.0.0.1:{s.port}" for s in servers],
            primary=_primary_closure(primary, 1),
        )
        queries = [[float(x) for x in v] for v in _vectors(2, seed=8)]
        want = primary.search_many(list(_vectors(2, seed=8)), [3, 3])
        for _ in range(4):
            commit, results = router.retrieve(queries, 3)
            assert commit == 1 and results == want
        assert router.stats["replica_served"] == 4

        servers[0].close()  # half the fleet vanishes mid-traffic
        for _ in range(6):
            commit, results = router.retrieve(queries, 3)
            assert commit == 1 and results == want
        assert router.stats["failovers"] >= 1
        assert router.stats["primary_served"] == 0  # fleet still covered it

        servers[1].close()  # whole fleet gone: the primary absorbs
        for _ in range(3):
            commit, results = router.retrieve(queries, 3)
            assert commit == 1 and results == want
        assert router.stats["primary_served"] == 3
    finally:
        for s in servers:
            s.close()


def test_router_stale_fleet_sheds_with_min_retry_after(tmp_path):
    """With no primary, an all-stale fleet surfaces a typed
    ``ReplicaStaleError`` carrying the smallest advertised backoff; an
    all-dead fleet surfaces ``ReplicaUnavailableError``."""
    clock = FakeClock()
    primary = _primary(6)
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    follower = ReplicaFollower(feed, default_index_factory, clock=clock)
    follower.bootstrap()
    clock.advance(60.0)
    server = ReplicaServer(follower)
    try:
        router = ReplicaRouter([f"http://127.0.0.1:{server.port}"])
        queries = [[float(x) for x in v] for v in _vectors(1)]
        with pytest.raises(ReplicaStaleError) as exc_info:
            router.retrieve(queries, 3, max_staleness_s=0.5)
        assert exc_info.value.retry_after_s >= 1.0  # the advertised integer
        assert router.stats["sheds_seen"] == 1
    finally:
        server.close()
    router = ReplicaRouter([f"http://127.0.0.1:{server.port}"])
    with pytest.raises(ReplicaUnavailableError):
        router.retrieve(queries, 3)


# -- fleet autoscaling (pure) ---------------------------------------------------


def test_fleet_signals_fold_served_and_shed_rates():
    from pathway_tpu.parallel.replica import _fleet_signals

    statuses0 = {
        0: {"served_total": 100, "shed_total": 0},
        1: {"served_total": 50, "shed_total": 2},
    }
    signals, carry = _fleet_signals(statuses0, None, 10.0, 2)
    assert signals.stable and signals.current_n == 2
    assert signals.ingest_rate == 0.0  # first sample: no window yet
    statuses1 = {
        0: {"served_total": 600, "shed_total": 0},
        1: {"served_total": 250, "shed_total": 12},
    }
    signals, carry = _fleet_signals(statuses1, carry, 12.0, 2)
    assert signals.ingest_rate == pytest.approx(350.0)  # +700 served / 2 s
    assert signals.shed_rate == pytest.approx(5.0)
    # a missing status file (replica mid-relaunch) reads as unstable
    signals, _ = _fleet_signals({0: statuses1[0]}, carry, 13.0, 2)
    assert not signals.stable


def test_replica_policy_scales_up_on_query_load(monkeypatch):
    """The replica-flavored pure controller (QPS-per-replica capacity, shed
    escalates immediately) grows the fleet after a sustained overload — no
    ingest signal involved."""
    from pathway_tpu.parallel.autoscaler import (
        AutoscaleController,
        AutoscalePolicy,
        AutoscaleSignals,
    )

    monkeypatch.delenv("PATHWAY_REPLICA_AUTOSCALE_QPS", raising=False)
    policy = AutoscalePolicy.replica_from_env()
    assert policy.min_workers == 1 and policy.max_workers == 4
    assert policy.rows_per_worker == 200.0  # queries/s per replica
    controller = AutoscaleController(policy, 1)
    target = None
    for tick in range(20):
        decision = controller.sample(
            float(tick * 2),
            AutoscaleSignals(ingest_rate=700.0, stable=True, current_n=1),
        )
        if decision is not None:
            target = decision
            break
    assert target == 4  # ceil(700/200) = 4, within the fleet ceiling


# -- fleet spawn acceptances ----------------------------------------------------


def _spawn_env(tmp_path, **extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PATHWAY_REPLICA_POLL_S"] = "0.05"
    env.update(extra)
    return env


@pytest.mark.chaos
def test_fleet_chaos_kill_zero_client_errors(tmp_path):
    """THE acceptance: n=2 replicas + primary fallback, chaos SIGKILLs
    replica 0 at its first applied frame — 20 straight client queries all
    succeed (zero visible errors), the death is attributed (exit cause,
    last applied commit, staleness at death), the flight dump survives
    supervise-dir cleanup, and the relaunched replica rejoins."""
    primary = _primary(10)
    feed_root = str(tmp_path / "feed")
    supervise_dir = str(tmp_path / "supervise")
    os.makedirs(supervise_dir)
    feed = ReplicaFeed(feed_root)
    feed.export_bootstrap(1, primary)

    plan = {"replica": [{"op": "replica_kill", "replica": 0, "commit": 2}]}
    fleet = ReplicaFleet(
        feed_root=feed_root,
        supervise_dir=supervise_dir,
        run_id="test-kill",
        n=2,
        base_env=_spawn_env(tmp_path, PATHWAY_CHAOS_PLAN=json.dumps(plan)),
        autoscale=False,
    )
    preserved = None
    try:
        fleet.start()
        endpoints = fleet.wait_serving(2, deadline_s=240.0)
        assert len(endpoints) == 2

        # move the primary forward: re-export FIRST so the relaunched
        # replica bootstraps PAST the killing frame (the prune discipline),
        # then publish the frame the chaos plan is armed on
        extra = _vectors(2, seed=21)
        primary.add_many(["x0", "x1"], extra)
        feed.export_bootstrap(2, primary)
        feed.record_commit(2, ["x0", "x1"], extra)

        router = ReplicaRouter(
            endpoints, primary=_primary_closure(primary, 2), timeout_s=10.0
        )
        queries = [[float(x) for x in v] for v in _vectors(2, seed=22)]
        want = primary.search_many(list(_vectors(2, seed=22)), [3, 3])
        deadline = time.monotonic() + 240.0
        served = 0
        while served < 20:
            assert time.monotonic() < deadline, "kill acceptance timed out"
            _, results = router.retrieve(queries, 3)  # must NEVER raise
            assert results == want
            served += 1
            fleet.watch_once()
            time.sleep(0.02)
        assert served == 20  # zero client-visible errors

        # the SIGKILL happened and was attributed
        deadline = time.monotonic() + 240.0
        while not fleet.post_mortems and time.monotonic() < deadline:
            fleet.watch_once()
            time.sleep(0.05)
        assert fleet.post_mortems, "replica 0 was never reaped"
        line = fleet.post_mortems[0]
        assert "replica 0" in line
        assert "killed by signal SIGKILL" in line
        assert "last applied commit" in line
        assert "staleness at death" in line
        # chaos dumps the flight recorder before the kill; the fleet
        # preserved it outside the supervise dir
        match = re.search(r"flight dump preserved at (\S+)", line)
        assert match, line
        preserved = match.group(1)
        assert os.path.exists(preserved)

        # the relaunch converges back to a full fleet at the NEW bootstrap
        fleet.wait_serving(2, deadline_s=240.0)
        statuses = read_replica_statuses(supervise_dir, 2)
        assert statuses[0]["applied_commit"] == 2
    finally:
        fleet.stop()
        shutil.rmtree(supervise_dir, ignore_errors=True)
    # preservation outlives the supervise dir
    assert preserved is not None and os.path.exists(preserved)
    os.unlink(preserved)


def test_fleet_stop_preserves_flight_dumps(tmp_path):
    """Even without a chaos kill, ``stop()`` copies whatever flight dumps
    the replicas wrote out of the doomed supervise dir first."""
    fleet = ReplicaFleet(
        feed_root=str(tmp_path / "feed"),
        supervise_dir=str(tmp_path / "supervise"),
        run_id="test-preserve",
        n=0,
        autoscale=False,
    )
    replicas_dir = os.path.join(str(tmp_path / "supervise"), "replicas")
    os.makedirs(replicas_dir)
    with open(os.path.join(replicas_dir, "flight-rank-3.json"), "w") as f:
        json.dump({"events": []}, f)
    fleet.procs[3] = type(  # a stub "already exited" process handle
        "P", (), {"poll": lambda self: 0, "terminate": lambda self: None,
                  "wait": lambda self, timeout=None: 0}
    )()
    fleet.stop()
    shutil.rmtree(str(tmp_path / "supervise"))
    preserved = os.path.join(
        tempfile.gettempdir(), "pathway-flight-test-preserve-replica-3.json"
    )
    assert os.path.exists(preserved)
    os.unlink(preserved)


def test_replica_process_refuses_typed_on_torn_bootstrap_spawn(tmp_path):
    """A spawned replica whose bootstrap is chaos-torn stays UP, publishes
    ``refused`` (out of rotation), and answers 503 — a typed refusal an
    operator can see, not a crash loop."""
    primary = _primary(6)
    feed_root = str(tmp_path / "feed")
    supervise_dir = str(tmp_path / "supervise")
    os.makedirs(supervise_dir)
    ReplicaFeed(feed_root).export_bootstrap(1, primary)
    plan = {"replica": [{"op": "replica_torn_bootstrap", "replica": 0}]}
    fleet = ReplicaFleet(
        feed_root=feed_root,
        supervise_dir=supervise_dir,
        run_id="test-torn",
        n=1,
        base_env=_spawn_env(tmp_path, PATHWAY_CHAOS_PLAN=json.dumps(plan)),
        autoscale=False,
    )
    try:
        fleet.start()
        deadline = time.monotonic() + 240.0
        status = None
        while time.monotonic() < deadline:
            status = read_replica_statuses(supervise_dir, 1).get(0)
            if status and status.get("state") == "refused":
                break
            time.sleep(0.05)
        assert status is not None and status["state"] == "refused", status
        assert "checksum" in (status.get("refusal") or "")
        assert fleet.procs[0].poll() is None  # up, just out of rotation
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_retrieve(
                int(status["port"]), {"vectors": [[0.0] * DIM], "k": 1}
            )
        assert exc_info.value.code == 503
        assert json.loads(exc_info.value.read())["state"] == "refused"
    finally:
        fleet.stop()
