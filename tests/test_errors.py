"""terminate_on_error + error-log tables (reference internals/errors.py, graph.rs:996)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.columnar import Error
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.trace import EngineErrorWithTrace
from tests.utils import T


def _collect(table):
    rows = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = row
        else:
            rows.pop(key, None)

    pw.io.subscribe(table, on_change)
    return rows


def test_terminate_on_error_true_raises_with_trace():
    t = T(
        """
        | a
    1   | 1
    """
    )
    bad = t.select(b=pw.apply(lambda x: 1 / 0, t.a))
    _collect(bad)
    with pytest.raises(EngineErrorWithTrace):
        GraphRunner(G._current).run(terminate_on_error=True)


def test_terminate_on_error_false_poisons_and_logs():
    t = T(
        """
        | a
    1   | 1
    2   | 2
    """
    )

    def sometimes(x):
        if x == 1:
            raise ValueError("bad row")
        return x * 10

    out = t.select(b=pw.apply(sometimes, t.a))
    log = pw.global_error_log()
    out_rows = _collect(out)
    log_rows = _collect(log)
    GraphRunner(G._current).run(terminate_on_error=False)
    values = sorted(
        (
            (int(row["b"]) if not isinstance(row["b"], Error) else "ERR")
            for row in out_rows.values()
        ),
        key=str,
    )
    assert values == [20, "ERR"]
    messages = [row["message"] for row in log_rows.values()]
    assert messages == ["ValueError: bad row"]
    assert all(isinstance(row["operator_id"], int) for row in log_rows.values())


def test_local_error_log_scopes_operators():
    t = T(
        """
        | a
    1   | 1
    """
    )
    with pw.local_error_log() as log:
        bad = t.select(b=pw.apply(lambda x: 1 / 0, t.a))
    log_rows = _collect(log)
    _collect(bad)
    GraphRunner(G._current).run(terminate_on_error=False)
    assert len(log_rows) == 1
    assert "ZeroDivisionError" in next(iter(log_rows.values()))["message"]
