"""Encoder service tests (ISSUE 11): continuous batching, pre-warmed jit
buckets, the semantic query cache's honesty contract (exact mode bitwise;
retraction/re-ingest isolation), the preserved shed/backpressure contract
through the coalescer shim, and the fence-replay exactly-once extension for
service-queued in-flight queries. All tier-1 (CPU, tiny encoder config)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.keys import KEY_DTYPE, pointer_from
from pathway_tpu.models.embed_pipeline import EmbedOverloadError, EmbedPipeline
from pathway_tpu.models.encoder import EncoderConfig, JaxSentenceEncoder
from pathway_tpu.models.encoder_service import (
    EncoderService,
    SemanticQueryCache,
    stop_all_workers,
)

pytestmark = pytest.mark.encsvc

TINY = EncoderConfig(
    vocab_size=8192, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=128
)


@pytest.fixture(scope="module")
def tiny_encoder() -> JaxSentenceEncoder:
    return JaxSentenceEncoder("pw-test-tiny", config=TINY, max_length=64)


def _tiny_embedder(**kwargs):
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    return SentenceTransformerEmbedder(
        model="pw-test-tiny", encoder_config=TINY, **kwargs
    )


def _hash_rows(texts):
    out = []
    for t in texts:
        h = np.frombuffer(str(t).encode().ljust(8, b"\0")[:8], dtype=np.uint8)
        out.append(h.astype(np.float32))
    return out


class _HashEncoder:
    """Instant deterministic encoder: row value encodes the text identity."""

    dim = 8

    def __init__(self):
        self.calls = []

    def encode_device(self, texts):
        self.calls.append(list(texts))
        return np.stack(_hash_rows(texts))


# ---------------------------------------------------------------------------
# SemanticQueryCache
# ---------------------------------------------------------------------------


def test_semantic_cache_exact_mode_normalized_key():
    cache = SemanticQueryCache(8, mode="exact")
    vec = np.arange(4, dtype=np.float32)
    cache.put("what is rag?", vec)
    # whitespace runs and case fold onto the same canonical key
    hit = cache.get("  What   is  RAG? ")
    assert hit is not None and np.array_equal(hit, vec)
    assert not hit.flags.writeable
    assert cache.get("what is ivf?") is None
    s = cache.stats()
    assert s["semantic_exact_hits"] == 1 and s["semantic_misses"] == 1
    assert s["semantic_cosine_hits"] == 0  # exact mode never fuzzy-matches


def test_semantic_cache_lru_eviction_and_off_mode():
    cache = SemanticQueryCache(2, mode="exact")
    v = np.ones(2, dtype=np.float32)
    cache.put("a", v)
    cache.put("b", v * 2)
    cache.put("c", v * 3)  # evicts "a"
    assert cache.get("a") is None
    assert np.array_equal(cache.get("c"), v * 3)
    assert cache.stats()["semantic_evictions"] == 1
    off = SemanticQueryCache(8, mode="off")
    off.put("a", v)
    assert off.get("a") is None and len(off) == 0


def test_semantic_cache_cosine_mode_near_match():
    cache = SemanticQueryCache(8, mode="cosine", threshold=0.8)
    vec = np.arange(4, dtype=np.float32)
    cache.put("how do i restart a crashed worker rank", vec)
    # near-duplicate phrasing: high bag-of-words cosine, different exact key
    hit = cache.get("how do i restart a crashed worker")
    assert hit is not None and np.array_equal(hit, vec)
    assert cache.stats()["semantic_cosine_hits"] == 1
    # unrelated text stays a miss even in cosine mode
    assert cache.get("tumbling window aggregation semantics") is None


def test_semantic_cache_cosine_threshold_respected():
    strict = SemanticQueryCache(8, mode="cosine", threshold=0.999)
    strict.put("alpha beta gamma delta", np.ones(2, dtype=np.float32))
    assert strict.get("alpha beta gamma epsilon") is None  # below threshold
    assert strict.get("alpha  BETA gamma delta") is not None  # exact canonical key


# ---------------------------------------------------------------------------
# EncoderService: continuous batching
# ---------------------------------------------------------------------------


def test_service_solo_submit_no_deadline_wait():
    """A solo request dispatches the moment the worker is free — well under
    any deadline-window latency (the legacy path waited max_wait_ms)."""
    enc = _HashEncoder()
    svc = EncoderService(enc, tick_ms=5_000.0, prewarm=False)  # absurd tick
    t0 = time.perf_counter()
    out = svc.submit(["solo"])
    elapsed = time.perf_counter() - t0
    assert np.array_equal(out[0], _hash_rows(["solo"])[0])
    assert elapsed < 2.0, f"solo submit waited for a window: {elapsed:.3f}s"
    svc.close()


def test_service_concurrent_clients_coalesce_and_get_own_rows():
    release = threading.Event()
    first_gate = [True]

    class _GatedHashEncoder(_HashEncoder):
        def encode_device(self, texts):
            if first_gate[0]:
                first_gate[0] = False
                release.wait(timeout=10)  # hold tick 1 so a burst piles up
            return super().encode_device(texts)

    enc = _GatedHashEncoder()
    svc = EncoderService(enc, prewarm=False)
    results: dict = {}

    def client(i: int) -> None:
        results[i] = svc.submit([f"query {i}"])[0]

    threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
    threads[0].start()
    time.sleep(0.2)  # worker now held inside tick 1
    for t in threads[1:]:
        t.start()
    deadline = time.monotonic() + 5.0
    while svc.queue_depth_rows() < 16 and time.monotonic() < deadline:
        time.sleep(0.01)
    release.set()
    for t in threads:
        t.join(timeout=10)
    for i in range(16):  # every client got exactly ITS row
        assert np.array_equal(results[i], _hash_rows([f"query {i}"])[0]), i
    assert svc.ticks < svc.requests  # the pile-up coalesced into fewer ticks
    assert svc.max_tick_rows > 1
    assert svc.queue_depth_rows() == 0  # slots always released
    svc.close()


def test_service_dedups_identical_texts_within_tick():
    release = threading.Event()
    first_gate = [True]

    class _GatedHashEncoder(_HashEncoder):
        def encode_device(self, texts):
            if first_gate[0]:
                first_gate[0] = False
                release.wait(timeout=10)
            return super().encode_device(texts)

    enc = _GatedHashEncoder()
    svc = EncoderService(enc, prewarm=False)
    out: list = [None] * 8

    def client(i: int) -> None:
        out[i] = svc.submit(["same question"])[0]

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    threads[0].start()
    time.sleep(0.2)
    for t in threads[1:]:
        t.start()
    deadline = time.monotonic() + 5.0
    while svc.queue_depth_rows() < 8 and time.monotonic() < deadline:
        time.sleep(0.01)
    release.set()
    for t in threads:
        t.join(timeout=10)
    expect = _hash_rows(["same question"])[0]
    assert all(np.array_equal(v, expect) for v in out)
    # the duplicate text encoded once per tick, not once per client
    assert sum(len(b) for b in enc.calls) == svc.ticks
    assert svc.dedup_rows == 8 - svc.ticks
    svc.close()


def test_service_error_propagates_and_releases_slots():
    class _FailingEncoder:
        dim = 4

        def encode_device(self, texts):
            raise RuntimeError("encoder exploded")

    svc = EncoderService(_FailingEncoder(), prewarm=False)
    with pytest.raises(RuntimeError, match="encoder exploded"):
        svc.submit(["x"])
    assert svc.queue_depth_rows() == 0  # the leak_inflight invariant, live
    # the worker survives a failing tick
    svc.encoder = _HashEncoder()
    assert np.array_equal(svc.submit(["later"])[0], _hash_rows(["later"])[0])
    svc.close()


def test_service_large_tick_splits_length_sorted():
    enc = _HashEncoder()
    svc = EncoderService(enc, sub_batch=4, prewarm=False)
    texts = [f"{'w ' * (i % 7 + 1)}q{i}" for i in range(10)]
    out = svc.submit(texts)
    for i, t in enumerate(texts):
        assert np.array_equal(out[i], _hash_rows([t])[0]), i
    # one submission of 10 rows with sub_batch=4 → 3 length-sorted dispatches
    assert len(enc.calls) == 3
    assert sorted(len(b) for b in enc.calls) == [2, 4, 4]
    lengths = [len(t.split()) for b in enc.calls for t in b]
    assert lengths == sorted(lengths), "packing was not length-sorted"
    svc.close()


# ---------------------------------------------------------------------------
# pre-warm: startup honesty
# ---------------------------------------------------------------------------


def test_prewarm_compiles_buckets_and_reports_wall_time(tiny_encoder):
    from pathway_tpu.engine import telemetry

    before = telemetry.stage_snapshot("embed.svc.").get("embed.svc.prewarm_s", 0.0)
    svc = EncoderService(
        tiny_encoder, prewarm=True, prewarm_max_batch=8, max_in_flight=8
    )
    assert svc.wait_warm(timeout_s=120.0), "pre-warm never finished"
    # batch bucket {8} x seq buckets {8,16,32,64} for max_length=64
    assert svc.prewarm_compiles == 4
    assert svc.prewarm_s > 0.0
    snap = telemetry.stage_snapshot("embed.svc.")
    assert snap.get("embed.svc.prewarm_s", 0.0) > before
    assert snap.get("embed.svc.prewarm_compiles", 0.0) >= 4
    stats = svc.stats()
    assert stats["svc_warm"] and stats["svc_prewarm_compiles"] == 4
    # warm path still answers correctly
    row = np.asarray(svc.submit(["warm bucket query"])[0], dtype=np.float32)
    assert np.array_equal(row, tiny_encoder.encode(["warm bucket query"])[0])
    svc.close()


def test_stop_worker_aborts_prewarm_even_without_worker(tiny_encoder):
    """pw.run teardown (stop_all_workers) must cancel an in-flight pre-warm
    compile matrix even when no query ever spawned a worker — the abort rides
    its own event, not the worker's _stop_requested flag."""
    svc = EncoderService(
        tiny_encoder, prewarm=True, prewarm_max_batch=256, max_in_flight=256
    )
    svc.stop_worker()
    pt = svc._prewarm_thread
    assert pt is None or not pt.is_alive(), "pre-warm thread survived stop_worker"
    assert svc._prewarm_abort.is_set()
    assert svc.warm  # nobody blocks on wait_warm after an abort
    svc.close()


def test_prewarm_skipped_for_non_jax_encoders():
    svc = EncoderService(_HashEncoder(), prewarm=True)
    assert svc.warm  # nothing to compile: warm immediately, no thread spun
    assert svc.prewarm_compiles == 0
    svc.close()


# ---------------------------------------------------------------------------
# pipeline integration: semantic cache honesty
# ---------------------------------------------------------------------------


def _wait_cache_fill(pipe: EmbedPipeline, n: int, timeout: float = 10.0) -> None:
    deadline = time.monotonic() + timeout
    while len(pipe.cache) < n and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(pipe.cache) >= n, "after-batch cache fill never ran"


def test_exact_mode_hit_is_bitwise_identical_to_direct_encode(tiny_encoder):
    pipe = EmbedPipeline(tiny_encoder, model="t", cache_size=64, prewarm=False)
    pipe.embed_query_rows(["What is a Vector  Index?"])
    _wait_cache_fill(pipe, 1)
    variant = "  what IS a vector index?  "
    row = pipe.embed_query_rows([variant])[0]
    assert pipe.semantic_cache.stats()["semantic_exact_hits"] == 1
    direct = tiny_encoder.encode([variant])[0]
    assert np.array_equal(np.asarray(row, dtype=np.float32), direct), (
        "exact-mode semantic hit is not bitwise-identical to a fresh encode"
    )
    stop_all_workers()


def test_semantic_hit_skips_the_forward_entirely(tiny_encoder):
    pipe = EmbedPipeline(tiny_encoder, model="t2", cache_size=64, prewarm=False)
    calls = []
    orig = tiny_encoder.encode_device
    tiny_encoder.encode_device = lambda t: (calls.append(list(t)), orig(t))[1]
    try:
        pipe.embed_query_rows(["semantic skip test"])
        _wait_cache_fill(pipe, 1)
        n_before = sum(len(b) for b in calls)
        pipe.embed_query_rows(["  SEMANTIC   skip   test "])
        assert sum(len(b) for b in calls) == n_before  # no new forward rows
    finally:
        tiny_encoder.encode_device = orig
    stop_all_workers()


def test_cosine_mode_is_opt_in_and_off_by_default(tiny_encoder):
    pipe = EmbedPipeline(tiny_encoder, model="t3", cache_size=64, prewarm=False)
    assert pipe.semantic_cache.mode == "exact"
    pipe2 = EmbedPipeline(
        tiny_encoder, model="t4", cache_size=64, prewarm=False,
        semantic_mode="cosine", semantic_threshold=0.8,
    )
    assert pipe2.semantic_cache.mode == "cosine"
    stop_all_workers()


def test_reingest_never_served_from_semantic_cache(tiny_encoder):
    """The ingest path (encode_batch) must not consult the semantic cache: a
    poisoned semantic entry for the same canonical text must never leak into
    document embeddings on re-ingest."""
    pipe = EmbedPipeline(tiny_encoder, model="t5", cache_size=64, prewarm=False)
    text = "document chunk about cats"
    truth = pipe.encode_batch([text])[0]
    # plant a poisoned semantic entry under the same canonical key
    pipe.semantic_cache.put(text, np.full(TINY.hidden_size, 777.0, dtype=np.float32))
    pipe.cache.clear()  # force the content cache to miss on re-ingest
    again = pipe.encode_batch(["  DOCUMENT chunk about cats  "])[0]
    assert not np.array_equal(again, np.full(TINY.hidden_size, 777.0)), (
        "re-ingest was served from the semantic query cache"
    )
    reingest = pipe.encode_batch([text])[0]
    assert np.array_equal(reingest, truth)
    stop_all_workers()


def test_retractions_never_reach_semantic_cache():
    """device_expression is deterministic=False: retraction rows replay from
    the engine memo — neither the service, the content cache, nor the semantic
    cache may see them (a semantic near-match answering a retraction would
    break the bit-identical replay contract)."""
    import pathway_tpu as pw
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg

    emb = _tiny_embedder(embed_cache_size=64, encsvc_prewarm=False)
    forwards = []
    orig = emb.encoder.encode_device
    emb.encoder.encode_device = lambda t: (forwards.append(list(t)), orig(t))[1]

    sem_gets = []
    orig_get = emb.pipeline.semantic_cache.get
    emb.pipeline.semantic_cache.get = lambda t: (sem_gets.append(t), orig_get(t))[1]

    pg.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"q": str}),
        [("what is a cat", 0, 1), ("what is a dog", 0, 1), ("what is a cat", 2, -1)],
        is_stream=True,
    )
    res = t.select(v=emb.device_expression(t.q))
    got = []
    pw.io.subscribe(
        res,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["v"], diffs.tolist())
        ),
    )
    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    # the two inserts consulted the caches; the retraction consulted NOTHING
    # (replayed from the evaluator memo): 2 lookups, 2 forward rows, no more
    assert len(sem_gets) == 2
    assert sum(len(b) for b in forwards) == 2
    ret = [np.asarray(v) for v, d in got if d == -1]
    ins = [np.asarray(v) for v, d in got if d == 1]
    assert len(ret) == 1 and any(np.array_equal(ret[0], v) for v in ins)


# ---------------------------------------------------------------------------
# shed/backpressure contract preserved through the coalescer shim
# ---------------------------------------------------------------------------


def test_shim_sheds_with_honest_retry_after_when_service_backed_up():
    from pathway_tpu.engine import telemetry

    release = threading.Event()

    class _GatedEncoder:
        dim = 4

        def encode_device(self, texts):
            release.wait(timeout=10)
            return np.zeros((len(texts), 4), dtype=np.float32)

    pipe = EmbedPipeline(
        _GatedEncoder(), model="shed", cache_size=0, max_queue_rows=2,
        prewarm=False,
    )
    assert pipe.coalescer._service is pipe.service  # shim mode active
    done: dict = {}

    def client(name, texts):
        done[name] = pipe.coalescer.embed(texts)

    ta = threading.Thread(target=client, args=("a", ["a"]))
    ta.start()
    deadline = time.perf_counter() + 5.0
    # row a is in flight (worker holds it inside encode_device)
    while pipe.service.queue_depth_rows() != 1:
        assert time.perf_counter() < deadline, "worker never picked up row a"
        time.sleep(0.01)
    tb = threading.Thread(target=client, args=("b", ["b"]))
    tb.start()
    while pipe.service.queue_depth_rows() != 2:
        assert time.perf_counter() < deadline, "row b never queued"
        time.sleep(0.01)

    assert pipe.coalescer.overloaded()
    shed_before = telemetry.stage_snapshot("embed.").get("embed.shed", 0.0)
    with pytest.raises(EmbedOverloadError) as exc_info:
        pipe.coalescer.embed(["c"])
    assert exc_info.value.retry_after_s >= 1.0
    assert pipe.coalescer.shed_requests == 1
    assert telemetry.stage_snapshot("embed.").get("embed.shed", 0.0) == shed_before + 1
    # the engine path (already admitted at the REST boundary) still bypasses
    done["d"] = None
    td = threading.Thread(
        target=lambda: done.update(d=pipe.coalescer.embed(["d"], enforce_cap=False))
    )
    td.start()
    release.set()
    for t in (ta, tb, td):
        t.join(timeout=10)
    assert all(done[k] is not None for k in ("a", "b", "d"))
    # queue drained: admission opens again, no sticky overload
    assert not pipe.coalescer.overloaded()
    assert len(pipe.coalescer.embed(["e"])) == 1
    pipe.service.close()


# ---------------------------------------------------------------------------
# fence replay: service-queued in-flight queries, exactly once
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_fence_replay_service_inflight_queries_exactly_once():
    """The PR-3 replay contract extended to the encoder service: a fence
    aborts the commit AFTER service-queued queries were encoded but before
    results committed; the replay with a fresh memo must answer every query
    exactly once with identical values, absorbed by the content cache — the
    service's forward must not run a second time, and the semantic cache must
    not have answered any retraction."""
    from pathway_tpu.engine.expression_evaluator import evaluate

    emb = _tiny_embedder(embed_cache_size=64, encsvc_prewarm=False)
    assert emb.pipeline.service is not None  # the service path is under test
    forwards = []
    orig = emb.encoder.encode_device
    emb.encoder.encode_device = lambda t: (forwards.append(list(t)), orig(t))[1]

    texts = np.array(
        [f"inflight svc query {i}" for i in range(4)] + ["inflight svc query 0"],
        dtype=object,
    )
    e = emb.device_expression(expr.ColumnReference(None, "q"))
    keys = np.empty(len(texts), dtype=KEY_DTYPE)
    for i in range(len(texts)):
        p = pointer_from(f"row{i}")
        keys[i] = (p.hi, p.lo)

    def run_commit(memo: dict, diffs: np.ndarray) -> np.ndarray:
        return evaluate(
            e,
            len(texts),
            lambda ref: texts,
            keys=keys,
            diffs=diffs,
            memo=memo,
            memo_tokens={id(e): "nd0"},
        )

    ins = np.ones(len(texts), dtype=np.int64)
    first = run_commit({}, ins)
    n_rows_first = sum(len(b) for b in forwards)
    assert n_rows_first == 4  # 5 rows, 1 duplicate deduped in the tick
    assert emb.pipeline.service.ticks >= 1

    _wait_cache_fill(emb.pipeline, 4, timeout=30.0)

    # FENCE: evaluator state reset → lockstep replay with a FRESH memo
    memo_after: dict = {}
    replay = run_commit(memo_after, ins)
    assert len(replay) == len(first) == len(texts)
    for i in range(len(texts)):
        assert np.array_equal(np.asarray(first[i]), np.asarray(replay[i])), i
    # absorbed by the content cache: the service ran no new forward rows
    assert sum(len(b) for b in forwards) == n_rows_first

    # post-fence retraction: engine memo replay, no cache/service involvement
    sem_before = emb.pipeline.semantic_cache.stats()
    retr = run_commit(memo_after, -np.ones(len(texts), dtype=np.int64))
    assert sum(len(b) for b in forwards) == n_rows_first
    sem_after = emb.pipeline.semantic_cache.stats()
    assert sem_after["semantic_exact_hits"] == sem_before["semantic_exact_hits"]
    assert sem_after["semantic_cosine_hits"] == sem_before["semantic_cosine_hits"]
    for i in range(len(texts)):
        assert np.array_equal(np.asarray(retr[i]), np.asarray(replay[i]))
    assert len(memo_after["nd0"]) == 0  # memo entries popped on retraction
