"""Supervisor unit tests: exit-code aggregation, restart policy, heartbeat
staleness, post-mortem reporting — with tiny no-dependency child programs so
the supervision logic is exercised without engine startup cost."""

from __future__ import annotations

import os
import sys
import textwrap

from pathway_tpu.parallel.supervisor import Supervisor, describe_exit, status_path

CLEAN_PROG = "import sys; sys.exit(0)\n"

# writes its rank status (the shape pw.run publishes), then rank 0 SIGKILLs
# itself on the FIRST incarnation only — exactly the failover scenario
CRASH_ONCE_PROG = textwrap.dedent(
    """
    import json, os, signal, time
    d = os.environ["PATHWAY_SUPERVISE_DIR"]
    rank = int(os.environ["PATHWAY_PROCESS_ID"])
    persistence = os.environ.get("PW_TEST_PERSISTENCE", "1") == "1"
    path = os.path.join(d, f"rank-{rank}.status.json")
    with open(path + ".tmp", "w") as f:
        json.dump({"pid": os.getpid(), "rank": rank, "commit": 7,
                   "persistence": persistence, "peers": {}, "ts": time.time()}, f)
    os.replace(path + ".tmp", path)
    time.sleep(0.5)  # let every rank publish before the crash
    if rank == 0 and os.environ.get("PATHWAY_RESTART_COUNT") == "0":
        os.kill(os.getpid(), signal.SIGKILL)
    """
)

WEDGED_PROG = textwrap.dedent(
    """
    import json, os, time
    d = os.environ["PATHWAY_SUPERVISE_DIR"]
    rank = int(os.environ["PATHWAY_PROCESS_ID"])
    path = os.path.join(d, f"rank-{rank}.status.json")
    with open(path + ".tmp", "w") as f:
        json.dump({"pid": os.getpid(), "rank": rank, "commit": 1,
                   "persistence": False, "peers": {}, "ts": time.time()}, f)
    os.replace(path + ".tmp", path)
    time.sleep(120)  # wedged: status never refreshes, process never exits
    """
)


# like CRASH_ONCE_PROG, but rank 0 SIGKILLs itself on run 0 AND run 1 — the
# surgical replacement dies too, forcing the restart-all fallback rung
CRASH_TWICE_PROG = CRASH_ONCE_PROG.replace(
    'os.environ.get("PATHWAY_RESTART_COUNT") == "0"',
    'os.environ.get("PATHWAY_RESTART_COUNT") in ("0", "1")',
)


def _supervisor(tmp_path, prog_text, *, n=2, max_restarts=0, stale_after=0.0,
                env=None, restart_mode="surgical"):
    prog = tmp_path / "prog.py"
    prog.write_text(prog_text)
    env_base = os.environ.copy()
    env_base.update(env or {})
    return Supervisor(
        processes=n,
        threads=1,
        first_port=0,  # children here never open the exchange
        program=sys.executable,
        arguments=[str(prog)],
        env_base=env_base,
        max_restarts=max_restarts,
        restart_mode=restart_mode,
        stale_after_s=stale_after,
        poll_interval_s=0.05,
    )


def test_clean_cluster_exits_zero(tmp_path):
    sup = _supervisor(tmp_path, CLEAN_PROG)
    assert sup.run() == 0
    assert sup.restarts_used == 0


def test_crash_with_persistence_restarts_and_succeeds(tmp_path):
    sup = _supervisor(tmp_path, CRASH_ONCE_PROG, max_restarts=1)
    assert sup.run() == 0, "restart should have recovered the cluster"
    assert sup.restarts_used == 1


def test_surgical_mode_relaunches_only_the_dead_rank(tmp_path, capsys):
    """Default mode: rank 0's crash relaunches rank 0 ONLY — the survivor is
    neither terminated nor relaunched, and the epoch advances."""
    sup = _supervisor(tmp_path, CRASH_ONCE_PROG, max_restarts=1)
    assert sup.run() == 0
    assert sup.restarts_used == 1
    assert sup.cluster_epoch == 1
    err = capsys.readouterr().err
    assert "surgically relaunching rank 0 only" in err
    assert "restarting the cluster" not in err
    assert "terminated by supervisor" not in err


def test_restart_mode_all_skips_surgical(tmp_path, capsys):
    sup = _supervisor(tmp_path, CRASH_ONCE_PROG, max_restarts=1, restart_mode="all")
    assert sup.run() == 0
    err = capsys.readouterr().err
    assert "restarting the cluster" in err
    assert "surgically relaunching" not in err


def test_surgical_replacement_crash_falls_back_to_restart_all(tmp_path, capsys):
    """The relaunched rank dies again while the rejoin is in flight: the
    supervisor must degrade to restart-all (budget permitting) and recover."""
    sup = _supervisor(tmp_path, CRASH_TWICE_PROG, max_restarts=2)
    assert sup.run() == 0
    assert sup.restarts_used == 2
    err = capsys.readouterr().err
    assert "surgically relaunching rank 0 only" in err
    assert "falling back to restart-all" in err
    assert "restarting the cluster" in err


def test_crash_without_persistence_refuses_restart(tmp_path, capsys):
    sup = _supervisor(
        tmp_path, CRASH_ONCE_PROG, max_restarts=3, env={"PW_TEST_PERSISTENCE": "0"}
    )
    rc = sup.run()
    assert rc != 0
    assert sup.restarts_used == 0, "must not restart when the journal can't restore"
    err = capsys.readouterr().err
    assert "post-mortem" in err
    assert "persistence is off" in err
    assert "killed by signal SIGKILL" in err
    # the SIGKILL came from the program itself, not from the supervisor
    assert "signal was external (chaos plan or operator)" in err
    assert "epoch 0 at death" in err


def test_restart_budget_exhausted_reports_and_fails(tmp_path, capsys):
    sup = _supervisor(tmp_path, CRASH_ONCE_PROG, max_restarts=0)
    rc = sup.run()
    assert rc != 0
    err = capsys.readouterr().err
    assert "restart budget exhausted" in err
    assert "last commit 7" in err  # per-rank post-mortem carries progress


def test_wedged_rank_detected_by_heartbeat_staleness(tmp_path, capsys):
    sup = _supervisor(tmp_path, WEDGED_PROG, n=1, stale_after=1.0)
    rc = sup.run()
    assert rc != 0
    err = capsys.readouterr().err
    assert "stale" in err and "wedged" in err
    # post-mortem attributes the kill to the supervisor, not to chaos/operator
    assert "killed by supervisor for staleness" in err
    assert "signal was external" not in err


def test_clean_exit_straggler_is_a_cluster_event(tmp_path, capsys, monkeypatch):
    """A rank that exits 0 while its peers keep running (rank-conditional
    sys.exit in the program) must surface as a failure after the drain grace —
    lockstep shutdown means legitimate clean exits land together, and fenced
    survivors must not wait a full fence timeout for a replacement the
    supervisor would never launch."""
    monkeypatch.setenv("PATHWAY_SUPERVISOR_DRAIN_S", "0.5")
    prog = textwrap.dedent(
        """
        import os, sys, time
        if int(os.environ["PATHWAY_PROCESS_ID"]) == 0:
            sys.exit(0)
        time.sleep(60)
        """
    )
    sup = _supervisor(tmp_path, prog)
    rc = sup.run()
    assert rc != 0
    err = capsys.readouterr().err
    assert "exited 0 while peers kept running" in err


def test_startup_wedge_detected_without_any_status(tmp_path, capsys, monkeypatch):
    """A rank that hangs BEFORE its first commit (no status file ever) is still
    caught — by the startup grace deadline, not the staleness monitor."""
    monkeypatch.setenv("PATHWAY_SUPERVISOR_STARTUP_S", "1")
    sup = _supervisor(tmp_path, "import time; time.sleep(60)\n", n=1)
    rc = sup.run()
    assert rc != 0
    assert "wedged at startup" in capsys.readouterr().err


def test_describe_exit_names_signals():
    assert describe_exit(0) == "exit code 0"
    assert describe_exit(-9) == "killed by signal SIGKILL"
    assert describe_exit(None) == "running"


def test_status_path_layout(tmp_path):
    assert status_path(str(tmp_path), 3).endswith("rank-3.status.json")


def test_post_mortem_attaches_flight_recorder_summary(tmp_path, capsys):
    """A rank that left a flight-recorder dump behind gets it named in the
    post-mortem — path + one-line summary — and the file is preserved outside
    the supervise dir before that dir is rmtree'd."""
    import json
    import re

    prog = textwrap.dedent(
        """
        import json, os, signal, time
        d = os.environ["PATHWAY_SUPERVISE_DIR"]
        rank = int(os.environ["PATHWAY_PROCESS_ID"])
        path = os.path.join(d, f"rank-{rank}.status.json")
        with open(path + ".tmp", "w") as f:
            json.dump({"pid": os.getpid(), "rank": rank, "commit": 7,
                       "persistence": False, "peers": {}, "ts": time.time()}, f)
        os.replace(path + ".tmp", path)
        if rank == 0:
            dump = os.path.join(d, "flight-rank-0.json")
            with open(dump, "w") as f:
                json.dump({"reason": "crash: Boom", "rank": 0, "profiles": [],
                           "events": [],
                           "summary": {"last_commit": 6,
                                       "slowest_operator": {"name": "groupby",
                                                            "kind": "groupby",
                                                            "seconds": 0.25},
                                       "pending_barrier": "12:3:i0"}}, f)
            time.sleep(0.3)
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(0.5)
        """
    )
    sup = _supervisor(tmp_path, prog)
    rc = sup.run()
    assert rc != 0
    err = capsys.readouterr().err
    assert "flight recorder" in err
    assert "last commit 6" in err
    assert "slowest operator groupby (250.0 ms)" in err
    assert "pending barrier 12:3:i0" in err
    m = re.search(r"flight recorder (\S+):", err)
    assert m, err
    kept = m.group(1)
    try:
        assert os.path.exists(kept), "dump must be preserved past supervise-dir cleanup"
        assert json.load(open(kept))["summary"]["last_commit"] == 6
    finally:
        try:
            os.unlink(kept)
        except OSError:
            pass


def test_kill_wedged_sends_sigterm_before_sigkill(tmp_path, capsys, monkeypatch):
    """Stall-kill grace: the wedged rank gets SIGTERM first (the flight
    recorder's dump window); one that ignores it is SIGKILLed anyway."""
    import signal as signal_mod

    # the grace knob is read by the SUPERVISOR process, not the children
    monkeypatch.setenv("PATHWAY_SUPERVISOR_TERM_GRACE_S", "0.5")

    prog = textwrap.dedent(
        """
        import json, os, signal, time
        signal.signal(signal.SIGTERM, signal.SIG_IGN)  # worst case: ignores TERM
        d = os.environ["PATHWAY_SUPERVISE_DIR"]
        path = os.path.join(d, "rank-0.status.json")
        with open(path + ".tmp", "w") as f:
            json.dump({"pid": os.getpid(), "rank": 0, "commit": 1,
                       "persistence": False, "peers": {}, "ts": time.time()}, f)
        os.replace(path + ".tmp", path)
        time.sleep(120)
        """
    )
    sup = _supervisor(tmp_path, prog, n=1, stale_after=1.0)
    rc = sup.run()
    assert rc != 0
    assert sup.handles[0].returncode == -signal_mod.SIGKILL
    err = capsys.readouterr().err
    assert "killed by supervisor for staleness" in err


# rank 0 crashes on run 0; its surgical REPLACEMENT (run 1) wedges without
# ever adopting the new epoch; run 2 (the restart-all rung) completes clean
WEDGED_REJOIN_PROG = textwrap.dedent(
    """
    import json, os, signal, time
    d = os.environ["PATHWAY_SUPERVISE_DIR"]
    rank = int(os.environ["PATHWAY_PROCESS_ID"])
    run = int(os.environ.get("PATHWAY_RESTART_COUNT", "0"))
    path = os.path.join(d, f"rank-{rank}.status.json")
    with open(path + ".tmp", "w") as f:
        json.dump({"pid": os.getpid(), "rank": rank, "commit": 7,
                   "persistence": True, "peers": {}, "epoch": 0,
                   "ts": time.time()}, f)
    os.replace(path + ".tmp", path)
    time.sleep(0.5)
    if rank == 0 and run == 0:
        os.kill(os.getpid(), signal.SIGKILL)
    if rank == 0 and run == 1:
        time.sleep(120)  # wedged rejoin: epoch never converges
    time.sleep(2)
    """
)


def test_wedged_rejoin_hits_deadline_and_escalates(tmp_path, capsys, monkeypatch):
    """A surgical rejoin that never converges must not strand survivors for
    the fence/staleness bounds: past PATHWAY_SUPERVISOR_REJOIN_DEADLINE_S the
    replacement is shot and recovery escalates to restart-all."""
    monkeypatch.setenv("PATHWAY_SUPERVISOR_REJOIN_DEADLINE_S", "1.5")
    sup = _supervisor(tmp_path, WEDGED_REJOIN_PROG, max_restarts=2)
    assert sup.run() == 0, "restart-all should have recovered the cluster"
    assert sup.restarts_used == 2
    err = capsys.readouterr().err
    assert "surgically relaunching rank 0 only" in err
    assert "rejoin did not converge within 2s" in err
    assert "falling back to restart-all" in err
    assert "restarting the cluster" in err


def test_status_file_carries_checkpoint_fields(tmp_path):
    """write_status publishes the recovery-SLO pair (checkpoint base commit +
    journal tail frames) the post-mortems and /healthz consumers read."""
    import json as json_mod

    from pathway_tpu.parallel.supervisor import write_status

    write_status(
        str(tmp_path), 0, commit=9, persistence=True,
        checkpoint_commit=42, journal_tail_frames=7,
    )
    payload = json_mod.load(open(status_path(str(tmp_path), 0)))
    assert payload["checkpoint_commit"] == 42
    assert payload["journal_tail_frames"] == 7


def test_post_mortem_names_last_cluster_checkpoint(tmp_path, capsys):
    """Triage needs to know what a recovery would have cost: the post-mortem
    names the checkpoint base + journal tail when the rank published one."""
    prog = textwrap.dedent(
        """
        import json, os, signal, time
        d = os.environ["PATHWAY_SUPERVISE_DIR"]
        rank = int(os.environ["PATHWAY_PROCESS_ID"])
        path = os.path.join(d, f"rank-{rank}.status.json")
        with open(path + ".tmp", "w") as f:
            json.dump({"pid": os.getpid(), "rank": rank, "commit": 50,
                       "persistence": False, "peers": {},
                       "checkpoint_commit": 42, "journal_tail_frames": 7,
                       "ts": time.time()}, f)
        os.replace(path + ".tmp", path)
        time.sleep(0.5)
        os.kill(os.getpid(), signal.SIGKILL)
        """
    )
    sup = _supervisor(tmp_path, prog, n=1)
    assert sup.run() != 0
    err = capsys.readouterr().err
    assert "last cluster checkpoint at commit 42 (+7 journal tail frame(s))" in err
