"""Multi-process persistence torture: ``spawn -n 2`` + fs persistence backend,
kill -9 each process once (mid-run), restart, EXACT global output — the
reference's wordcount torture matrix (``integration_tests/wordcount/base.py:320``,
``test_new_data.py:21-23``) at n=2 (VERDICT r3 item 6).

Cluster resume semantics: journal-only (operator snapshots are wall-clock-driven
and unsynchronized across processes, so the runner disables them under spawn);
on restart every process replays the UNION of journaled commit ids in lockstep,
so journals that differ by a trailing commit (the kill window) re-align."""

from __future__ import annotations

import collections
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROG = textwrap.dedent(
    """
    import json, os, signal, threading, time
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    kill_pid = os.environ.get("PW_TEST_KILL_PID")
    marker = os.environ.get("PW_TEST_KILL_MARKER", "")

    if kill_pid is not None and int(kill_pid) == pid:
        def _assassin():
            # progress-gated, not wall-clock: the kill must land mid-RUN (after
            # commits + journal frames + supervisor status exist), not during
            # the multi-second interpreter/jax import window. The per-rank
            # status file is per-INCARNATION (the supervisor clears it on every
            # launch and it carries this process's pid), unlike output files
            # which linger from earlier phases.
            spath = os.path.join(
                os.environ["PATHWAY_SUPERVISE_DIR"], f"rank-{pid}.status.json"
            )
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    if json.load(open(spath))["pid"] == os.getpid():
                        break
                except Exception:
                    pass
                time.sleep(0.05)
            time.sleep(0.5)
            try:
                # O_EXCL: exactly one kill per marker even across restarts
                fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
            except FileExistsError:
                return
            os.kill(os.getpid(), signal.SIGKILL)
        threading.Thread(target=_assassin, daemon=True).start()

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

    out_path = os.path.join(tmp, f"out_{pid}.json")
    rows = {}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(repr(key), None)
        with open(out_path + ".tmp", "w") as f:
            json.dump(list(rows.values()), f)
        os.replace(out_path + ".tmp", out_path)

    pw.io.subscribe(counts, on_change)
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(os.path.join(tmp, "store")),
        snapshot_interval_ms=10,  # must be IGNORED under spawn (journal-only resume)
    )
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    """
)


def _spawn_popen(tmp_path, first_port: int, kill_pid: int | None, marker: str,
                 max_restarts: int = 0, restart_mode: "str | None" = None):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if kill_pid is not None:
        env["PW_TEST_KILL_PID"] = str(kill_pid)
        env["PW_TEST_KILL_MARKER"] = marker
    prog = tmp_path / "prog.py"
    prog.write_text(PROG)
    mode_args = ["--restart-mode", restart_mode] if restart_mode else []
    return subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(first_port),
            "--max-restarts", str(max_restarts), *mode_args,
            sys.executable, str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        start_new_session=True,  # killpg reaches the spawned children too
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def _read_merged(tmp_path) -> dict:
    merged: dict = {}
    owners: collections.Counter = collections.Counter()
    for p in range(2):
        path = tmp_path / f"out_{p}.json"
        if not path.exists():
            continue
        try:
            for r in json.loads(path.read_text()):
                merged[r["word"]] = r["total"]
                owners[r["word"]] += 1
        except ValueError:
            pass
    assert all(v == 1 for v in owners.values()), f"duplicate owners: {owners}"
    return merged


def _terminate_group(proc) -> None:
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    try:
        proc.wait(timeout=20)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()


def test_spawn_kill9_each_process_restart_exact(tmp_path):
    (tmp_path / "in").mkdir()
    first_port = 24000 + os.getpid() % 500 * 4

    # several files so the hash-shard placement gives BOTH processes input
    for i in range(4):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    # phase 1: kill -9 process 0 mid-run; the peer must fail loudly, not hang
    proc = _spawn_popen(tmp_path, first_port, 0, str(tmp_path / "marker0"))
    rc = proc.wait(timeout=120)
    assert rc != 0, "cluster survived a SIGKILL'd member without reporting failure"
    assert (tmp_path / "marker0").exists(), "kill thread never fired"

    # new data while the cluster is down
    (tmp_path / "in" / "b.csv").write_text("word\n" + "\n".join(["cat"] * 2 + ["owl"] * 4) + "\n")

    # phase 2: restart, kill -9 process 1 this time
    proc = _spawn_popen(tmp_path, first_port, 1, str(tmp_path / "marker1"))
    rc = proc.wait(timeout=120)
    assert rc != 0
    assert (tmp_path / "marker1").exists()

    (tmp_path / "in" / "c.csv").write_text("word\n" + "\n".join(["owl"] * 1 + ["elk"] * 5) + "\n")

    # phase 3: restart with no kill; resumed journals + new data -> exact totals
    expected = {
        "cat": sum(i + 1 for i in range(4)) + 2,  # 12
        "dog": 8,
        "owl": 5,
        "elk": 5,
    }
    proc = _spawn_popen(tmp_path, first_port, None, "")
    try:
        deadline = time.time() + 120
        merged: dict = {}
        while time.time() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(f"spawn exited early (rc={proc.returncode}): {err}")
            merged = _read_merged(tmp_path)
            if merged == expected:
                break
            time.sleep(0.3)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        _terminate_group(proc)


def test_spawn_kill9_single_worker_supervised_failover(tmp_path):
    """Single-worker failover, ONE spawn invocation: rank 0 SIGKILLs itself
    mid-run, the supervisor restarts the cluster from the journal (pinned to
    ``--restart-mode all`` — the PR 2 rung; surgical mode is covered by
    ``test_rejoin.py``), and the merged output converges to the exact totals —
    no operator in the loop."""
    (tmp_path / "in").mkdir()
    first_port = 24000 + os.getpid() % 500 * 4 + 2

    for i in range(4):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    marker = str(tmp_path / "marker-failover")
    proc = _spawn_popen(tmp_path, first_port, 0, marker, max_restarts=2,
                        restart_mode="all")
    err = ""
    try:
        # wait for the SIGKILL to actually land, THEN add data only the
        # restarted cluster can count — converged pre-kill output files linger
        # on disk, so totals alone cannot prove the failover happened
        deadline = time.time() + 60
        while time.time() < deadline and not os.path.exists(marker):
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"supervised spawn exited early (rc={proc.returncode}): {err}"
                )
            time.sleep(0.1)
        assert os.path.exists(marker), "kill thread never fired"
        (tmp_path / "in" / "late.csv").write_text(
            "word\n" + "\n".join(["owl"] * 3) + "\n"
        )
        expected = {"cat": sum(i + 1 for i in range(4)), "dog": 8, "owl": 3}
        deadline = time.time() + 120
        merged: dict = {}
        while time.time() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"supervised spawn exited early (rc={proc.returncode}): {err}"
                )
            merged = _read_merged(tmp_path)
            if merged == expected:
                break
            time.sleep(0.3)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            _, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            _, err = proc.communicate()
    assert "restarting the cluster" in (err or ""), (
        f"supervisor never reported the failover restart:\n{err}"
    )
