"""EmbedPipeline tests (ISSUE 4): overlapped length-sorted encode, query
coalescing, content-hash cache, and their interaction with the engine's
memoize-on-retraction and fence-replay contracts. All tier-1 (CPU, tiny
encoder config); the torture-scale variants live behind the ``slow`` marker.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.keys import KEY_DTYPE, pointer_from
from pathway_tpu.internals.shapes import next_pow2
from pathway_tpu.models.embed_pipeline import EmbedCache, EmbedPipeline, QueryCoalescer
from pathway_tpu.models.encoder import EncoderConfig, HashTokenizer, JaxSentenceEncoder

TINY = EncoderConfig(
    vocab_size=8192, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=128
)


@pytest.fixture(scope="module")
def tiny_encoder() -> JaxSentenceEncoder:
    # nonexistent model name -> deterministic random init + HashTokenizer
    return JaxSentenceEncoder("pw-test-tiny", config=TINY, max_length=64)


def _tiny_embedder(**kwargs):
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    kwargs.setdefault("max_wait_ms", 1.0)
    return SentenceTransformerEmbedder(
        model="pw-test-tiny", encoder_config=TINY, **kwargs
    )


# -- shared pow2 util ---------------------------------------------------------


def test_next_pow2_shared_rule():
    assert [next_pow2(n) for n in (0, 1, 2, 3, 8, 9, 1000)] == [1, 1, 2, 4, 8, 16, 1024]
    assert [next_pow2(n, floor=8) for n in (0, 1, 8, 9)] == [8, 8, 8, 16]
    # every former duplicate delegates to the one rule
    from pathway_tpu.models.encoder import _next_pow2 as enc_pow2
    from pathway_tpu.ops.knn import next_pow2 as knn_pow2
    from pathway_tpu.ops.segment import _next_pow2 as seg_pow2

    for n in (1, 5, 8, 9, 127, 128, 129):
        assert knn_pow2(n) == next_pow2(n)
        assert seg_pow2(n) == next_pow2(n)
        assert enc_pow2(n) == next_pow2(n, floor=8)


# -- vectorized HashTokenizer -------------------------------------------------


def _reference_tokenize(texts, vocab_size=30522, max_length=128):
    """The pre-vectorization per-word loop, kept as the parity oracle."""
    import xxhash

    n = len(texts)
    ids = np.zeros((n, max_length), dtype=np.int32)
    mask = np.zeros((n, max_length), dtype=np.int32)
    for i, text in enumerate(texts):
        words = str(text).lower().split()[: max_length - 2]
        toks = [101] + [
            2000 + (xxhash.xxh32_intdigest(w) % (vocab_size - 3000)) for w in words
        ] + [102]
        ids[i, : len(toks)] = toks
        mask[i, : len(toks)] = 1
    return ids, mask


def test_hash_tokenizer_vectorized_parity():
    texts = ["Hello World", "", "a b c d e f g h", "ONE two THREE", "x " * 200]
    tok = HashTokenizer()
    ids, mask = tok(texts)
    ref_ids, ref_mask = _reference_tokenize(texts)
    width = ids.shape[1]
    assert width <= 128  # trimmed to the longest row, not padded to max_length
    assert np.array_equal(ids, ref_ids[:, :width])
    assert np.array_equal(mask, ref_mask[:, :width])
    assert ref_ids[:, width:].sum() == 0  # nothing real was trimmed away
    # second call rides the word->id memo and must agree with the first
    ids2, mask2 = tok(texts)
    assert np.array_equal(ids, ids2) and np.array_equal(mask, mask2)


def test_hash_tokenizer_word_cache_bound():
    tok = HashTokenizer()
    tok._WORD_CACHE_MAX = 8
    tok([f"w{i}" for i in range(6)])
    assert len(tok._word_ids) == 6
    tok([f"v{i}" for i in range(6)])  # would exceed the cap -> memo resets
    assert len(tok._word_ids) == 6
    # correctness survives the reset
    ids_a, _ = tok(["w0 v0"])
    ids_b, _ = _reference_tokenize(["w0 v0"])
    assert np.array_equal(ids_a, ids_b[:, : ids_a.shape[1]])
    # the batch that TRIGGERS the overflow may itself mix cached and new words:
    # the reset must re-hash the cached ones too, not KeyError on them
    tok2 = HashTokenizer()
    tok2._WORD_CACHE_MAX = 4
    tok2(["alpha beta"])  # cached: alpha, beta
    ids_mix, _ = tok2(["alpha beta gamma delta epsilon"])  # overflow mid-batch
    ref_mix, _ = _reference_tokenize(["alpha beta gamma delta epsilon"])
    assert np.array_equal(ids_mix, ref_mix[:, : ids_mix.shape[1]])


# -- encoder: single copy + sorted sub-batch bitwise equivalence --------------


def test_encode_single_copy_float32(tiny_encoder):
    out = tiny_encoder.encode(["hello world"])
    assert out.dtype == np.float32
    assert out.shape == (1, TINY.hidden_size)


def test_sorted_subbatch_bitwise_equal(tiny_encoder):
    rng = np.random.default_rng(3)
    texts = [
        " ".join(f"word{rng.integers(0, 500)}" for _ in range(int(rng.integers(1, 40))))
        for _ in range(37)
    ]
    sync = tiny_encoder.encode(texts)
    piped, stats = tiny_encoder.encode_pipelined(texts, sub_batch=8)
    assert np.array_equal(sync, piped)  # bitwise, not approx
    assert stats["sub_batches"] == 5
    assert stats["real_tokens"] <= stats["padded_tokens"]
    # sorting must actually reduce padding vs the one-bucket sync path
    ids, mask = tiny_encoder._tokenize(texts)
    sync_padded = next_pow2(len(texts), floor=8) * next_pow2(ids.shape[1], floor=8)
    assert stats["padded_tokens"] < sync_padded


def test_encode_pipelined_empty(tiny_encoder):
    out, stats = tiny_encoder.encode_pipelined([], sub_batch=8)
    assert out.shape == (0, TINY.hidden_size)
    assert stats["sub_batches"] == 0


# -- content-hash cache -------------------------------------------------------


def test_embed_cache_hit_miss_eviction():
    cache = EmbedCache(max_entries=2, model="m")
    v1 = np.ones(4, dtype=np.float32)
    assert cache.get("a") is None
    cache.put("a", v1)
    hit = cache.get("a")
    assert np.array_equal(hit, v1)
    assert not hit.flags.writeable  # shared rows must be immutable
    cache.put("b", v1 * 2)
    cache.put("c", v1 * 3)  # evicts LRU ("a")
    assert cache.get("a") is None
    assert np.array_equal(cache.get("c"), v1 * 3)
    s = cache.stats()
    assert (s["cache_hits"], s["cache_evictions"], s["cache_size"]) == (2, 1, 2)
    assert s["cache_misses"] == 2


def test_embed_cache_model_salt_and_disabled():
    a = EmbedCache(max_entries=4, model="model-a")
    a.put("text", np.ones(2, dtype=np.float32))
    b = EmbedCache(max_entries=4, model="model-b")
    assert b.get("text") is None  # different model never shares entries
    off = EmbedCache(max_entries=0)
    off.put("text", np.ones(2, dtype=np.float32))
    assert off.get("text") is None and len(off) == 0


def test_pipeline_cache_reingest_skips_forward(tiny_encoder):
    pipe = EmbedPipeline(tiny_encoder, model="t", sub_batch=8, cache_size=128)
    texts = [f"doc number {i} about topic {i % 3}" for i in range(20)]
    first = pipe.encode_batch(texts)
    assert np.array_equal(first, tiny_encoder.encode(texts))
    calls = []
    orig = tiny_encoder.encode_pipelined
    tiny_encoder.encode_pipelined = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    try:
        second = pipe.encode_batch(texts)
    finally:
        tiny_encoder.encode_pipelined = orig
    assert calls == []  # full cache hit: the encoder never ran
    assert np.array_equal(second, first)
    assert pipe.cache.stats()["cache_hits"] == len(texts)
    assert 0.0 <= pipe.pad_waste_ratio() < 1.0


# -- query coalescer ----------------------------------------------------------


def _hash_rows(texts):
    # deterministic instant "encoder": row value encodes the text identity
    out = []
    for t in texts:
        h = np.frombuffer(str(t).encode().ljust(8, b"\0")[:8], dtype=np.uint8)
        out.append(h.astype(np.float32))
    return out


def test_coalescer_concurrent_rows_no_leakage():
    batches = []

    def encode_rows(texts):
        batches.append(list(texts))
        time.sleep(0.02)  # while busy, later requests pile up and coalesce
        return _hash_rows(texts)

    co = QueryCoalescer(encode_rows, max_wait_ms=10.0, max_batch=64)
    results: dict = {}

    def client(i: int) -> None:
        rows = co.embed([f"query {i}"])
        results[i] = rows[0]

    threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(16):  # every client got exactly ITS row back
        assert np.array_equal(results[i], _hash_rows([f"query {i}"])[0]), i
    assert co.batches < co.requests  # coalescing actually happened
    assert co.coalesced_rows == 16
    assert sum(len(b) for b in batches) + co.dedup_rows == 16


def test_coalescer_dedups_identical_texts():
    seen = []

    def encode_rows(texts):
        seen.extend(texts)
        time.sleep(0.02)
        return _hash_rows(texts)

    co = QueryCoalescer(encode_rows, max_wait_ms=20.0, max_batch=64)
    out: list = [None] * 8

    def client(i: int) -> None:
        out[i] = co.embed(["same question"])[0]

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    expect = _hash_rows(["same question"])[0]
    assert all(np.array_equal(v, expect) for v in out)
    # the duplicate text encoded at most once per dispatched batch
    assert len(seen) == co.batches
    assert co.dedup_rows == 8 - co.batches


def test_coalescer_deadline_and_max_batch():
    def encode_rows(texts):
        return _hash_rows(texts)

    # max_batch reached -> dispatch long before the (absurd) deadline
    co = QueryCoalescer(encode_rows, max_wait_ms=30_000.0, max_batch=4)
    t0 = time.perf_counter()
    done = []

    def client(i: int) -> None:
        co.embed([f"q{i}"])
        done.append(i)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert time.perf_counter() - t0 < 10.0  # not the 30 s window
    assert sorted(done) == [0, 1, 2, 3]

    # a solo request is dispatched once its window closes (deadline respected)
    co2 = QueryCoalescer(encode_rows, max_wait_ms=50.0, max_batch=64)
    t0 = time.perf_counter()
    co2.embed(["solo"])
    elapsed = time.perf_counter() - t0
    assert elapsed < 5.0


def test_coalescer_deadline_anchors_at_arrival_not_worker_wakeup():
    """A request that queued behind a busy encoder already spent its window:
    the next gather must dispatch it immediately instead of waiting a fresh
    max_wait_ms (the 'no later than max_wait_ms after submission' contract)."""
    release = threading.Event()
    gate_used = [False]

    def encode_rows(texts):
        if not gate_used[0]:
            gate_used[0] = True
            release.wait(5.0)  # batch 1 holds the worker busy
        return _hash_rows(texts)

    co = QueryCoalescer(encode_rows, max_wait_ms=400.0, max_batch=64)
    t_done: dict = {}

    def client(name: str) -> None:
        co.embed([name])
        t_done[name] = time.perf_counter()

    first = threading.Thread(target=client, args=("first",))
    first.start()
    time.sleep(0.1)  # worker now busy inside batch 1
    second = threading.Thread(target=client, args=("second",))
    second.start()
    time.sleep(0.5)  # 'second' queued > max_wait_ms ago, still parked
    t_release = time.perf_counter()
    release.set()
    first.join()
    second.join()
    # window already expired while the worker was busy -> batch 2 dispatches
    # without a fresh 400 ms wait
    assert t_done["second"] - t_release < 0.3, t_done["second"] - t_release


def test_coalescer_error_propagates_to_all_waiters():
    def encode_rows(texts):
        raise RuntimeError("encoder exploded")

    co = QueryCoalescer(encode_rows, max_wait_ms=10.0, max_batch=8)
    errors = []

    def client(i: int) -> None:
        try:
            co.embed([f"q{i}"])
        except RuntimeError as exc:
            errors.append(str(exc))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == ["encoder exploded"] * 3
    # the worker survives a failing batch: a later healthy batch still answers
    co._encode_rows = _hash_rows
    assert np.array_equal(co.embed(["later"])[0], _hash_rows(["later"])[0])


# -- engine integration: memoize-on-retraction + fence replay -----------------


def test_query_memo_retraction_never_reinvokes_encoder():
    """device_expression is deterministic=False: the engine memoizes each query
    row's embedding and REPLAYS it on retraction — with the pipeline in front,
    the retraction must reach neither the coalescer nor the encoder."""
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg

    emb = _tiny_embedder(embed_cache_size=0)  # cache off: isolate the memo path
    forwards = []
    orig = emb.encoder.encode_device
    emb.encoder.encode_device = lambda texts: (forwards.append(list(texts)), orig(texts))[1]

    pg.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"q": str}),
        [("what is a cat", 0, 1), ("what is a dog", 0, 1), ("what is a cat", 2, -1)],
        is_stream=True,
    )
    res = t.select(v=emb.device_expression(t.q))
    got = []
    pw.io.subscribe(
        res,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            zip(columns["v"], diffs.tolist())
        ),
    )
    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    # both inserts encoded exactly once (one coalesced dispatch), retraction replayed
    assert sum(len(b) for b in forwards) == 2
    ins_cat = [np.asarray(v) for v, d in got if d == 1]
    ret = [np.asarray(v) for v, d in got if d == -1]
    assert len(ins_cat) == 2 and len(ret) == 1
    assert any(np.array_equal(ret[0], v) for v in ins_cat)


@pytest.mark.chaos
def test_fence_replay_inflight_coalesced_queries_exactly_once():
    """Cluster-fence contract for in-flight coalesced queries (the PR 3 replay
    semantics): a fence aborts the commit AFTER the coalesced encode ran but
    before results committed; the engine resets evaluator state (fresh memo)
    and lockstep-replays the same rows. Each query must be re-answered EXACTLY
    once, each with its own row, and the content-hash cache must absorb the
    replay so the device forward does not run a second time."""
    from pathway_tpu.engine.expression_evaluator import evaluate

    emb = _tiny_embedder(embed_cache_size=64)
    forwards = []
    orig = emb.encoder.encode_device
    emb.encoder.encode_device = lambda texts: (forwards.append(list(texts)), orig(texts))[1]

    texts = np.array(
        [f"inflight query {i}" for i in range(4)] + ["inflight query 0"], dtype=object
    )
    e = emb.device_expression(expr.ColumnReference(None, "q"))
    keys = np.empty(len(texts), dtype=KEY_DTYPE)
    for i in range(len(texts)):
        p = pointer_from(f"row{i}")
        keys[i] = (p.hi, p.lo)
    diffs = np.ones(len(texts), dtype=np.int64)

    def run_commit(memo: dict) -> np.ndarray:
        return evaluate(
            e,
            len(texts),
            lambda ref: texts,
            keys=keys,
            diffs=diffs,
            memo=memo,
            memo_tokens={id(e): "nd0"},
        )

    memo_before_fence: dict = {}
    first = run_commit(memo_before_fence)
    n_forward_rows_first = sum(len(b) for b in forwards)
    assert n_forward_rows_first == 4  # 5 rows, 1 duplicate text deduped

    # the query-path cache fill runs on the coalescer worker AFTER responders
    # are released (off the serving latency path); the fence quiesce
    # (PATHWAY_FENCE_TIMEOUT_S, default 180 s) dwarfs it in production — wait
    # for it here so the replay assertion is deterministic under suite load
    deadline = time.monotonic() + 30.0
    while len(emb.pipeline.cache) < 4 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(emb.pipeline.cache) == 4

    # FENCE: commit aborted, evaluator state reset -> replay with a FRESH memo
    memo_after_fence: dict = {}
    replay = run_commit(memo_after_fence)

    # replayed exactly once: one more evaluation, same per-row values
    assert len(replay) == len(first) == len(texts)
    for i in range(len(texts)):
        assert np.array_equal(np.asarray(first[i]), np.asarray(replay[i])), i
    # ...and the replay was absorbed by the content cache: no new forward rows
    assert sum(len(b) for b in forwards) == n_forward_rows_first
    # the replayed commit rebuilt its memo so a post-fence retraction replays
    store = memo_after_fence["nd0"]
    assert len(store) == len(texts)
    ret_diffs = -np.ones(len(texts), dtype=np.int64)
    before = sum(len(b) for b in forwards)
    retr = evaluate(
        e,
        len(texts),
        lambda ref: texts,
        keys=keys,
        diffs=ret_diffs,
        memo=memo_after_fence,
        memo_tokens={id(e): "nd0"},
    )
    assert sum(len(b) for b in forwards) == before  # retraction: no encoder work
    for i in range(len(texts)):
        assert np.array_equal(np.asarray(retr[i]), np.asarray(replay[i]))
    assert len(store) == 0  # memo entries popped on retraction


# -- embedder dimension short-circuit ----------------------------------------


def test_api_embedder_dimension_short_circuit():
    from pathway_tpu.xpacks.llm.embedders import (
        GeminiEmbedder,
        LiteLLMEmbedder,
        OpenAIEmbedder,
    )

    # known models: no client library, no network, no asyncio.run
    assert OpenAIEmbedder(model="text-embedding-3-small").get_embedding_dimension() == 1536
    assert OpenAIEmbedder(model="text-embedding-3-large").get_embedding_dimension() == 3072
    assert (
        OpenAIEmbedder(model="text-embedding-3-large", dimensions=256).get_embedding_dimension()
        == 256
    )
    assert GeminiEmbedder(model="models/embedding-001").get_embedding_dimension() == 768
    assert (
        LiteLLMEmbedder(model="openai/text-embedding-3-small").get_embedding_dimension()
        == 1536
    )


def test_unknown_embedder_still_probes():
    from pathway_tpu.xpacks.llm.embedders import BaseEmbedder

    class Custom(BaseEmbedder):
        def __init__(self):
            super().__init__()
            self.calls = 0

            def embed(text: str) -> list:
                self.calls += 1
                return [0.0] * 5

            self.func = embed

    c = Custom()
    assert c.get_embedding_dimension() == 5
    assert c.calls == 1


def test_sentence_transformer_dimension_no_encode(tiny_encoder):
    emb = _tiny_embedder()
    forwards = []
    orig = emb.encoder.encode_device
    emb.encoder.encode_device = lambda t: (forwards.append(t), orig(t))[1]
    assert emb.get_embedding_dimension() == TINY.hidden_size
    assert forwards == []


# -- document store integration ----------------------------------------------


def test_document_store_serves_pipeline_stats():
    from pathway_tpu.stdlib.indexing.nearest_neighbors import (
        BruteForceKnnFactory,
        BruteForceKnnMetricKind,
    )
    from pathway_tpu.xpacks.llm.document_store import DocumentStore

    from .utils import capture_rows

    emb = _tiny_embedder(embed_cache_size=32)
    factory = BruteForceKnnFactory(
        dimensions=TINY.hidden_size, metric=BruteForceKnnMetricKind.COS, embedder=emb
    )
    docs = pw.debug.table_from_rows(
        pw.schema_builder({"data": bytes, "_metadata": pw.Json}),
        [
            (b"cats sit on mats", pw.Json({"path": "/a.txt"})),
            (b"dogs chase balls", pw.Json({"path": "/b.txt"})),
        ],
    )
    store = DocumentStore(docs, retriever_factory=factory)
    stats_q = pw.debug.table_from_rows(pw.schema_builder({"dummy": int}), [(1,)])
    rows = capture_rows(store.statistics_query(stats_q))
    payload = rows[0]["result"].value
    assert payload["file_count"] == 2
    emb_stats = payload["embedder"]
    for key in ("cache_hits", "cache_misses", "coalesce_batches", "pad_waste_ratio"):
        assert key in emb_stats


def test_document_store_retrieve_with_pipeline_cache():
    """End-to-end retrieve through the pipelined embedder: correct hit, and a
    repeated identical query answered out of the content-hash cache."""
    from pathway_tpu.stdlib.indexing.nearest_neighbors import (
        BruteForceKnnFactory,
        BruteForceKnnMetricKind,
    )
    from pathway_tpu.xpacks.llm.document_store import DocumentStore

    from .utils import capture_rows

    emb = _tiny_embedder(embed_cache_size=32)
    factory = BruteForceKnnFactory(
        dimensions=TINY.hidden_size, metric=BruteForceKnnMetricKind.COS, embedder=emb
    )
    docs = pw.debug.table_from_rows(
        pw.schema_builder({"data": bytes, "_metadata": pw.Json}),
        [
            (b"the cat sits on the mat", pw.Json({"path": "/cats.txt"})),
            (b"dogs chase the ball in the park", pw.Json({"path": "/dogs.txt"})),
        ],
    )
    store = DocumentStore(docs, retriever_factory=factory)
    q_schema = pw.schema_builder(
        {"query": str, "k": int, "metadata_filter": str, "filepath_globpattern": str}
    )
    queries = pw.debug.table_from_rows(
        q_schema, [("the cat sits on the mat", 1, None, None)]
    )
    rows = capture_rows(store.retrieve_query(queries))
    docs_out = rows[0]["result"].value
    assert docs_out[0]["metadata"]["path"] == "/cats.txt"
    hits_before = emb.pipeline.cache.stats()["cache_hits"]
    from pathway_tpu.internals import parse_graph as pg

    pg.G.clear()  # fresh run; the embedder object (and its cache) persists
    queries2 = pw.debug.table_from_rows(
        q_schema, [("the cat sits on the mat", 1, None, None)]
    )
    # the document table was rebuilt in the new graph, so ingest re-runs too —
    # the cache must serve BOTH the re-ingested chunks and the repeated query
    docs2 = pw.debug.table_from_rows(
        pw.schema_builder({"data": bytes, "_metadata": pw.Json}),
        [
            (b"the cat sits on the mat", pw.Json({"path": "/cats.txt"})),
            (b"dogs chase the ball in the park", pw.Json({"path": "/dogs.txt"})),
        ],
    )
    store2 = DocumentStore(docs2, retriever_factory=factory)
    rows2 = capture_rows(store2.retrieve_query(queries2))
    assert rows2[0]["result"].value[0]["metadata"]["path"] == "/cats.txt"
    assert emb.pipeline.cache.stats()["cache_hits"] > hits_before


# -- telemetry stage counters -------------------------------------------------


def test_stage_counters_accumulate_and_reset():
    from pathway_tpu.engine import telemetry

    telemetry.stage_reset("testns.")
    telemetry.stage_add("testns.count", 2)
    telemetry.stage_add("testns.count", 3)
    with telemetry.stage_timer("testns.work"):
        pass
    snap = telemetry.stage_snapshot("testns.")
    assert snap["testns.count"] == 5
    assert snap["testns.work_calls"] == 1
    assert snap["testns.work_s"] >= 0
    telemetry.stage_reset("testns.")
    assert telemetry.stage_snapshot("testns.") == {}


@pytest.mark.slow
def test_pipeline_torture_many_threads(tiny_encoder):
    """Soak: 64 threads hammering cache+coalescer with overlapping text sets;
    every response must match the direct encode."""
    pipe = EmbedPipeline(tiny_encoder, model="t", max_wait_ms=2.0, cache_size=256)
    texts = [f"torture {i % 40}" for i in range(400)]
    expected = {t: tiny_encoder.encode([t])[0] for t in set(texts)}
    errors = []

    def client(ti: int) -> None:
        t = texts[ti]
        row = np.asarray(pipe.embed_query_rows([t])[0], dtype=np.float32)
        if not np.array_equal(row, expected[t]):
            errors.append(ti)

    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(64) as pool:
        list(pool.map(client, range(len(texts))))
    assert errors == []


def test_coalescer_admission_cap_sheds_with_honest_retry_after():
    """Backpressure slice (ISSUE 6): past ``max_queue_rows`` the coalescer
    sheds direct callers with a typed EmbedOverloadError (the REST plane
    probes the same cap pre-admission and sheds with 429 there) carrying an
    honest Retry-After estimate, bumps the embed.shed stage counter, and
    admits new work again once the queue drains."""
    from pathway_tpu.engine import telemetry
    from pathway_tpu.models.embed_pipeline import EmbedOverloadError

    release = threading.Event()

    def encode_rows(texts):
        release.wait(10.0)
        return _hash_rows(texts)

    co = QueryCoalescer(
        encode_rows, max_wait_ms=5.0, max_batch=1, max_queue_rows=2
    )
    done: dict = {}

    def client(name, texts):
        done[name] = co.embed(texts)

    # a: popped by the worker (max_batch=1) and held inside encode_rows
    ta = threading.Thread(target=client, args=("a", ["a"]))
    ta.start()
    deadline = time.perf_counter() + 5.0
    while (co._queued_rows, co.requests) != (0, 1):
        assert time.perf_counter() < deadline, "worker never picked up row a"
        time.sleep(0.01)
    # b: fills the admission queue exactly to the cap
    tb = threading.Thread(target=client, args=("b", ["b1", "b2"]))
    tb.start()
    while co._queued_rows != 2:
        assert time.perf_counter() < deadline, "row b never queued"
        time.sleep(0.01)

    shed_before = telemetry.stage_snapshot("embed.").get("embed.shed", 0.0)
    with pytest.raises(EmbedOverloadError) as exc_info:
        co.embed(["c"])
    assert exc_info.value.retry_after_s >= 1.0
    assert co.shed_requests == 1
    assert telemetry.stage_snapshot("embed.").get("embed.shed", 0.0) == shed_before + 1

    release.set()
    ta.join(timeout=10.0)
    tb.join(timeout=10.0)
    assert np.array_equal(done["a"][0], _hash_rows(["a"])[0])
    assert np.array_equal(done["b"][1], _hash_rows(["b2"])[0])
    # the queue drained: admission opens again, no sticky overload state
    assert np.array_equal(co.embed(["d"])[0], _hash_rows(["d"])[0])
    assert co.shed_requests == 1
    co.close()


def test_coalescer_retry_after_scales_with_queue_depth():
    """Retry-After must be an estimate, not a constant: a deeper queue names a
    later retry (batches-to-drain x per-batch time, floored at 1 s)."""
    co = QueryCoalescer(lambda t: _hash_rows(t), max_wait_ms=100.0, max_batch=2)
    co._encode_ewma_s = 2.0  # pretend the encoder runs 2 s batches
    shallow = co.retry_after_s(extra_rows=2)    # 1 batch to drain
    deep = co.retry_after_s(extra_rows=20)      # 10 batches to drain
    assert shallow >= 1.0
    assert deep > shallow * 5
    co.close()


def test_coalescer_overload_probe_and_engine_path_bypass():
    """``overloaded`` is the REST pre-admission probe for the row-queue cap;
    ``embed(enforce_cap=False)`` (the engine serving path — its request was
    already admitted against the cap at the REST boundary) never raises even
    past the cap, so a race between admission and the commit cannot tear the
    run down."""
    co = QueryCoalescer(lambda t: _hash_rows(t), max_wait_ms=1.0, max_queue_rows=2)
    assert not co.overloaded()
    co._queued_rows = 2  # simulate a full queue without racing the worker
    assert co.overloaded()
    assert co.overloaded(extra_rows=1)
    co._queued_rows = 0
    assert not co.overloaded()
    co._queued_rows = 5  # past the cap: enforce_cap=False must still admit
    got = co.embed(["x", "y", "z"], enforce_cap=False)
    assert np.array_equal(got[2], _hash_rows(["z"])[0])
    assert co.shed_requests == 0
    co.close()

    unbounded = QueryCoalescer(lambda t: _hash_rows(t), max_wait_ms=1.0)
    assert not unbounded.overloaded(extra_rows=10**9)  # cap 0 = disabled
    unbounded.close()


def test_embed_pipeline_wires_queue_cap_from_env(monkeypatch, tiny_encoder):
    """EmbedPipeline passes PATHWAY_EMBED_MAX_QUEUE_ROWS through to its
    coalescer (the knob was previously constructed-but-unwired), and an
    explicit kwarg wins over the env."""
    monkeypatch.setenv("PATHWAY_EMBED_MAX_QUEUE_ROWS", "17")
    pipe = EmbedPipeline(tiny_encoder, model="t")
    assert pipe.coalescer.max_queue_rows == 17
    pipe.coalescer.close()
    pipe2 = EmbedPipeline(tiny_encoder, model="t", max_queue_rows=0)
    assert pipe2.coalescer.max_queue_rows == 0
    pipe2.coalescer.close()
