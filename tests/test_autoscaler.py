"""Closed-loop autoscaler + brownout ladder (ISSUE 13).

Five layers under test:

- controller policy (``parallel/autoscaler.py``): rate-based targets through
  hysteresis bands, per-direction cooldowns, one-transition-in-flight,
  TYPED refusal backoff (at most one retry per window), and the flap lock
  under the chaos ``oscillating_load`` profile;
- brownout ladder (``engine/brownout.py``): occupancy-driven rungs with
  hysteresis, admission/coalesce/n_probe degradation factors, the quiesce
  window, and the REST plane shedding 429 + honest Retry-After on both;
- supervisor wiring: the hardened control endpoint (``err <reason>`` for
  malformed commands, the read-only ``status`` command, concurrent ``scale``
  requests), refusal feedback into the controller, and the typed
  ``AutoscaleRefusedError`` in post-mortems;
- chaos (``internals/chaos.py``): the ``load_spike`` / ``oscillating_load``
  / ``noisy_neighbor`` load profiles and the ``scale_refused`` preflight op;
- spawn acceptance: an ``--autoscale`` cluster at n=2 under a ramping
  synthetic load scales to 4 and back to 2 with NO operator input, final
  output bit-identical to a static run.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.brownout import BrownoutState, get_brownout, reset_brownout
from pathway_tpu.internals.chaos import Chaos
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.parallel.autoscaler import (
    AutoscaleController,
    AutoscalePolicy,
    AutoscaleRefusedError,
    AutoscaleSignals,
    aggregate_signals,
    read_state,
    write_state,
)
from pathway_tpu.parallel.membership import MembershipDirective
from pathway_tpu.parallel.supervisor import Supervisor

pytestmark = pytest.mark.autoscale

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PORT_SLOT = itertools.count()


def _port_base() -> int:
    return 31000 + os.getpid() % 150 * 30 + next(_PORT_SLOT) * 6


def _steady(rate: float, n: int = 2, **kw) -> AutoscaleSignals:
    return AutoscaleSignals(ingest_rate=rate, stable=True, current_n=n, **kw)


# -- controller policy --------------------------------------------------------


def test_policy_env_knobs(monkeypatch):
    monkeypatch.setenv("PATHWAY_AUTOSCALE_MAX", "6")
    monkeypatch.setenv("PATHWAY_AUTOSCALE_ROWS_PER_WORKER", "42")
    monkeypatch.setenv("PATHWAY_AUTOSCALE_FLAP_REVERSALS", "5")
    policy = AutoscalePolicy.from_env()
    assert policy.max_workers == 6
    assert policy.rows_per_worker == 42.0
    assert policy.flap_reversals == 5
    assert policy.min_workers == 2  # untouched default


def test_scale_up_needs_consecutive_samples_and_respects_cooldown():
    policy = AutoscalePolicy(
        rows_per_worker=100, up_samples=3, up_cooldown_s=10, max_workers=8
    )
    ctrl = AutoscaleController(policy, 2)
    # two samples above the band: not yet
    assert ctrl.sample(0.0, _steady(1000)) is None
    assert ctrl.sample(1.0, _steady(1000)) is None
    target = ctrl.sample(2.0, _steady(1000))
    assert target == 8  # ceil(1000/100) clamped to max
    ctrl.on_issued(target, 2.0)
    ctrl.on_complete(target, 3.0)
    # overload persists, but the up cooldown holds the next transition
    for t in (4.0, 5.0, 6.0, 7.0):
        assert ctrl.sample(t, _steady(10_000, n=8)) is None


def test_scale_down_is_slower_and_banded():
    policy = AutoscalePolicy(
        rows_per_worker=100, down_samples=3, down_cooldown_s=0, min_workers=2
    )
    ctrl = AutoscaleController(policy, 4)
    # inside the band (4 workers * 100 * 0.75 = 300): no decision
    for t in range(5):
        assert ctrl.sample(float(t), _steady(350, n=4)) is None
    # well below: needs down_samples consecutive, then targets the rate
    assert ctrl.sample(5.0, _steady(120, n=4)) is None
    assert ctrl.sample(6.0, _steady(120, n=4)) is None
    assert ctrl.sample(7.0, _steady(120, n=4)) == 2

def test_one_transition_in_flight_and_resume_after_stable():
    policy = AutoscalePolicy(rows_per_worker=10, up_samples=1, up_cooldown_s=0)
    ctrl = AutoscaleController(policy, 2)
    target = ctrl.sample(0.0, _steady(1000))
    assert target is not None
    ctrl.on_issued(target, 0.0)
    # in flight: no further decisions whatever the signals say
    assert ctrl.sample(1.0, _steady(10_000)) is None
    # the transition dies mid-flight: controller holds until stable again
    ctrl.on_aborted("crash", 2.0)
    assert ctrl.sample(3.0, AutoscaleSignals(ingest_rate=10_000, stable=False)) is None
    # the recovery ladder owns the cluster while unstable; the first STABLE
    # sample re-arms the controller (matching the model's stable-gate)
    assert ctrl.sample(4.0, _steady(10_000)) is not None


def test_refusal_backs_off_typed_and_retries_at_most_once_per_window():
    policy = AutoscalePolicy(
        rows_per_worker=10, up_samples=1, up_cooldown_s=0, refusal_backoff_s=10,
        shed_first_s=0,
    )
    ctrl = AutoscaleController(policy, 2)
    target = ctrl.sample(0.0, _steady(1000))
    ctrl.on_issued(target, 0.0)
    ctrl.on_refused(target, "join state is not reshardable", 1.0)
    # typed surface for post-mortems/tests
    assert isinstance(ctrl.last_refusal, AutoscaleRefusedError)
    assert ctrl.last_refusal.target_n == target
    assert "preflight" in str(ctrl.last_refusal)
    # inside the backoff window: never retried, however hot the signals
    for t in range(2, 11):
        assert ctrl.sample(float(t), _steady(10_000)) is None
    # after the window: exactly one retry is allowed
    retry = ctrl.sample(11.5, _steady(10_000))
    assert retry is not None
    ctrl.on_issued(retry, 11.5)
    ctrl.on_refused(retry, "still not reshardable", 12.0)
    for t in range(13, 22):
        assert ctrl.sample(float(t), _steady(10_000)) is None


def test_oscillating_load_flap_locks_with_bounded_transition_rate():
    """THE oscillating-load scenario (chaos ``oscillating_load`` profile
    drives the offered rate): at most one transition per cooldown window,
    and after ``flap_reversals`` direction reversals the controller locks
    into hold-and-alert instead of thrashing the reshard path."""
    load = Chaos(0, {"load": {
        "op": "oscillating_load", "period_s": 8.0, "low": 0.0, "high": 100.0,
    }})
    policy = AutoscalePolicy(
        min_workers=2, max_workers=4, rows_per_worker=20,
        up_samples=2, down_samples=2, up_cooldown_s=2, down_cooldown_s=2,
        flap_window_s=100, flap_reversals=3, shed_first_s=0,
    )
    ctrl = AutoscaleController(policy, 2)
    issued = []
    for t in range(80):
        rate = load.load_rate(float(t))
        target = ctrl.sample(float(t), _steady(rate, n=ctrl.current_n))
        if target is not None:
            issued.append((t, target))
            ctrl.on_issued(target, float(t))
            ctrl.on_complete(target, float(t))  # transitions land instantly
    assert ctrl.flap_locked, "oscillating load never engaged the flap lock"
    assert ctrl.state == "flap_locked"
    # at most one transition per cooldown window
    for (t1, _a), (t2, _b) in zip(issued, issued[1:]):
        assert t2 - t1 >= 2, f"two transitions inside one cooldown: {issued}"
    # the lock shows up in the decision log and the exported state
    kinds = [d.kind for d in ctrl.decisions]
    assert "flap_lock" in kinds
    locked_at = kinds.index("flap_lock")
    # ...and the lock HOLDS: nothing is issued after it
    assert all(
        d.kind not in ("scale_up", "scale_down")
        for d in ctrl.decisions[locked_at + 1:]
    )
    assert ctrl.as_dict()["flap_locked"] is True


def test_overload_scales_only_after_shed_window():
    """Shed-before-scale: a shed storm alone does not scale until the
    brownout/shed signal has been engaged for shed_first_s — cheap
    degradation is spent before a reshard pause."""
    policy = AutoscalePolicy(
        rows_per_worker=1000, up_samples=99, up_cooldown_s=0, shed_first_s=5
    )
    ctrl = AutoscaleController(policy, 2)
    # rate is modest (never crosses the band) but requests are shedding
    sig = lambda: _steady(100, shed_rate=4.0, brownout_level=1)
    for t in range(5):
        assert ctrl.sample(float(t), sig()) is None
    got = ctrl.sample(6.0, sig())
    assert got == 3  # current + 1 under overload
    decision = ctrl.last_decision()
    assert decision is not None and "overload" in decision.reason


def test_aggregate_signals_rates_and_reset_clamp():
    def status(rows, shed, state="running", mstate="stable"):
        return {
            "state": state,
            "membership_state": mstate,
            "autoscale": {
                "input_rows": rows, "shed": shed, "barrier_wait_s": 0.0,
                "commit_p99_s": 0.02, "brownout_level": 1,
            },
        }

    sig, carry = aggregate_signals(
        {0: status(100, 0), 1: status(100, 0)}, None, 10.0, 2
    )
    assert sig.stable and sig.ingest_rate == 0.0  # first sample: no rate yet
    sig, carry = aggregate_signals(
        {0: status(200, 3), 1: status(200, 1)}, carry, 12.0, 2
    )
    assert sig.ingest_rate == pytest.approx(100.0)  # +200 rows over 2 s
    assert sig.shed_rate == pytest.approx(2.0)
    assert sig.brownout_level == 1
    assert sig.commit_p99_s == pytest.approx(0.02)
    # a relaunched worker resets its counters: the delta clamps at 0
    sig, carry = aggregate_signals(
        {0: status(0, 0), 1: status(0, 0)}, carry, 14.0, 2
    )
    assert sig.ingest_rate == 0.0 and sig.shed_rate == 0.0
    # a missing or mid-transition rank makes the sample unstable
    sig, _ = aggregate_signals({0: status(0, 0)}, carry, 16.0, 2)
    assert not sig.stable
    sig, _ = aggregate_signals(
        {0: status(0, 0), 1: status(0, 0, mstate="resharding")}, carry, 18.0, 2
    )
    assert not sig.stable


def test_state_file_roundtrip(tmp_path):
    ctrl = AutoscaleController(AutoscalePolicy(), 2)
    ctrl.sample(0.0, _steady(10))
    write_state(str(tmp_path), ctrl)
    state = read_state(str(tmp_path))
    assert state is not None
    assert state["state"] == "watching"
    assert state["current_n"] == 2
    assert state["flap_locked"] is False
    assert read_state(str(tmp_path / "nope")) is None


def test_health_payload_carries_signals_and_controller_mirror(tmp_path):
    """Satellite: /healthz (via GraphRunner.health) exposes this rank's
    published load signals AND the mirrored controller state, and a flap
    lock appearing in the state file bumps the autoscale counters."""
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.parse_graph import ParseGraph

    runner = GraphRunner(ParseGraph())
    runner._supervise_dir = str(tmp_path)
    health = runner.health()
    assert "input_rows" in health["autoscale"]
    assert health["autoscaler"] is None  # no state file yet
    ctrl = AutoscaleController(AutoscalePolicy(), 2)
    ctrl.flap_locked = True
    ctrl.state = "flap_locked"
    ctrl._bump()
    write_state(str(tmp_path), ctrl)
    before = telemetry.stage_snapshot("autoscale.").get("autoscale.flap_locks", 0.0)
    runner._mirror_autoscale_state(time.monotonic() + 10)
    health = runner.health()
    assert health["autoscaler"]["flap_locked"] is True
    assert health["autoscaler"]["state"] == "flap_locked"
    after = telemetry.stage_snapshot("autoscale.").get("autoscale.flap_locks", 0.0)
    assert after == before + 1


# -- chaos load profiles ------------------------------------------------------


def test_chaos_load_profiles_are_deterministic():
    spike = Chaos(0, {"load": {
        "op": "load_spike", "at_s": 5, "duration_s": 10, "low": 50, "high": 400,
    }})
    assert spike.load_rate(0.0) == 50
    assert spike.load_rate(5.0) == 400
    assert spike.load_rate(14.9) == 400
    assert spike.load_rate(15.0) == 50
    osc = Chaos(0, {"load": {
        "op": "oscillating_load", "period_s": 4, "low": 10, "high": 90,
    }})
    assert osc.load_rate(0.0) == 90
    assert osc.load_rate(1.9) == 90
    assert osc.load_rate(2.0) == 10
    assert osc.load_rate(4.0) == 90
    assert Chaos(0, {}).load_rate(1.0) is None
    noisy = Chaos(0, {"load": {
        "op": "noisy_neighbor", "client": "tenant-7", "rps": 25, "rows": 2,
    }})
    assert noisy.noisy_neighbor() == {"client": "tenant-7", "rps": 25.0, "rows": 2}
    assert noisy.load_rate(1.0) is None
    assert spike.noisy_neighbor() is None


def test_chaos_scale_refused_gating():
    chaos = Chaos(0, {"scale": [{"op": "scale_refused", "rank": 0, "at": 0}]})
    assert chaos.scale_fault("scale_refused", 0)
    assert not chaos.scale_fault("scale_refused", 1)
    chaos2 = Chaos(0, {"scale": [{"op": "scale_refused", "rank": 0, "at": 1}]})
    assert not chaos2.scale_fault("scale_refused", 0)
    chaos2.begin_scale_attempt()
    chaos2.begin_scale_attempt()
    assert chaos2.scale_fault("scale_refused", 0)


# -- brownout ladder ----------------------------------------------------------


def test_brownout_rungs_engage_and_release_with_hysteresis():
    bo = BrownoutState(enabled=True, hold_s=0.5)
    t0 = 100.0
    assert bo.observe_occupancy(0.3, now=t0) == 0
    assert bo.admission_scale() == 1.0
    assert bo.observe_occupancy(0.7, now=t0 + 1) == 1
    assert bo.admission_scale() == 0.5
    assert bo.coalesce_window_scale() == 0.5
    assert bo.nprobe_shift() == 0
    assert bo.observe_occupancy(0.9, now=t0 + 2) == 2
    assert bo.admission_scale() == 0.25
    assert bo.coalesce_window_scale() == 0.0
    assert bo.nprobe_shift() == 1
    # oscillating just below the threshold does NOT release inside hold_s
    assert bo.observe_occupancy(0.5, now=t0 + 2.1) == 2
    # quiet past hold_s: rungs release
    assert bo.observe_occupancy(0.1, now=t0 + 10) == 0
    snap = bo.snapshot()
    assert snap["engages"] == 2 and snap["releases"] == 2


def test_brownout_disabled_stays_level_zero(monkeypatch):
    assert BrownoutState(enabled=False).observe_occupancy(0.99) == 0
    monkeypatch.setenv("PATHWAY_BROWNOUT", "off")
    reset_brownout()
    try:
        assert not get_brownout().enabled
        assert get_brownout().observe_occupancy(0.99) == 0
    finally:
        monkeypatch.delenv("PATHWAY_BROWNOUT")
        reset_brownout()


def test_brownout_quiesce_window_retry_after():
    bo = BrownoutState(enabled=True)
    assert bo.quiesce_retry_after() is None
    bo.enter_quiesce(2.0)
    retry = bo.quiesce_retry_after()
    assert retry is not None and 0.4 <= retry <= 2.0
    assert bo.snapshot()["quiesced"] is True
    bo.exit_quiesce()
    assert bo.quiesce_retry_after() is None


def test_ivf_n_probe_degrades_under_brownout(monkeypatch):
    import numpy as np

    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    reset_brownout()
    try:
        store = IvfKnnStore(dim=8, n_clusters=16, n_probe=8)
        rng = np.random.default_rng(0)
        store.add_many(
            list(range(64)), rng.standard_normal((64, 8)).astype(np.float32)
        )
        assert store._effective_n_probe() == store.n_probe
        get_brownout().observe_occupancy(0.9)  # rung 2: n_probe halves
        assert store._effective_n_probe() == max(1, store.n_probe >> 1)
        # serving still works at the degraded rung
        scores, slots, valid = store.search_batch(
            rng.standard_normal((4, 8), dtype=np.float32), k=3
        )
        assert scores.shape == (4, 3)
    finally:
        reset_brownout()


# -- supervisor: control endpoint + refusal feedback --------------------------


def _mini_supervisor(**kw) -> Supervisor:
    return Supervisor(
        processes=2, threads=1, first_port=_port_base(), program="true",
        arguments=[], env_base={}, **kw,
    )


def _control(port: int, line: str) -> str:
    with socket.create_connection(("127.0.0.1", port), timeout=5) as conn:
        conn.sendall(line.encode() + b"\n")
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = conn.recv(4096)
            if not chunk:
                break
            buf += chunk
    return buf.decode()


def test_control_endpoint_commands_and_errors():
    sup = _mini_supervisor(control_port=0, autoscale=True)
    sup._start_control_endpoint()
    try:
        port = sup.control_port
        assert port
        assert _control(port, "scale 3") == "ok\n"
        assert sup._scale_requests == [3]
        # malformed commands answer err <reason> instead of being dropped
        assert _control(port, "scale x").startswith("err scale target must be")
        assert _control(port, "scale").startswith("err usage")
        assert _control(port, "resize 9").startswith("err unknown command")
        assert _control(port, "").startswith("err empty command")
        # read-only status: topology + controller state + last decision
        status = json.loads(_control(port, "status"))
        assert status["n"] == 2
        assert status["transition_in_flight"] is False
        assert status["autoscaler"]["state"] == "watching"
        assert status["autoscaler"]["current_n"] == 2
    finally:
        sup._control_listener.close()


def test_control_endpoint_concurrent_scale_requests():
    sup = _mini_supervisor(control_port=0)
    sup._start_control_endpoint()
    try:
        port = sup.control_port
        replies = []
        lock = threading.Lock()

        def ask(n):
            reply = _control(port, f"scale {n}")
            with lock:
                replies.append(reply)

        threads = [
            threading.Thread(target=ask, args=(3 + i % 2,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert replies == ["ok\n"] * 8
        with sup._scale_lock:
            assert len(sup._scale_requests) == 8
    finally:
        sup._control_listener.close()


def test_supervisor_refusal_feeds_controller_and_post_mortem(tmp_path, capsys):
    """An autoscaler-issued scale-up refused by the preflight vote reaches
    the controller as a TYPED AutoscaleRefusedError, and the post-mortem
    names it."""
    sup = _mini_supervisor(autoscale=True)
    sup._supervise_dir = str(tmp_path)
    directive = MembershipDirective(1, 4, 1, 2, origin="autoscaler")
    sup._transition = (directive, time.monotonic())
    sup.autoscaler.on_issued(4, time.monotonic())
    statuses = {0: {"membership_refused": [1, "join state is not reshardable"]}}
    assert sup._watch_transition(statuses) is None
    assert sup._transition is None  # unwound, cluster keeps running
    refusal = sup.autoscaler.last_refusal
    assert isinstance(refusal, AutoscaleRefusedError)
    assert refusal.target_n == 4
    assert "join state is not reshardable" in str(refusal)
    # the controller is back to watching (not stuck in-flight), but the
    # refused direction is under backoff
    assert sup.autoscaler.state == "watching"
    assert sup.autoscaler.sample(
        time.monotonic(), _steady(1e9)
    ) is None
    sup._post_mortem((0, "exit code 1"), {}, "budget exhausted")
    err = capsys.readouterr().err
    assert "post-mortem autoscaler" in err
    assert "AutoscaleRefusedError" in err


def test_operator_origin_refusal_skips_controller(tmp_path):
    """A refusal of an OPERATOR-issued transition must not arm the
    autoscaler's backoff — the controller only owns its own decisions."""
    sup = _mini_supervisor(autoscale=True)
    sup._supervise_dir = str(tmp_path)
    directive = MembershipDirective(1, 4, 1, 2, origin="operator")
    sup._transition = (directive, time.monotonic())
    statuses = {0: {"membership_refused": [1, "nope"]}}
    assert sup._watch_transition(statuses) is None
    assert sup.autoscaler.last_refusal is None


def test_directive_file_carries_origin(tmp_path):
    from pathway_tpu.parallel.membership import read_directive, write_directive

    directive = MembershipDirective(3, 4, 2, 2, origin="autoscaler")
    write_directive(str(tmp_path), directive)
    got = read_directive(str(tmp_path))
    assert got is not None and got.origin == "autoscaler"
    # the vote payload stays the stable 4-tuple
    assert got.as_tuple() == (3, 4, 2, 2)


# -- spawn acceptance: capacity follows load, no operator ---------------------

AUTOSCALE_PROG = """
import json, os
import pathway_tpu as pw

tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

class WordSchema(pw.Schema):
    word: str

t = pw.io.fs.read(
    os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming",
)
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

out_path = os.path.join(tmp, f"out_{pid}.json")
rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
    else:
        rows.pop(repr(key), None)
    with open(out_path + ".tmp", "w") as f:
        json.dump(list(rows.values()), f)
    os.replace(out_path + ".tmp", out_path)

pw.io.subscribe(counts, on_change)
cfg = pw.persistence.Config(
    pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
)
pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
"""


def _read_merged(tmp_path, n: int) -> dict:
    merged: dict = {}
    for p in range(n):
        path = tmp_path / f"out_{p}.json"
        if not path.exists():
            continue
        try:
            for r in json.loads(path.read_text()):
                merged[r["word"]] = r["total"]
        except ValueError:
            pass
    return merged


def _static_reference_counts(tmp_path) -> dict:
    """The bit-identity baseline: the same pipeline run statically in-process
    over everything the feeder wrote."""
    G.clear()

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        str(tmp_path / "in"), format="csv", schema=WordSchema, mode="static"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    rows: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(key, None)

    pw.io.subscribe(counts, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    G.clear()
    return {r["word"]: r["total"] for r in rows.values()}


@pytest.mark.chaos
def test_autoscale_cycle_under_ramping_load_no_operator_input(tmp_path):
    """THE acceptance scenario: ``spawn -n 2 --autoscale`` under a ramping
    synthetic load (the chaos ``load_spike`` profile) scales to 4 and back
    to 2 with NO operator input — no scale plan, no control commands — and
    the final merged output is bit-identical to a static run. Exactly one
    transition per direction (no flap), never a restart-all."""
    (tmp_path / "in").mkdir()
    load = Chaos(0, {"load": {
        "op": "load_spike", "at_s": 3.0, "duration_s": 8.0,
        "low": 60.0, "high": 600.0,
    }})
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "60"
    env["PATHWAY_FENCE_TIMEOUT_S"] = "60"
    env["PATHWAY_MEMBERSHIP_DEADLINE_S"] = "90"
    env["PATHWAY_AUTOSCALE"] = "on"
    env["PATHWAY_AUTOSCALE_MIN"] = "2"
    env["PATHWAY_AUTOSCALE_MAX"] = "4"
    env["PATHWAY_AUTOSCALE_ROWS_PER_WORKER"] = "150"
    env["PATHWAY_AUTOSCALE_SAMPLE_S"] = "0.5"
    env["PATHWAY_AUTOSCALE_UP_SAMPLES"] = "2"
    env["PATHWAY_AUTOSCALE_DOWN_SAMPLES"] = "4"
    env["PATHWAY_AUTOSCALE_UP_COOLDOWN_S"] = "2"
    env["PATHWAY_AUTOSCALE_DOWN_COOLDOWN_S"] = "4"
    env["PATHWAY_AUTOSCALE_FLAP_WINDOW_S"] = "60"
    env["PATHWAY_AUTOSCALE_FLAP_REVERSALS"] = "3"
    prog = tmp_path / "prog.py"
    prog.write_text(AUTOSCALE_PROG)
    control_port = _port_base() + 5
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(_port_base()),
            "--max-restarts", "2", "--autoscale",
            "--control-port", str(control_port),
            sys.executable, str(prog),
        ],
        env=env, cwd=str(tmp_path), start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )
    err = ""
    expected: dict = {}
    try:
        # feed at the chaos load profile (rows/s follow the spike), tallying
        # the expected counts as we write
        t0 = time.monotonic()
        carry = 0.0
        last = 0.0
        i = 0
        while True:
            elapsed = time.monotonic() - t0
            if elapsed >= 15.0:
                break
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early (rc={proc.returncode}): {err}"
                )
            carry += (load.load_rate(elapsed) or 0.0) * max(0.0, elapsed - last)
            last = elapsed
            rows = int(carry)
            if rows > 0:
                carry -= rows
                word = f"w{i % 17}"
                (tmp_path / "in" / f"f{i:06d}.csv").write_text(
                    "word\n" + f"{word}\n" * rows
                )
                expected[word] = expected.get(word, 0) + rows
                i += 1
            time.sleep(0.1)
        # convergence: everything fed is delivered exactly once AND the
        # supervisor reports the cluster stable back at n=2 (the read-only
        # status command — still no operator INPUT). 240 s, the suite-wide
        # spawn-convergence discipline: a full out-and-back cycle (two
        # membership transitions) under full-suite load legitimately takes
        # minutes, and a tight wait reads as spurious row loss
        deadline = time.monotonic() + 240
        merged: dict = {}
        back_at_2 = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early (rc={proc.returncode}): {err}"
                )
            merged = _read_merged(tmp_path, 4)
            try:
                status = json.loads(_control(control_port, "status"))
                back_at_2 = (
                    status.get("n") == 2
                    and not status.get("transition_in_flight")
                )
            except (OSError, ValueError):
                back_at_2 = False
            if merged == expected and back_at_2:
                break
            time.sleep(0.3)
        assert merged == expected, f"got {merged}, want {expected}"
        assert back_at_2, "cluster never reported stable at n=2"
    finally:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            _, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            _, err = proc.communicate()
        err = err or ""
    assert "autoscaler: scaling n=2 -> n=4" in err, (
        f"the controller never scaled out:\n{err}"
    )
    assert "membership change complete: cluster is n=4" in err, (
        f"scale-out never completed:\n{err}"
    )
    assert "membership change complete: cluster is n=2" in err, (
        f"scale-in never completed:\n{err}"
    )
    assert err.count("membership change requested") == 2, (
        f"more than one transition per direction (flap?):\n{err}"
    )
    assert "FLAP-LOCKED" not in err
    assert "restarting the cluster" not in err, (
        f"a transition fell back to restart-all:\n{err}"
    )
    # bit-identical to the failure-free static run of the same pipeline
    assert _static_reference_counts(tmp_path) == expected


@pytest.mark.chaos
def test_chaos_scale_refused_backs_off_typed_under_spawn(tmp_path):
    """The chaos ``scale_refused`` op injects a preflight refusal into a live
    cluster: the autoscaler's scale-up is refused TYPED
    (AutoscaleRefusedError in the supervisor log), retried at most once per
    backoff window, and the cluster keeps running at n=2 with exact
    output."""
    (tmp_path / "in").mkdir()
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "60"
    env["PATHWAY_MEMBERSHIP_DEADLINE_S"] = "60"
    env["PATHWAY_CHAOS_SEED"] = "7"
    # every attempt on rank 0 is refused at the preflight vote
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(
        {"scale": [{"op": "scale_refused", "rank": 0}]}
    )
    env["PATHWAY_AUTOSCALE"] = "on"
    env["PATHWAY_AUTOSCALE_MIN"] = "2"
    env["PATHWAY_AUTOSCALE_MAX"] = "4"
    env["PATHWAY_AUTOSCALE_ROWS_PER_WORKER"] = "50"
    env["PATHWAY_AUTOSCALE_SAMPLE_S"] = "0.5"
    env["PATHWAY_AUTOSCALE_UP_SAMPLES"] = "2"
    env["PATHWAY_AUTOSCALE_UP_COOLDOWN_S"] = "1"
    env["PATHWAY_AUTOSCALE_REFUSAL_BACKOFF_S"] = "30"
    prog = tmp_path / "prog.py"
    prog.write_text(AUTOSCALE_PROG)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(_port_base()),
            "--max-restarts", "2", "--autoscale",
            sys.executable, str(prog),
        ],
        env=env, cwd=str(tmp_path), start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )
    err = ""
    expected: dict = {}
    try:
        # a steady overload: rate well past 2 workers' capacity, so the
        # controller keeps WANTING to scale up — the backoff must hold it
        t0 = time.monotonic()
        i = 0
        while time.monotonic() - t0 < 10.0:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early (rc={proc.returncode}): {err}"
                )
            word = f"w{i % 7}"
            (tmp_path / "in" / f"f{i:06d}.csv").write_text(
                "word\n" + f"{word}\n" * 30
            )
            expected[word] = expected.get(word, 0) + 30
            i += 1
            time.sleep(0.15)
        deadline = time.monotonic() + 60
        merged: dict = {}
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early (rc={proc.returncode}): {err}"
                )
            merged = _read_merged(tmp_path, 2)
            if merged == expected:
                break
            time.sleep(0.3)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            _, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            _, err = proc.communicate()
        err = err or ""
    assert "chaos: injected preflight refusal" in err, (
        f"the scale_refused op never fired:\n{err}"
    )
    # typed in the supervisor's log, and the backoff held: the refused
    # scale-up was attempted at most once inside the 30 s window
    assert "AutoscaleRefusedError" in err, f"refusal was not typed:\n{err}"
    assert err.count("membership change requested") <= 1, (
        f"refusal retry storm against the preflight vote:\n{err}"
    )
    assert "membership change complete: cluster is n=4" not in err
    assert "restarting the cluster" not in err


# -- bench registration satellites --------------------------------------------


def test_bench_sections_all_have_deadlines():
    """Satellite: section registration auto-derives both deadline tables —
    a section can no longer be added without them (the orchestrator used to
    KeyError at run time)."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    assert set(bench.SUB_BENCHES) == set(bench._DEADLINES_FULL)
    assert set(bench.SUB_BENCHES) == set(bench._DEADLINES_SMALL)
    assert bench.DEVICE_BOUND <= set(bench.SUB_BENCHES)
    assert "autoscale" in bench.SUB_BENCHES


def test_bench_positional_name_is_loud_usage_error():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "not-a-section"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2
    assert "unknown section" in proc.stderr
    assert "autoscale" in proc.stderr  # usage lists the sections
