"""Sorted-index subsystem tests, mirroring the reference's
``python/pathway/tests/test_sorting.py`` plus the tree/retrieval APIs
(``stdlib/indexing/sorting.py``): build_sorted_index structure invariants,
sort_from_index on arbitrary trees, retrieve_prev_next_values chains, and
incremental updates."""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.stdlib.indexing import (
    build_sorted_index,
    retrieve_prev_next_values,
    sort_from_index,
)


def _rows(table) -> dict:
    captured = {}
    pw.io.subscribe(
        table,
        lambda key, row, time, is_addition: (
            captured.__setitem__(key, dict(row))
            if is_addition
            else captured.pop(key, None)
        ),
    )
    from pathway_tpu.engine.runner import GraphRunner

    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    return captured


def setup_function(_fn):
    pg.G.clear()


def test_prevnext_single_instance():
    # reference test_sorting.py::test_prevnext_single_instance
    nodes = pw.debug.table_from_markdown(
        """
          | key | instance
        1 |  1  | 42
        2 |  5  | 42
        3 |  3  | 42
        4 |  8  | 42
        5 |  2  | 42
        """
    )
    result = nodes.sort(key=nodes.key, instance=nodes.instance)
    got = _rows(result.select(k=nodes.key, prev=result.prev, next=result.next))
    key_of_ptr = {}
    for ptr, r in got.items():
        key_of_ptr[str(ptr)] = r["k"]
    chain = {
        r["k"]: (
            key_of_ptr.get(str(r["prev"])) if r["prev"] is not None else None,
            key_of_ptr.get(str(r["next"])) if r["next"] is not None else None,
        )
        for r in got.values()
    }
    assert chain == {
        1: (None, 2),
        2: (1, 3),
        3: (2, 5),
        5: (3, 8),
        8: (5, None),
    }


def test_prevnext_many_instances():
    nodes = pw.debug.table_from_markdown(
        """
          | key | instance
        1 |  1  | 42
        2 |  1  | 28
        3 |  5  | 42
        4 |  5  | 28
        5 |  3  | 42
        6 |  3  | 28
        """
    )
    result = nodes.sort(key=nodes.key, instance=nodes.instance)
    got = _rows(
        result.select(k=nodes.key, inst=nodes.instance, prev=result.prev, next=result.next)
    )
    key_of_ptr = {str(ptr): (r["inst"], r["k"]) for ptr, r in got.items()}
    for r in got.values():
        for col in ("prev", "next"):
            if r[col] is not None:
                inst, _k = key_of_ptr[str(r[col])]
                assert inst == r["inst"], "chain crossed instances"
    chains = {}
    for r in got.values():
        chains.setdefault(r["inst"], {})[r["k"]] = (
            key_of_ptr[str(r["prev"])][1] if r["prev"] is not None else None,
            key_of_ptr[str(r["next"])][1] if r["next"] is not None else None,
        )
    for inst in (42, 28):
        assert chains[inst] == {1: (None, 3), 3: (1, 5), 5: (3, None)}


def _tree_invariants(index_rows: dict) -> None:
    """Structural invariants of a sorted binary tree emitted by build_sorted_index."""
    by_ptr = {str(ptr): r for ptr, r in index_rows.items()}
    roots = [p for p, r in by_ptr.items() if r["parent"] is None]
    instances = {r["instance"] for r in by_ptr.values()}
    assert len(roots) == len(instances), "one root per instance"
    for p, r in by_ptr.items():
        for side, cmp in (("left", -1), ("right", 1)):
            child = r[side]
            if child is None:
                continue
            c = by_ptr[str(child)]
            assert c["instance"] == r["instance"]
            assert str(c["parent"]) == p, "child's parent pointer must point back"
            if cmp < 0:
                assert c["key"] < r["key"]
            else:
                assert c["key"] > r["key"]


def test_build_sorted_index_structure_and_oracle():
    nodes = pw.debug.table_from_markdown(
        """
          | key | instance
        1 |  4  | 0
        2 |  1  | 0
        3 |  9  | 0
        4 |  6  | 0
        5 |  2  | 1
        6 |  8  | 1
        """
    )
    si = build_sorted_index(nodes)
    index_rows = _rows(si["index"])
    _tree_invariants(index_rows)
    pg.G.clear()
    nodes = pw.debug.table_from_markdown(
        """
          | key | instance
        1 |  4  | 0
        2 |  1  | 0
        5 |  2  | 1
        """
    )
    si = build_sorted_index(nodes)
    oracle_rows = _rows(si["oracle"])
    assert {r["instance"] for r in oracle_rows.values()} == {0, 1}


def test_sort_from_index_matches_native_sort():
    """In-order traversal of the built tree == the engine's native sort order."""
    nodes = pw.debug.table_from_markdown(
        """
          | key | instance
        1 |  10 | 7
        2 |  3  | 7
        3 |  7  | 7
        4 |  1  | 7
        5 |  5  | 7
        6 |  12 | 7
        """
    )
    si = build_sorted_index(nodes)
    pn = sort_from_index(si["index"])
    got = _rows(pn.select(k=nodes.key, prev=pn.prev, next=pn.next))
    key_of_ptr = {str(ptr): r["k"] for ptr, r in got.items()}
    heads = [r for r in got.values() if r["prev"] is None]
    assert len(heads) == 1
    walked, cur = [], heads[0]
    while True:
        walked.append(cur["k"])
        if cur["next"] is None:
            break
        nxt = key_of_ptr[str(cur["next"])]
        cur = next(r for r in got.values() if r["k"] == nxt)
    assert walked == [1, 3, 5, 7, 10, 12]


def test_retrieve_prev_next_values_chain():
    # reference sorting.py:183 semantics: pointer to the nearest row (incl.
    # itself) with a non-None value, along prev/next order
    ordered = pw.debug.table_from_markdown(
        """
          | t | value
        1 | 1 |
        2 | 2 | 20.0
        3 | 3 |
        4 | 4 |
        5 | 5 | 50.0
        6 | 6 |
        """
    )
    s = ordered.sort(ordered.t)
    chained = ordered.select(prev=s.prev, next=s.next, value=ordered.value)
    got = _rows(
        retrieve_prev_next_values(chained).select(
            t=ordered.t, prev_value=pw.this.prev_value, next_value=pw.this.next_value
        )
    )
    t_of_ptr = {str(ptr): r["t"] for ptr, r in got.items()}
    resolved = {
        r["t"]: (
            t_of_ptr.get(str(r["prev_value"])) if r["prev_value"] is not None else None,
            t_of_ptr.get(str(r["next_value"])) if r["next_value"] is not None else None,
        )
        for r in got.values()
    }
    assert resolved == {
        1: (None, 2),
        2: (2, 2),
        3: (2, 5),
        4: (2, 5),
        5: (5, 5),
        6: (5, None),
    }


def test_sorted_index_incremental_updates():
    """Streamed inserts + a retraction: the tree restructures and stays valid."""
    nodes = pw.debug.table_from_markdown(
        """
        key | instance | __time__ | __diff__
        4   | 0        | 0        | 1
        1   | 0        | 0        | 1
        9   | 0        | 2        | 1
        6   | 0        | 4        | 1
        1   | 0        | 6        | -1
        """
    )
    si = build_sorted_index(nodes)
    index_rows = _rows(si["index"])
    _tree_invariants(index_rows)
    assert sorted(r["key"] for r in index_rows.values()) == [4, 6, 9]
