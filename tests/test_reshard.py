"""Universal reshardability: join/dedup/key-changing state rides the handoff.

Layers under test:

- the reshard-policy MATRIX: every graph node kind has an explicit entry in
  ``RESHARD_KIND_POLICIES`` (both directions — no stale entries either), and
  an undeclared kind refuses loudly instead of guessing;
- per-evaluator handoff round-trips: join arrangements partition by JOIN
  key, dedup instances by their OUTPUT key, derived-key nodes (reindex /
  flatten) compose owners through their provenance maps — each export
  re-imports exactly and overlapping fragments refuse;
- bounded transport: ``build_fragment_chunks`` keeps every chunk under the
  ``PATHWAY_RESHARD_CHUNK_BYTES`` budget with at most one payload per
  (section, node), and the persistence layer's chunk manifests make streams
  complete-or-abort (missing/torn/short chunks read as ABSENT, never a
  partial install);
- chaos: the three new scale plan ops (``join_handoff_torn``,
  ``dedup_install_kill``, ``chunk_stream_kill``) gate correctly and the
  spawn acceptances recover down the ladder with exact output;
- spawn acceptance: a LIVE join+groupby+dedup+reindex graph scaled
  2 -> 4 -> 2 under ingestion, final output bit-identical to a static run,
  with ZERO preflight refusals;
- drift audit: every chaos plan op has a CHAOS.md row and vice versa.
"""

from __future__ import annotations

import json
import os
import re
import signal
import textwrap
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.chaos import PLAN_OPS, Chaos
from pathway_tpu.internals.keys import KEY_DTYPE, shard_of
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.parallel.membership import (
    RESHARD_KIND_POLICIES,
    ReshardPlan,
    _approx_nbytes,
    _owner_fn_derived,
    build_fragment_chunks,
    compute_reshard_plan,
    reshard_chunk_bytes,
)
from tests.test_membership import (
    _await_counts,
    _port_base,
    _spawn_elastic,
    _terminate_group,
    _write_files,
)

pytestmark = [pytest.mark.reshard, pytest.mark.elastic]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _all_graph_kinds() -> set:
    from pathway_tpu.internals import parse_graph as pg

    kinds = set()
    stack = list(pg.Node.__subclasses__())
    while stack:
        cls = stack.pop()
        stack.extend(cls.__subclasses__())
        if cls.kind != "node":
            kinds.add(cls.kind)
    return kinds


# -- the policy matrix ---------------------------------------------------------


@pytest.mark.parametrize("kind", sorted(_all_graph_kinds()))
def test_every_node_kind_declares_a_reshard_policy(kind):
    """THE matrix: a graph kind without an explicit policy entry would be a
    silent guess at handoff time — every kind must be declared."""
    assert kind in RESHARD_KIND_POLICIES, (
        f"node kind {kind!r} has no entry in RESHARD_KIND_POLICIES — a new "
        "evaluator must declare how its state rides the membership handoff"
    )
    assert RESHARD_KIND_POLICIES[kind] in (
        "source", "root", "bykey", "derived", "replicate", "inherit",
    )


def test_policy_table_has_no_stale_entries():
    """Both directions: an entry for a kind that no longer exists is dead
    configuration that would mask a rename."""
    stale = set(RESHARD_KIND_POLICIES) - _all_graph_kinds()
    assert not stale, f"RESHARD_KIND_POLICIES names unknown kinds: {stale}"


def test_undeclared_kind_refuses_loudly():
    """A node kind absent from the table is a typed refusal naming the fix,
    never a silent placement guess."""
    class _MysteryNode:
        # deliberately NOT a pg.Node subclass: subclassing would register the
        # fake kind process-wide and pollute the matrix tests above
        id = 7
        kind = "quantum_sort"
        inputs = ()
        config: dict = {}

    node = _MysteryNode()

    class _Runner:
        _nodes = [node]
        evaluators: dict = {}

    plan = compute_reshard_plan(_Runner())
    assert not plan.ok
    assert "quantum_sort" in plan.refusals[0]
    assert "RESHARD_KIND_POLICIES" in plan.refusals[0]
    assert plan.refused_nodes[0]["kind"] == "quantum_sort"
    # the table itself never learns about it implicitly
    assert "quantum_sort" not in RESHARD_KIND_POLICIES


def test_reshard_plan_positional_compat():
    """Older call sites build ReshardPlan(policies, refusals) positionally;
    the structured fields default empty."""
    plan = ReshardPlan({1: "bykey"}, [])
    assert plan.ok and plan.refused_nodes == [] and plan.derived_base == {}


# -- join-side handoff ---------------------------------------------------------


def _join_runner(left_rows, right_rows):
    from pathway_tpu.engine.runner import GraphRunner

    G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "a": int}), left_rows
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "b": int}), right_rows
    )
    joined = left.join(right, left.k == right.k).select(left.a, right.b)
    pw.io.subscribe(joined, lambda *a, **kw: None)
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=3)
    nid = next(n.id for n in runner._nodes if n.kind == "join")
    return runner, nid


def test_join_reshard_export_import_roundtrip():
    """Both arrangements partition by JOIN key and a fresh evaluator
    rebuilds from the payloads exactly (row-for-row, both sides)."""
    runner, nid = _join_runner(
        [(1, 10), (2, 20), (3, 30)], [(1, 100), (2, 200)]
    )
    ev = runner.evaluators[nid]
    assert ev.reshard_check() is None
    owner = lambda keys: shard_of(keys, 2)  # noqa: E731
    exports = ev.reshard_export(owner, 2)
    total_left = sum(len(p.get("left", {"keys": []})["keys"])
                     for p in exports.values() if "left" in p)
    total_right = sum(len(p.get("right", {"keys": []})["keys"])
                      for p in exports.values() if "right" in p)
    assert total_left == 3 and total_right == 2
    # a row's destination is decided by its JOIN key, not its row key
    for payload in exports.values():
        for side in ("left", "right"):
            if side in payload:
                dests = set(int(d) for d in shard_of(payload[side]["jk"], 2))
                assert len(dests) == 1
    fresh_runner, fresh_nid = _join_runner([], [])
    fresh = fresh_runner.evaluators[fresh_nid]
    for payload in exports.values():
        fresh.reshard_import(payload)
    fk, _ = fresh.left.row_index.items()
    assert len(fk) == 3
    fk, _ = fresh.right.row_index.items()
    assert len(fk) == 2
    # overlapping fragments (same row key twice) refuse, never merge
    with pytest.raises(RuntimeError, match="overlap"):
        for payload in exports.values():
            fresh.reshard_import(payload)
    G.clear()


def test_join_reshard_export_parts_slices_bounded():
    """The chunked export yields the SAME partition as the full export, in
    pieces no larger than the row budget."""
    runner, nid = _join_runner(
        [(i, i * 10) for i in range(8)], [(i, i * 100) for i in range(5)]
    )
    ev = runner.evaluators[nid]
    owner = lambda keys: shard_of(keys, 2)  # noqa: E731
    pieces = list(ev.reshard_export_parts(owner, 2, 3))
    assert all(
        len(piece[side]["keys"]) <= 3
        for _, piece in pieces
        for side in piece
    )
    got_rows = sum(
        len(piece[side]["keys"]) for _, piece in pieces for side in piece
    )
    assert got_rows == 13  # 8 left + 5 right, nothing lost or duplicated
    fresh_runner, fresh_nid = _join_runner([], [])
    fresh = fresh_runner.evaluators[fresh_nid]
    for _dest, piece in pieces:
        fresh.reshard_import(piece)
    fk, _ = fresh.left.row_index.items()
    assert len(fk) == 8
    fk, _ = fresh.right.row_index.items()
    assert len(fk) == 5
    G.clear()


def test_join_refuses_with_populated_replay_memo():
    runner, nid = _join_runner([(1, 10)], [(1, 100)])
    ev = runner.evaluators[nid]
    ev._udf_memo = {b"k": 1}
    assert ev.reshard_check() is not None
    from pathway_tpu.parallel.membership import MembershipUnsupportedError

    with pytest.raises(MembershipUnsupportedError):
        ev.reshard_export(lambda keys: shard_of(keys, 2), 2)
    G.clear()


# -- dedup handoff -------------------------------------------------------------


def _dedup_runner(rows):
    from pathway_tpu.engine.runner import GraphRunner

    G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"word": str, "score": int}), rows
    )
    best = t.deduplicate(
        value=t.score, instance=t.word, acceptor=lambda new, old: new >= old
    )
    pw.io.subscribe(best, lambda *a, **kw: None)
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=3)
    nid = next(n.id for n in runner._nodes if n.kind == "deduplicate")
    return runner, nid


def test_dedup_reshard_roundtrip_by_output_key():
    runner, nid = _dedup_runner(
        [("cat", 3), ("dog", 5), ("owl", 1), ("cat", 7)]
    )
    ev = runner.evaluators[nid]
    assert len(ev.current) == 3 and len(ev._okeys) == 3
    assert ev.reshard_check() is None
    owner = lambda keys: shard_of(keys, 2)  # noqa: E731
    exports = ev.reshard_export(owner, 2)
    assert sum(len(p["current"]) for p in exports.values()) == 3
    # destinations follow the recorded OUTPUT key, not the instance repr
    for dest, payload in exports.items():
        for kb in payload["okeys"].values():
            assert int(shard_of(np.frombuffer(kb, dtype=KEY_DTYPE), 2)[0]) == dest
    fresh_runner, fresh_nid = _dedup_runner([])
    fresh = fresh_runner.evaluators[fresh_nid]
    for payload in exports.values():
        fresh.reshard_import(payload)
    assert fresh.current == ev.current
    assert fresh._okeys == ev._okeys
    with pytest.raises(RuntimeError, match="overlap"):
        fresh.reshard_import(next(iter(exports.values())))
    G.clear()


def test_dedup_export_parts_carry_matching_okeys():
    runner, nid = _dedup_runner([(f"w{i}", i) for i in range(9)])
    ev = runner.evaluators[nid]
    owner = lambda keys: shard_of(keys, 3)  # noqa: E731
    pieces = list(ev.reshard_export_parts(owner, 3, 2))
    assert all(len(p["current"]) <= 2 for _, p in pieces)
    assert sum(len(p["current"]) for _, p in pieces) == 9
    for _dest, p in pieces:
        assert set(p["current"]) == set(p["okeys"])
    G.clear()


def test_dedup_pre_upgrade_state_refuses():
    """Instances restored without the output-key sidecar cannot be placed —
    the preflight refuses instead of guessing from the instance repr."""
    runner, nid = _dedup_runner([("cat", 3), ("dog", 5)])
    ev = runner.evaluators[nid]
    ev._okeys.popitem()
    reason = ev.reshard_check()
    assert reason is not None and "output-key tracking" in reason
    G.clear()


# -- derived-key handoff (reindex / flatten provenance) ------------------------


def test_reshard_plan_marks_reindex_derived():
    from pathway_tpu.engine.runner import GraphRunner

    G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"word": str}), [("cat",), ("dog",)]
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    renamed = counts.with_id_from(counts.word)
    pw.io.subscribe(renamed, lambda *a, **kw: None)
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=3)
    for node in runner._nodes:
        ev = runner.evaluators[node.id]
        ev._cluster_policies = tuple(
            ev.cluster_input_policy(i) for i in range(len(node.inputs))
        )
    plan = compute_reshard_plan(runner)
    assert plan.ok, plan.refusals
    reindex_nid = next(n.id for n in runner._nodes if n.kind == "reindex")
    assert plan.policies[reindex_nid] == f"derived:{reindex_nid}"
    assert plan.derived_base[reindex_nid] == "bykey"
    G.clear()


def test_owner_fn_derived_composes_through_provenance():
    """A derived key's owner is its provenance source's owner; unmapped keys
    fall through to the base hash unchanged."""
    from pathway_tpu.internals.keys import sequential_keys

    src = sequential_keys(100, 4)
    derived = sequential_keys(500, 4)

    class _Ev:
        _reshard_prov = {
            derived[i].tobytes(): src[i].tobytes() for i in range(3)
        }

    owner = _owner_fn_derived(_Ev(), lambda keys: shard_of(keys, 4))
    got = np.asarray(owner(derived))
    want_mapped = shard_of(src, 4)
    want_raw = shard_of(derived, 4)
    assert (got[:3] == want_mapped[:3]).all()
    assert got[3] == want_raw[3]  # no provenance entry: base hash decides


def test_flatten_tracks_provenance_under_cluster():
    from pathway_tpu.engine.columnar import Delta
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.keys import sequential_keys

    G.clear()
    t = pw.debug.table_from_rows(pw.schema_builder({"xs": str}), [("ab",)])
    flat = t.flatten(t.xs)
    pw.io.subscribe(flat, lambda *a, **kw: None)
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=2)
    ev = runner.evaluators[flat._node.id]
    assert ev._reshard_prov == {}  # single-process: nothing tracked
    runner._cluster = object()  # the gate is all process() consults
    keys = sequential_keys(900, 2)
    delta = Delta(
        keys,
        np.ones(2, dtype=np.int64),
        {"xs": np.array(["ab", "c"], dtype=object)},
    )
    out = ev.process([delta])
    assert len(out) == 3  # "ab" -> a,b ; "c" -> c
    assert len(ev._reshard_prov) == 3
    srcs = set(ev._reshard_prov.values())
    assert srcs == {keys[0].tobytes(), keys[1].tobytes()}
    runner._cluster = None
    G.clear()


# -- bounded transport: chunk build + manifest round-trips ---------------------


def test_reshard_chunk_bytes_env_knob(monkeypatch):
    from pathway_tpu.parallel.membership import DEFAULT_RESHARD_CHUNK_BYTES

    monkeypatch.delenv("PATHWAY_RESHARD_CHUNK_BYTES", raising=False)
    assert reshard_chunk_bytes() == DEFAULT_RESHARD_CHUNK_BYTES
    monkeypatch.setenv("PATHWAY_RESHARD_CHUNK_BYTES", "4096")
    assert reshard_chunk_bytes() == 4096
    monkeypatch.setenv("PATHWAY_RESHARD_CHUNK_BYTES", "garbage")
    assert reshard_chunk_bytes() == DEFAULT_RESHARD_CHUNK_BYTES
    monkeypatch.setenv("PATHWAY_RESHARD_CHUNK_BYTES", "-1")
    assert reshard_chunk_bytes() == DEFAULT_RESHARD_CHUNK_BYTES


def test_approx_nbytes_estimates():
    assert _approx_nbytes(np.zeros(8, dtype=np.int64)) == 64
    assert _approx_nbytes(b"abcd") == 4
    assert _approx_nbytes({"k": b"abcd"}) >= 4
    assert _approx_nbytes(None) == 8
    assert _approx_nbytes(object()) == 64


def _plan_for(runner) -> ReshardPlan:
    for node in runner._nodes:
        ev = runner.evaluators[node.id]
        ev._cluster_policies = tuple(
            ev.cluster_input_policy(i) for i in range(len(node.inputs))
        )
    plan = compute_reshard_plan(runner)
    assert plan.ok, plan.refusals
    return plan


def test_build_fragment_chunks_bounded_and_disjoint():
    """Chunks respect the byte budget (small budget => many chunks), carry
    at most one payload per (section, node), name the kinds aboard, and the
    full set re-imports into a fresh runner exactly."""
    from tests.test_membership import _groupby_runner

    rows = [f"w{i % 7}" for i in range(40)]
    runner, nid = _groupby_runner(rows)
    plan = _plan_for(runner)
    # chunk_bytes=1: every piece seals its own chunk, so the chunk count is
    # the piece count — the budget is genuinely per-chunk, not per-stream
    chunk_iter, stats = build_fragment_chunks(
        runner, plan, 2, commit=5, generation=1, chunk_bytes=1
    )
    chunks = list(chunk_iter)
    assert stats["chunks"] == len(chunks) >= 3
    dests = {d for d, _ in chunks}
    assert dests == {0, 1}  # every destination gets at least one chunk
    for _dest, chunk in chunks:
        assert chunk["format"] == 1
        n_payloads = sum(
            len(chunk[s])
            for s in ("states", "evals", "evals_full", "evals_rebuild",
                      "source_offsets", "source_deltas")
        )
        assert n_payloads <= 1  # sealed per piece under this budget
        if chunk["evals"].get(nid) or chunk["states"].get(nid):
            assert "groupby" in chunk["kinds"]
    # the union re-imports into fresh evaluators bit-exactly
    from pathway_tpu.parallel.membership import import_fragments

    fresh_runner, fresh_nid = _groupby_runner([])
    import_fragments(fresh_runner, [c for _d, c in chunks])
    gkeys, _ = fresh_runner.evaluators[fresh_nid].gindex.items()
    src_gkeys, _ = runner.evaluators[nid].gindex.items()
    assert len(gkeys) == len(src_gkeys) == 7
    G.clear()


def test_chunk_dump_load_roundtrip(tmp_path, monkeypatch):
    """The persistence layer writes read-back-verified chunks + a per-dest
    manifest; the loader verifies count + crc32 and returns the chunks; a
    torn or missing chunk makes the WHOLE stream read as absent (ValueError)
    — complete-or-abort."""
    from pathway_tpu.persistence.engine import PersistenceManager

    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(tmp_path / "store")
    )
    pm = PersistenceManager(cfg)

    def mk(dest, nid, rows):
        return dest, {
            "format": 1, "from_rank": 0, "commit": 9, "generation": 1,
            "states": {}, "evals": {nid: {"rows": rows}}, "evals_full": {},
            "evals_rebuild": {}, "source_offsets": {}, "source_deltas": {},
            "kinds": ["groupby"],
        }

    total = pm.dump_reshard_chunks(
        "sig", 9, iter([mk(1, 4, list(range(50))), mk(1, 5, list(range(50)))])
    )
    assert total > 0
    frags = pm.load_reshard_fragments("sig", 9, dest=1, from_n=1)
    assert len(frags) == 2
    assert frags[0]["evals"][4]["rows"] == list(range(50))
    # wrong graph signature refuses
    with pytest.raises(ValueError, match="different"):
        pm.load_reshard_fragments("other-sig", 9, dest=1, from_n=1)
    # torn chunk: checksum fails, the whole stream is refused
    shard = tmp_path / "store" / "process-0" / "reshard-0000000009"
    chunk0 = shard / "frag-00001.c0000.pkl"
    chunk0.write_bytes(chunk0.read_bytes()[:10])
    with pytest.raises(ValueError, match="checksum"):
        pm.load_reshard_fragments("sig", 9, dest=1, from_n=1)
    # missing chunk: same refusal
    chunk0.unlink()
    with pytest.raises(ValueError, match="missing or fails"):
        pm.load_reshard_fragments("sig", 9, dest=1, from_n=1)
    # no manifest at all falls back to legacy, which is also absent => loud
    (shard / "frag-00001.mf").unlink()
    with pytest.raises(ValueError, match="missing"):
        pm.load_reshard_fragments("sig", 9, dest=1, from_n=1)


def test_empty_handoff_distinguished_from_torn_write(tmp_path, monkeypatch):
    """A donor with nothing addressed to a dest still writes a manifest (an
    empty stream), so the loader can tell 'empty handoff' from 'torn
    write'."""
    from pathway_tpu.persistence.engine import PersistenceManager

    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(tmp_path / "store")
    )
    pm = PersistenceManager(cfg)
    empty = {
        "format": 1, "from_rank": 0, "commit": 3, "generation": 1,
        "states": {}, "evals": {}, "evals_full": {}, "evals_rebuild": {},
        "source_offsets": {}, "source_deltas": {}, "kinds": [],
    }
    pm.dump_reshard_chunks("sig", 3, iter([(1, empty)]))
    frags = pm.load_reshard_fragments("sig", 3, dest=1, from_n=1)
    assert len(frags) == 1 and frags[0]["evals"] == {}


# -- chaos: the three new scale ops --------------------------------------------


def test_new_scale_ops_gate_on_attempt(monkeypatch):
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "0")
    plan = {
        "scale": [
            {"op": "join_handoff_torn", "rank": 0, "at": 0},
            {"op": "dedup_install_kill", "rank": 1},
            {"op": "chunk_stream_kill", "rank": 0, "at": 1},
        ]
    }
    c = Chaos(0, plan)
    c.begin_scale_attempt()  # attempt 0
    assert c.scale_fault("join_handoff_torn", 0) is True
    assert c.scale_fault("join_handoff_torn", 1) is False
    assert c.scale_fault("chunk_stream_kill", 0) is False  # at: 1
    c.begin_scale_attempt()  # attempt 1
    assert c.scale_fault("join_handoff_torn", 0) is False
    assert c.scale_fault("chunk_stream_kill", 0) is True
    assert c.scale_fault("dedup_install_kill", 1) is True  # every attempt


def test_chunk_stream_kill_fires_after_first_chunk(tmp_path, monkeypatch):
    """The donor dies after its FIRST chunk write, before any manifest — the
    surviving store has chunks but no manifest, so the loader refuses."""
    from pathway_tpu.internals import chaos as chaos_mod
    from pathway_tpu.persistence.engine import PersistenceManager

    killed: list = []
    monkeypatch.setattr(
        chaos_mod.os, "kill", lambda pid, sig: killed.append((pid, sig))
    )
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps({"scale": [{"op": "chunk_stream_kill", "rank": 0, "at": 0}]}),
    )
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "0")
    from pathway_tpu.internals.chaos import get_chaos, reset_chaos

    reset_chaos()
    try:
        chaos = get_chaos()
        assert chaos is not None
        chaos.begin_scale_attempt()
        cfg = pw.persistence.Config(
            pw.persistence.Backend.filesystem(tmp_path / "store")
        )
        pm = PersistenceManager(cfg)
        chunk = {
            "format": 1, "from_rank": 0, "commit": 2, "generation": 1,
            "states": {}, "evals": {1: {"x": 1}}, "evals_full": {},
            "evals_rebuild": {}, "source_offsets": {}, "source_deltas": {},
            "kinds": ["join"],
        }
        pm.dump_reshard_chunks("sig", 2, iter([(1, chunk), (1, dict(chunk))]))
        assert killed and killed[0][1] == signal.SIGKILL
    finally:
        monkeypatch.delenv("PATHWAY_CHAOS_PLAN")
        reset_chaos()


def test_join_handoff_torn_fails_readback(tmp_path, monkeypatch):
    """A torn join chunk fails the dump's read-back verification loudly —
    the attempt aborts before any manifest promises the stream."""
    from pathway_tpu.persistence.engine import PersistenceManager

    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps({"scale": [{"op": "join_handoff_torn", "rank": 0, "at": 0}]}),
    )
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "0")
    from pathway_tpu.internals.chaos import get_chaos, reset_chaos

    reset_chaos()
    try:
        get_chaos().begin_scale_attempt()
        cfg = pw.persistence.Config(
            pw.persistence.Backend.filesystem(tmp_path / "store")
        )
        pm = PersistenceManager(cfg)
        join_chunk = {
            "format": 1, "from_rank": 0, "commit": 2, "generation": 1,
            "states": {}, "evals": {1: {"left": {}}}, "evals_full": {},
            "evals_rebuild": {}, "source_offsets": {}, "source_deltas": {},
            "kinds": ["join"],
        }
        with pytest.raises(ValueError, match="read-back"):
            pm.dump_reshard_chunks("sig", 2, iter([(0, join_chunk)]))
        # a chunk with NO join state aboard is untouched by this op
        plain = dict(join_chunk)
        plain["kinds"] = ["groupby"]
        reset_chaos()
        get_chaos().begin_scale_attempt()
        assert pm.dump_reshard_chunks("sig", 3, iter([(0, plain)])) > 0
    finally:
        monkeypatch.delenv("PATHWAY_CHAOS_PLAN")
        reset_chaos()


# -- CHAOS.md drift audit ------------------------------------------------------


def test_chaos_md_documents_every_plan_op_both_ways():
    """Every op in ``PLAN_OPS`` has a CHAOS.md table row under its plan key,
    and every documented op row names a registered op — the reference doc
    cannot drift from the injection registry in either direction."""
    text = open(os.path.join(REPO, "CHAOS.md")).read()
    documented: dict = {}
    current = None
    for line in text.splitlines():
        m = re.match(r"^### `(\w+)`", line)
        if m:
            current = m.group(1)
            continue
        m = re.match(r"^\| `([a-z0-9_]+)` \|", line)
        if m and current is not None:
            documented.setdefault(current, set()).add(m.group(1))
    for key, ops in PLAN_OPS.items():
        assert key in documented, f"CHAOS.md has no op table for plan key {key!r}"
        missing = set(ops) - documented[key]
        assert not missing, f"CHAOS.md is missing {key} rows for: {missing}"
        stale = documented[key] - set(ops)
        assert not stale, f"CHAOS.md documents unregistered {key} ops: {stale}"
    stray = set(documented) - set(PLAN_OPS)
    assert not stray, f"CHAOS.md op tables for unknown plan keys: {stray}"


# -- spawn acceptance: the universal graph rides the scale ---------------------

UNIVERSAL_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema,
        mode="streaming",
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    joined = t.join(counts, t.word == counts.word).select(
        t.word, total=counts.total
    )
    best = joined.deduplicate(
        value=joined.total, instance=joined.word,
        acceptor=lambda new, old: new >= old,
    )
    final = best.with_id_from(best.word)

    out_path = os.path.join(tmp, f"out_{pid}.json")
    rows = {}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(repr(key), None)
        with open(out_path + ".tmp", "w") as f:
            json.dump(list(rows.values()), f)
        os.replace(out_path + ".tmp", out_path)

    pw.io.subscribe(final, on_change)
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
    )
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    """
)


def _spawn_universal(tmp_path, first_port, **kw):
    """The join+groupby+dedup+reindex pipeline under the elastic spawner."""
    import tests.test_membership as tm

    saved = tm.ELASTIC_PROG
    tm.ELASTIC_PROG = UNIVERSAL_PROG
    try:
        return _spawn_elastic(tmp_path, first_port, **kw)
    finally:
        tm.ELASTIC_PROG = saved


def _universal_static_counts(tmp_path) -> dict:
    """Reference: the same dataflow run statically in one process."""
    G.clear()

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        str(tmp_path / "in"), format="csv", schema=WordSchema, mode="static"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    joined = t.join(counts, t.word == counts.word).select(
        t.word, total=counts.total
    )
    best = joined.deduplicate(
        value=joined.total, instance=joined.word,
        acceptor=lambda new, old: new >= old,
    )
    final = best.with_id_from(best.word)
    rows: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(key, None)

    pw.io.subscribe(final, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    G.clear()
    return {r["word"]: r["total"] for r in rows.values()}


@pytest.mark.chaos
def test_elastic_universal_graph_grow_shrink_exact(tmp_path):
    """THE tentpole acceptance: a LIVE join+groupby+dedup+reindex graph
    scaled n=2 -> 4 -> 2 under ingestion with ZERO preflight refusals,
    final output bit-identical to the static run. The graph that used to
    refuse the scale now rides it."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 3 + ["dog"] * 2,
        "1": ["cat"] * 2 + ["owl"] * 1,
        "2": ["dog"] * 4,
    })
    scale_plan = [
        {"after_commit": 4, "n": 4},
        {"after_commit": 14, "n": 2},
    ]
    proc = _spawn_universal(tmp_path, first_port, n=2, scale_plan=scale_plan)
    err = ""
    try:
        time.sleep(8)  # grow window
        _write_files(tmp_path, "b", {
            "0": ["fox"] * 3 + ["cat"] * 2,
            "1": ["owl"] * 2,
        })
        time.sleep(8)  # shrink window
        _write_files(tmp_path, "c", {"0": ["cat"] * 1 + ["bee"] * 2})
        expected = {"cat": 8, "dog": 6, "owl": 3, "fox": 3, "bee": 2}
        merged = _await_counts(proc, tmp_path, 4, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "membership change complete: cluster is n=4" in err, (
        f"grow transition never completed:\n{err}"
    )
    assert "membership change complete: cluster is n=2" in err, (
        f"shrink transition never completed:\n{err}"
    )
    assert "REFUSED" not in err, f"the scale was refused:\n{err}"
    assert "restarting the cluster" not in err, (
        f"a transition fell back to restart-all:\n{err}"
    )
    assert _universal_static_counts(tmp_path) == merged


@pytest.mark.chaos
def test_elastic_join_handoff_torn_retries_exact(tmp_path):
    """Chaos: the first attempt tears a chunk carrying join state. Read-back
    fails the ack barrier, the attempt aborts cleanly, the retry completes —
    output exact, no restart-all."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 3 + ["dog"] * 2,
        "1": ["owl"] * 2,
    })
    plan = {"scale": [{"op": "join_handoff_torn", "rank": 0, "at": 0, "run": 0}]}
    proc = _spawn_universal(
        tmp_path, first_port, n=2,
        scale_plan=[{"after_commit": 4, "n": 3}],
        plan=plan, max_restarts=2,
    )
    err = ""
    try:
        time.sleep(8)
        _write_files(tmp_path, "b", {"0": ["fox"] * 3})
        expected = {"cat": 3, "dog": 2, "owl": 2, "fox": 3}
        merged = _await_counts(proc, tmp_path, 3, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "aborted (transient" in err or "will retry" in err, (
        f"the torn join chunk never aborted an attempt:\n{err}"
    )
    assert "membership change complete: cluster is n=3" in err, (
        f"the retry never completed the transition:\n{err}"
    )
    assert "restarting the cluster" not in err, (
        f"the torn join chunk escalated to restart-all:\n{err}"
    )


@pytest.mark.chaos
def test_elastic_dedup_install_kill_recovers_exact(tmp_path):
    """Chaos: a rank is SIGKILLed right before it applies a chunk carrying
    dedup instance state (post-manifest install window). The ladder recovers
    — restart-all at the committed topology — and the output stays exact."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 2 + ["dog"] * 1,
        "1": ["owl"] * 2,
    })
    plan = {"scale": [{"op": "dedup_install_kill", "rank": 1, "run": 0, "at": 0}]}
    proc = _spawn_universal(
        tmp_path, first_port, n=2,
        scale_plan=[{"after_commit": 4, "n": 3}],
        plan=plan, max_restarts=3,
        extra_env={"PATHWAY_MEMBERSHIP_DEADLINE_S": "20",
                   "PATHWAY_CONNECT_TIMEOUT_S": "8",
                   "PATHWAY_FENCE_TIMEOUT_S": "12"},
    )
    err = ""
    try:
        time.sleep(14)
        _write_files(tmp_path, "b", {"0": ["fox"] * 2})
        expected = {"cat": 2, "dog": 1, "owl": 2, "fox": 2}
        merged = _await_counts(proc, tmp_path, 3, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "restarting the cluster" in err, (
        f"the dedup install kill did not recover via the ladder:\n{err}"
    )
