"""Thread-lifecycle hygiene (the PWA104 contract, audited dynamically): after
``pw.run`` / stepped-run teardown and after a monitoring/REST server stop, no
non-daemon thread beyond the main thread survives — a leaked non-daemon
thread blocks interpreter shutdown and holds its resources across back-to-back
runs. Plus regression tests for the PWA102 fix in ``QueryCoalescer``: the
previously-untimed ``event.wait()`` now aborts typed instead of wedging the
engine thread when the coalescer dies with the request still queued."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.models.embed_pipeline import QueryCoalescer


def _non_daemon_threads():
    main = threading.main_thread()
    return [
        t
        for t in threading.enumerate()
        if t is not main and not t.daemon and t.is_alive()
    ]


def _assert_no_leaks(before, what: str):
    # allow a short settle for threads mid-exit at teardown
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaked = [t for t in _non_daemon_threads() if t not in before]
        if not leaked:
            return
        time.sleep(0.05)
    raise AssertionError(f"non-daemon threads leaked after {what}: {leaked}")


def test_no_nondaemon_threads_after_pw_run():
    before = _non_daemon_threads()
    t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,), (2,)])
    got = []
    pw.io.subscribe(t, lambda key, row, time, is_addition: got.append(row["v"]))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert sorted(got) == [1, 2]
    _assert_no_leaks(before, "pw.run teardown")


def test_no_nondaemon_threads_after_stepped_run():
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg

    before = _non_daemon_threads()
    t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(3,)])
    got = []
    pw.io.subscribe(t, lambda key, row, time, is_addition: got.append(row["v"]))
    runner = GraphRunner(pg.G._current)
    runner.setup()
    while runner.step():
        pass
    runner.finish()
    assert got == [3]
    _assert_no_leaks(before, "stepped-run teardown")


def test_no_nondaemon_threads_after_monitoring_server_stop():
    from pathway_tpu.engine.http_server import MonitoringServer, ProberStats

    before = _non_daemon_threads()
    server = MonitoringServer(ProberStats(), 0)  # ephemeral port
    assert server.port > 0
    server.close()
    server.close()  # idempotent
    _assert_no_leaks(before, "MonitoringServer stop")
    # the serving thread itself (daemon) must also exit, not just be orphaned
    server.thread.join(timeout=5)
    assert not server.thread.is_alive()


def test_no_nondaemon_threads_after_rest_webserver_stop():
    aiohttp = pytest.importorskip("aiohttp")
    del aiohttp
    import socket

    from pathway_tpu.io.http._server import PathwayWebserver

    before = _non_daemon_threads()
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    server = PathwayWebserver(host="127.0.0.1", port=port)
    server._ensure_running()
    assert server._started.wait(timeout=10)
    _assert_no_leaks(before, "REST webserver start+stop")
    # the aiohttp loop thread is daemon by contract (PWA104): it must never
    # keep the interpreter alive
    assert server._thread.daemon


# ---------------------------------------------------------------------------
# QueryCoalescer PWA102 regression: the wait is bounded and abortable
# ---------------------------------------------------------------------------


def _rows(texts):
    return [np.zeros(4, dtype=np.float32) for _ in texts]


def test_coalescer_close_with_live_worker_still_answers():
    co = QueryCoalescer(_rows, max_wait_ms=1.0, max_batch=8)
    out = co.embed(["a", "b"])
    assert len(out) == 2
    co.close()
    co.close()  # idempotent


def test_coalescer_close_with_dead_worker_fails_typed_not_wedged():
    """A request stranded in the queue with no worker to drain it must fail
    typed within the poll interval — before the fix, embed() sat in an
    untimed event.wait() forever (the PWA102 finding)."""
    co = QueryCoalescer(_rows, max_wait_ms=1.0, max_batch=8)
    # plant a stranded request: queued, no worker thread, coalescer closed —
    # the state a worker crash (or an exec-env teardown) leaves behind
    from pathway_tpu.models.embed_pipeline import _Request

    req = _Request(["stuck"])
    with co._cond:
        co._queue.append(req)
        co._queued_rows += 1
        co._closed = True
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="closed before this request"):
        co._await(req)
        raise req.error  # _await sets the typed error; embed() re-raises it
    assert time.monotonic() - t0 < 5.0, "abort took longer than the poll bound"
    assert co._queued_rows == 0, "admission slot leaked on the abort path"


def test_coalescer_wait_timeout_knob(monkeypatch):
    """PATHWAY_EMBED_WAIT_TIMEOUT_S bounds the total wait against a wedged
    encoder device."""
    release = threading.Event()

    def wedged_encoder(texts):
        release.wait(timeout=30)
        return _rows(texts)

    monkeypatch.setenv("PATHWAY_EMBED_WAIT_TIMEOUT_S", "1")
    co = QueryCoalescer(wedged_encoder, max_wait_ms=1.0, max_batch=8)
    assert co.wait_timeout_s == 1.0
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="PATHWAY_EMBED_WAIT_TIMEOUT_S"):
        co.embed(["x"])
    assert time.monotonic() - t0 < 10.0
    release.set()  # un-wedge the worker so it exits
    co.close()


def test_coalescer_error_propagation_still_works():
    def failing(texts):
        raise ValueError("encoder down")

    co = QueryCoalescer(failing, max_wait_ms=1.0, max_batch=8)
    with pytest.raises(ValueError, match="encoder down"):
        co.embed(["x"])
    co.close()


# ---------------------------------------------------------------------------
# EncoderService worker hygiene: clean shutdown on service stop/close and on
# pw.run teardown (the leaked-thread check for the service worker)
# ---------------------------------------------------------------------------


class _InstantEncoder:
    dim = 4

    def encode_device(self, texts):
        return np.zeros((len(texts), 4), dtype=np.float32)


def test_encoder_service_worker_stops_on_stop_and_close():
    from pathway_tpu.models.encoder_service import EncoderService

    svc = EncoderService(_InstantEncoder(), prewarm=False)
    assert not svc.worker_alive()  # lazy spawn: no thread before first submit
    out = svc.submit(["a", "b"])
    assert len(out) == 2
    assert svc.worker_alive()
    svc.stop_worker()
    assert not svc.worker_alive()
    # stopped, not closed: the next submit respawns the worker and answers
    assert len(svc.submit(["c"])) == 1
    assert svc.worker_alive()
    svc.close()
    svc.close()  # idempotent
    assert not svc.worker_alive()
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit(["d"])


def test_encoder_service_stop_with_inflight_request_still_answers():
    """stop_all_workers racing an admitted request must drain, not drop (the
    drop_on_close bug class from the protocol model, checked on real threads)."""
    from pathway_tpu.models.encoder_service import EncoderService

    release = threading.Event()

    class _GatedEncoder:
        dim = 4

        def encode_device(self, texts):
            release.wait(timeout=10)
            return np.zeros((len(texts), 4), dtype=np.float32)

    svc = EncoderService(_GatedEncoder(), prewarm=False)
    got = []
    t = threading.Thread(target=lambda: got.append(svc.submit(["x"])))
    t.start()
    deadline = time.monotonic() + 5.0
    while not svc.worker_alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    stopper = threading.Thread(target=svc.stop_worker)
    stopper.start()
    release.set()
    t.join(timeout=10)
    stopper.join(timeout=10)
    assert got and len(got[0]) == 1, "admitted request dropped at stop"
    assert not svc.worker_alive()
    svc.close()


def test_no_encoder_service_worker_after_pw_run():
    """pw.run teardown stops the service worker (GraphRunner.finish →
    stop_all_workers); the embedder stays usable — the worker respawns on the
    next query."""
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    tiny = EncoderConfig(
        vocab_size=8192, hidden_size=32, num_layers=1, num_heads=2,
        intermediate_size=64,
    )
    emb = SentenceTransformerEmbedder(
        model="pw-test-tiny", encoder_config=tiny, encoder_service=True,
        encsvc_prewarm=False,
    )
    before = _non_daemon_threads()
    t = pw.debug.table_from_rows(pw.schema_builder({"q": str}), [("hygiene query",)])
    res = t.select(v=emb.device_expression(t.q))
    got = []
    pw.io.subscribe(res, lambda key, row, time, is_addition: got.append(row["v"]))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(got) == 1
    _assert_no_leaks(before, "pw.run with encoder service")
    svc = emb.pipeline.service
    assert svc is not None
    deadline = time.monotonic() + 5.0
    while svc.worker_alive() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not svc.worker_alive(), "service worker leaked past pw.run teardown"
    # still serviceable afterwards
    assert len(emb.pipeline.embed_query_rows(["again"])) == 1
    svc.close()
