"""Interactive mode + viz snapshot collector tests."""

from __future__ import annotations

import time

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from tests.utils import T


def test_live_table_snapshot():
    pw.enable_interactive_mode()
    t = T(
        """
        | a
    1   | 10
    2   | 20
    """
    )
    live = t.live()
    deadline = time.time() + 15
    while time.time() < deadline and len(live.snapshot()) < 2:
        time.sleep(0.05)
    assert not live.failed
    assert sorted(r["a"] for r in live.snapshot()) == [10, 20]
    assert "a" in str(live)


def test_viz_table_snapshot_collector():
    t = T(
        """
        | a
    1   | 1
    2   | 2
    """
    )
    collector = pw.viz.table_snapshot(t)
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.parse_graph import G

    GraphRunner(G._current).run()
    assert sorted(r["a"] for r in collector.snapshot()) == [1, 2]


def test_viz_plot_requires_bokeh():
    import pytest

    t = T(
        """
        | a
    1   | 1
    """
    )
    with pytest.raises(ImportError):
        pw.viz.plot(t, lambda source: None)
