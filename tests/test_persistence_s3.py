"""Object-store (S3/Azure) persistence backends: journal frames as immutable
objects, single-PUT checkpoints, compaction by object delete, cached-object
storage over the same store.

Parity: reference ``src/persistence/backends/mod.rs:50`` (PersistenceBackend
trait) + ``backends/s3.rs``; the crash-kill rig mirrors
``integration_tests/wordcount`` over the S3 backend instead of filesystem.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pathway_tpu as pw
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.persistence.backends import MemoryObjectStore, S3ObjectStore

from .mocks import DirS3Client


def _collect(table):
    rows = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = row
        else:
            rows.pop(key, None)

    pw.io.subscribe(table, on_change)
    return rows


def _wordcount_pipeline():
    t = pw.debug.table_from_markdown(
        """
        word  | n
        cat   | 1
        dog   | 2
        cat   | 3
        """
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.sum(t.n))
    return _collect(counts)


def _s3_backend(client):
    return pw.persistence.Backend.s3(
        "s3://bucket/pipelines/p1", _client_factory=lambda settings: client
    )


def test_s3_journal_replay_reproduces_state(tmp_path):
    client = DirS3Client(str(tmp_path / "fake-s3"))
    cfg = pw.persistence.Config(_s3_backend(client))

    rows1 = _wordcount_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    result1 = {tuple(sorted(r.items())) for r in rows1.values()}
    assert {dict(r)["word"] for r in result1} == {"cat", "dog"}

    # journal frame objects exist under the prefix
    frames = client.list_objects_v2(
        Bucket="bucket", Prefix="pipelines/p1/journal/"
    )["Contents"]
    assert frames, "no journal frame objects written"

    # "restart": fresh graph + fresh runner over the same store — rows must
    # come from the frame objects
    G.clear()
    rows2 = _wordcount_pipeline()
    cfg2 = pw.persistence.Config(_s3_backend(client))
    GraphRunner(G._current).run(persistence_config=cfg2)
    result2 = {tuple(sorted(r.items())) for r in rows2.values()}
    assert result2 == result1


def test_s3_checkpoint_compacts_frame_objects(tmp_path):
    client = DirS3Client(str(tmp_path / "fake-s3"))
    cfg = pw.persistence.Config(_s3_backend(client), snapshot_interval_ms=1)

    rows = _wordcount_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert rows

    listing = client.list_objects_v2(Bucket="bucket", Prefix="pipelines/p1/")
    keys = [c["Key"] for c in listing["Contents"]]
    assert any(k.endswith("checkpoint.pkl") for k in keys), keys
    # frames at/before the checkpoint were deleted (compaction)
    assert not any(k.endswith(".frame") for k in keys), keys

    # resume from the checkpoint alone
    G.clear()
    rows2 = _wordcount_pipeline()
    cfg2 = pw.persistence.Config(_s3_backend(client), snapshot_interval_ms=1)
    GraphRunner(G._current).run(persistence_config=cfg2)
    assert {dict(r)["word"] for r in rows2.values()} == {"cat", "dog"}


def test_s3_graph_signature_mismatch_raises(tmp_path):
    import pytest

    client = DirS3Client(str(tmp_path / "fake-s3"))
    cfg = pw.persistence.Config(_s3_backend(client))
    rows = _wordcount_pipeline()
    GraphRunner(G._current).run(persistence_config=cfg)
    assert rows

    G.clear()
    t = pw.debug.table_from_markdown(
        """
        city   | pop
        lisbon | 5
        """
    )
    _collect(t.select(t.city))
    cfg2 = pw.persistence.Config(_s3_backend(client))
    with pytest.raises(ValueError, match="different dataflow graph"):
        GraphRunner(G._current).run(persistence_config=cfg2)


def test_cached_objects_over_s3_store(tmp_path):
    from pathway_tpu.persistence.cached_objects import CachedObjectStorage

    client = DirS3Client(str(tmp_path / "fake-s3"))
    store = S3ObjectStore(client, "bucket", "cache")
    c1 = CachedObjectStorage(None, store=store)
    v1 = c1.place_object("s3://x/a", b"alpha", {"etag": "1"})
    c1.place_object("s3://x/b", b"beta", {"etag": "2"})
    c1.remove_object("s3://x/a")
    assert not c1.contains_object("s3://x/a")
    assert c1.get_object("s3://x/b") == b"beta"

    # a fresh instance over the same store replays the surviving events
    c2 = CachedObjectStorage(None, store=store)
    assert c2.actual_key_set() == {"s3://x/b"}
    assert c2.get_object("s3://x/b") == b"beta"
    assert c2.get_metadata("s3://x/b") == {"etag": "2"}

    # rewind durably drops newer events
    c2.rewind(v1)
    c3 = CachedObjectStorage(None, store=store)
    assert c3.actual_key_set() == {"s3://x/a"}
    assert c3.get_object("s3://x/a") == b"alpha"


def test_memory_object_store_contract():
    s = MemoryObjectStore()
    s.put("a/1", b"x")
    s.put("a/2", b"y")
    s.put("b/1", b"z")
    assert s.list("a/") == ["a/1", "a/2"]
    assert s.get("a/1") == b"x"
    assert s.get("missing") is None
    s.delete("a/1")
    assert s.list("a/") == ["a/2"]


_CRASH_SCRIPT = """
import json, os, sys
sys.path.insert(0, "/root/repo")
import pathway_tpu as pw
from tests.mocks import DirS3Client

input_dir, out_path, s3_dir = sys.argv[1], sys.argv[2], sys.argv[3]
t = pw.io.csv.read(input_dir, schema=pw.schema_builder({"word": str}), mode="streaming", autocommit_duration_ms=20)
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
rows = {}

def on_change(key, row, time, is_addition):
    if is_addition:
        rows[key] = row
    else:
        rows.pop(key, None)
    with open(out_path + ".tmp", "w") as f:
        json.dump(list(rows.values()), f)
    os.replace(out_path + ".tmp", out_path)

pw.io.subscribe(counts, on_change)
client = DirS3Client(s3_dir)
backend = pw.persistence.Backend.s3("s3://bucket/ps", _client_factory=lambda settings: client)
cfg = pw.persistence.Config(backend, snapshot_interval_ms=10)
pw.run(persistence_config=cfg)
"""


def test_s3_crash_kill_and_restart_wordcount(tmp_path):
    """kill -9 mid-run with the S3 backend; restart resumes from frame objects
    + checkpoint blobs without double-counting."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    out_path = str(tmp_path / "out.json")
    s3_dir = str(tmp_path / "fake-s3")
    script = tmp_path / "prog.py"
    script.write_text(_CRASH_SCRIPT)

    (input_dir / "a.csv").write_text("word\n" + "\n".join(["cat"] * 5 + ["dog"] * 3) + "\n")

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, str(script), str(input_dir), out_path, s3_dir],
        env=env,
        cwd="/root/repo",
    )
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(out_path):
        time.sleep(0.1)
    assert os.path.exists(out_path), "pipeline never produced output"
    time.sleep(0.5)
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()

    (input_dir / "b.csv").write_text("word\n" + "\n".join(["cat"] * 2 + ["owl"] * 4) + "\n")

    proc = subprocess.Popen(
        [sys.executable, str(script), str(input_dir), out_path, s3_dir],
        env=env,
        cwd="/root/repo",
    )
    try:
        deadline = time.time() + 90
        expected = {"cat": 7, "dog": 3, "owl": 4}
        rows = {}
        while time.time() < deadline:
            try:
                with open(out_path) as f:
                    rows = {r["word"]: r["total"] for r in json.load(f)}
            except Exception:
                rows = {}
            if rows == expected:
                break
            time.sleep(0.2)
        assert rows == expected, f"got {rows}, want {expected}"
    finally:
        proc.kill()
        proc.wait()
