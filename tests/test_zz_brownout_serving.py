"""Serving-plane brownout integration (ISSUE 13): the noisy-neighbor flood
and the reshard quiesce window against a LIVE REST route.

Lives at the end of the suite's alphabetical order on purpose: these tests
start real `pw.run` engines behind REST connectors, and streaming REST
sources run forever (daemon threads) — parked here, their residual idle load
cannot skew earlier timing-sensitive tests (the fusion profiler-attribution
assertions in particular). Keep new always-on-server tests in this file.
"""

from __future__ import annotations

import json
import socket
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.brownout import get_brownout, reset_brownout
from pathway_tpu.internals.parse_graph import G

pytestmark = pytest.mark.autoscale

# -- serving plane: noisy neighbor + quiesce window ---------------------------


def _start_rest_echo(port: int, *, max_pending: int, delay_s: float):
    """A REST route backed by a deliberately slow engine pipeline (echo with
    a per-row sleep) — the downstream pressure the brownout/shed path needs."""
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    G.clear()
    ws = PathwayWebserver(host="127.0.0.1", port=port)

    class Q(pw.Schema):
        text: str

    queries, writer = rest_connector(
        webserver=ws, route="/v1/retrieve", schema=Q,
        max_pending=max_pending, delete_completed_queries=True,
        # these engines outlive the test as daemon threads (REST sources
        # stream forever); a lazy commit tick keeps their idle churn from
        # loading the rest of the suite's timing-sensitive tests
        autocommit_duration_ms=25,
    )

    def slow_echo(t):
        time.sleep(delay_s)
        return t

    writer(queries.select(result=pw.apply(slow_echo, pw.this.text)))
    threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE),
        daemon=True,
    ).start()
    deadline = time.monotonic() + 20
    while True:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            assert time.monotonic() < deadline, "REST server never came up"
            time.sleep(0.2)


def _post(port: int, text: str, client: str, timeout: float):
    """POST one retrieve; returns (status_code, elapsed_s)."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/retrieve",
        data=json.dumps({"text": text}).encode(),
        headers={
            "Content-Type": "application/json",
            "X-Pathway-Client": client,
        },
    )
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status, time.monotonic() - t0
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code, time.monotonic() - t0
    except Exception:
        # transient transport hiccup (reset/timeout under suite load): report
        # it as code 0 so caller loops retry instead of the THREAD dying
        return 0, time.monotonic() - t0


@pytest.mark.chaos
def test_noisy_neighbor_flood_attributed_and_other_clients_bounded(monkeypatch):
    """ROADMAP item-5 chaos op, landed against the global cap: one client
    floods ``/v1/retrieve`` (parameters from the chaos ``noisy_neighbor``
    plan op); the flood's sheds are ATTRIBUTED to it on the per-client shed
    counters, and the polite client's completion times stay bounded — shed
    fast with an honest Retry-After, never hung behind the flood."""
    from pathway_tpu.engine import telemetry
    from pathway_tpu.internals.chaos import get_chaos, reset_chaos

    monkeypatch.setenv("PATHWAY_CHAOS_PLAN", json.dumps({
        "load": {"op": "noisy_neighbor", "client": "flood", "rps": 60, "rows": 1},
    }))
    monkeypatch.setenv("PATHWAY_CHAOS_SEED", "1")
    reset_chaos()
    try:
        params = get_chaos().noisy_neighbor()
        assert params is not None
        port = 18791
        # 8 serial flood workers against a cap of 4: the flood EXCEEDS the
        # admission cap by construction, not by a timing race
        n_flood = 8
        _start_rest_echo(port, max_pending=4, delay_s=0.08)

        stop = threading.Event()
        flood_results: list = []
        flood_lock = threading.Lock()

        def flood_worker():
            gap = n_flood / max(1.0, params["rps"])  # workers share the rps
            while not stop.is_set():
                code, _t = _post(port, "flood query", params["client"], 30)
                with flood_lock:
                    flood_results.append(code)
                time.sleep(gap)

        floods = [
            threading.Thread(target=flood_worker, daemon=True)
            for _ in range(n_flood)
        ]
        for t in floods:
            t.start()
        time.sleep(1.0)  # let the flood saturate the admission cap
        polite: list = []  # (final_code, total_s incl. honest retries)
        try:
            for i in range(6):
                t0 = time.monotonic()
                code = None
                while time.monotonic() - t0 < 12.0:
                    code, _t = _post(port, f"polite {i}", "polite", 30)
                    if code == 200:
                        break
                    # the polite client honors Retry-After (bounded for the
                    # test): a shed is a FAST, honest signal, not a hang
                    time.sleep(0.5)
                polite.append((code, time.monotonic() - t0))
        finally:
            stop.set()
        for t in floods:
            t.join(timeout=10)
        # the flood was shed (429s) — and attributed to ITS client id
        assert any(code == 429 for code in flood_results), flood_results
        stages = telemetry.stage_snapshot("rest.shed")
        flood_sheds = stages.get("rest.shed.client.flood", 0.0)
        polite_sheds = stages.get("rest.shed.client.polite", 0.0)
        assert flood_sheds > 0, stages
        assert flood_sheds >= polite_sheds
        # the polite client is BOUNDED: every request completed (served after
        # honest retries) well inside the window instead of hanging behind
        # the flood — the shed-fast + Retry-After contract
        assert all(code == 200 for code, _t in polite), polite
        assert max(t for _c, t in polite) < 12.0, polite
        assert all(code in (0, 200, 429) for code in flood_results)
    finally:
        reset_chaos()
        reset_brownout()


@pytest.mark.chaos
def test_quiesce_window_serves_429_not_hangs():
    """While a membership transition has the commit loop paused, admitted
    requests would hang until C+1 — the REST plane must shed with 429 + the
    expected remaining pause as Retry-After instead (and recover the moment
    the quiesce lifts)."""
    from pathway_tpu.engine import telemetry

    port = 18797
    _start_rest_echo(port, max_pending=64, delay_s=0.0)
    reset_brownout()
    try:
        code, _t = _post(port, "before", "c1", 20)
        assert code == 200
        get_brownout().enter_quiesce(3.0)
        before = telemetry.stage_snapshot("rest.").get("rest.quiesce_shed", 0.0)
        t0 = time.monotonic()
        code, elapsed = _post(port, "during", "c1", 20)
        assert code == 429
        assert elapsed < 5.0  # shed fast, not parked until the pause ends
        assert (
            telemetry.stage_snapshot("rest.").get("rest.quiesce_shed", 0.0)
            > before
        )
        get_brownout().exit_quiesce()
        code, _t = _post(port, "after", "c1", 20)
        assert code == 200
        assert time.monotonic() - t0 < 20
    finally:
        reset_brownout()


