"""Quantized retrieval tower (ISSUE 16): per-page symmetric int8 payloads in
every tier with an exact fp32 rescore epilogue (``ops/knn_quant.py`` +
``ops/knn_tiers.py``). The contracts pinned here:

- returned scores are BITWISE what :func:`knn_quant.rescore_pairs` computes
  over the returned (query, slot) pairs from the fp32 source rows — the
  approximate int8 pass builds shortlists only;
- residency moves stay bitwise-invariant under int8 (exact integer dots in
  f32 — accumulation order cannot matter);
- sidecars (per-page scale/zero-point) survive frozen-spill serialization and
  rebuild-descriptor replication bit-exactly, and a recalibrated scale WINS
  over append-time re-derivation across the round-trip;
- mode mismatches are typed refusals (``QuantConfigError``), never silent
  fp32 fallbacks;
- scale recalibration rides the churn/maintenance path, and a ``quant`` chaos
  kill mid-recalibration leaves the OLD scales serving intact.

The recalibration protocol's schedule-exhaustive model checks live in
``test_modelcheck.py`` (``quant_recalibration_model``)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from pathway_tpu.ops import knn_quant
from pathway_tpu.ops.knn_quant import (
    PAGE,
    QuantConfigError,
    quant_mode,
    quantize_queries,
    rescore_pairs,
)
from pathway_tpu.ops.knn_tiers import (
    DirSpillStore,
    TieredIvfKnnStore,
    _ClusterPages,
)

pytestmark = pytest.mark.quant


def _clustered(n, dim, n_centers, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(n_centers, dim)).astype(np.float32)
    docs = (
        centers[rng.integers(0, n_centers, n)] + rng.normal(size=(n, dim))
    ).astype(np.float32)
    return centers, docs


def _exact_top(docs, queries, k):
    qn = np.sum(queries * queries, axis=1)[:, None]
    dn = np.sum(docs * docs, axis=1)[None, :]
    dist = qn + dn - 2.0 * queries @ docs.T
    return np.argsort(dist, axis=1)[:, :k]


def _int8_store(dim, n_clusters, n_probe, **kw):
    return TieredIvfKnnStore(
        dim, n_clusters=n_clusters, n_probe=n_probe, quant="int8", **kw
    )


def _assert_rescore_bitwise(store, queries, scores, idx):
    """The external honesty recompute ``bench.py quant`` also runs: every
    returned score must equal the pinned epilogue over the returned pair's
    fp32 source row, bit for bit."""
    qn = np.sum(queries * queries, axis=1)
    for r in range(len(queries)):
        m = idx[r] >= 0
        slots = idx[r][m].astype(int)
        if slots.size == 0:
            continue
        vecs = np.stack([store._vector_of(int(s)) for s in slots]).astype(
            np.float32
        )
        norms = np.sum(vecs * vecs, axis=1)
        exact = rescore_pairs(
            np.repeat(queries[r : r + 1], slots.size, axis=0),
            vecs,
            norms,
            np.repeat(qn[r : r + 1], slots.size),
            store.metric,
        ).astype(np.float32)
        np.testing.assert_array_equal(exact, scores[r][m])


# -- mode resolution ----------------------------------------------------------


def test_quant_mode_resolution_and_typed_refusals(monkeypatch):
    assert quant_mode("int8") == "int8"
    for off in (None, "", "off", "0", "false", "none", "No"):
        assert quant_mode(off) == "off" or off is None
    monkeypatch.delenv("PATHWAY_IVF_QUANT", raising=False)
    assert quant_mode() == "off"
    monkeypatch.setenv("PATHWAY_IVF_QUANT", "int8")
    assert quant_mode() == "int8"
    # fp8 is reserved sidecar format, not a silent fallback
    with pytest.raises(QuantConfigError, match="reserved"):
        quant_mode("fp8")
    # a typo'd mode must not silently serve fp32 under an int8 budget
    with pytest.raises(QuantConfigError, match="unknown"):
        quant_mode("int4")


def test_quant_opt_in_resolves_tiered_store_under_auto(monkeypatch):
    """``PATHWAY_IVF_QUANT=int8`` alone must engage the tiered store that
    hosts the tower — silently serving fp32 under an int8 opt-in would
    violate the loud-refusal contract. Explicit ``PATHWAY_IVF_TIERED=off``
    still wins, and no knobs at all keeps the untiered store bit-for-bit."""
    from pathway_tpu.ops.knn_tiers import tiering_enabled

    monkeypatch.delenv("PATHWAY_IVF_TIERED", raising=False)
    monkeypatch.delenv("PATHWAY_IVF_HBM_BUDGET_MB", raising=False)
    monkeypatch.delenv("PATHWAY_IVF_QUANT", raising=False)
    assert not tiering_enabled()
    monkeypatch.setenv("PATHWAY_IVF_QUANT", "int8")
    assert tiering_enabled()
    from pathway_tpu.ops.knn import IvfKnnIndex
    from pathway_tpu.ops.knn_tiers import TieredIvfKnnStore

    idx = IvfKnnIndex(8, n_clusters=2, n_probe=2)
    assert isinstance(idx.store, TieredIvfKnnStore)
    assert idx.store.quant == "int8"
    monkeypatch.setenv("PATHWAY_IVF_TIERED", "off")
    assert not tiering_enabled()


# -- recall + the pinned rescore epilogue -------------------------------------


def test_int8_full_probe_matches_exact_topk():
    _, docs = _clustered(3000, 24, 12, seed=31)
    store = _int8_store(24, 12, 12)
    store.add_many([f"d{i}" for i in range(3000)], docs)
    q = docs[:40]
    scores, idx, valid = store.search_batch(q, 10)
    assert valid.all()
    exact = _exact_top(docs, q, 10)
    for r in range(40):
        got = {store.key_of[int(i)] for i in idx[r] if i >= 0}
        assert got == {f"d{j}" for j in exact[r]}
    _assert_rescore_bitwise(store, q, scores, idx)
    store.close()


def test_rescore_bitwise_after_churn_and_dead_rows_masked():
    _, docs = _clustered(4000, 16, 8, seed=32)
    keys = [f"d{i}" for i in range(4000)]
    store = _int8_store(16, 8, 8)
    store.add_many(keys, docs)
    store.search_batch(docs[:4], 5)
    for i in range(0, 1500):
        store.remove(f"d{i}")
    q = docs[2000:2032]
    scores, idx, _v = store.search_batch(q, 10)
    dead = {f"d{i}" for i in range(1500)}
    for r in range(len(q)):
        got = {store.key_of.get(int(i)) for i in idx[r] if i >= 0}
        assert not (got & dead)
        assert None not in got
    _assert_rescore_bitwise(store, q, scores, idx)
    store.close()


def test_rescore_depth_follows_env_and_clamps_to_k(monkeypatch):
    """``PATHWAY_IVF_RESCORE_K`` sets the shortlist depth — but k always
    wins when it is deeper (the shortlist never truncates below what the
    caller asked for). Pinned via the rescore-depth histogram the epilogue
    observes, not via recall: at depth 4 near-ties in a crowded dim-8 set
    legitimately land outside the shortlist, which is WHY the default is
    64 — recall-at-depth is bench.py's honesty key, not a unit invariant."""
    from pathway_tpu.engine.profile import histogram

    monkeypatch.setenv("PATHWAY_IVF_RESCORE_K", "4")
    assert knn_quant.rescore_k() == 4
    _, docs = _clustered(600, 8, 4, seed=33)
    store = _int8_store(8, 4, 4)
    store.add_many([f"d{i}" for i in range(600)], docs)
    hist = histogram("pathway_ivf_quant_rescore_depth")

    def observed_depth(k):
        c0, s0 = hist.count, hist.sum
        scores, idx, valid = store.search_batch(docs[:8], k)
        assert valid.all()
        # the query is its own document: the self-match dominates every
        # shortlist, so the top hit is exact even at starvation depth
        for r in range(8):
            assert store.key_of[int(idx[r][0])] == f"d{r}"
            assert np.count_nonzero(idx[r] >= 0) == k
        _assert_rescore_bitwise(store, docs[:8], scores, idx)
        assert hist.count == c0 + 1
        return hist.sum - s0

    assert observed_depth(2) == 4.0  # env floor applies above k
    assert observed_depth(12) == 12.0  # k wins when deeper than the env
    store.close()


# -- residency + spill round-trips --------------------------------------------


def test_residency_moves_bitwise_invariant_under_int8(tmp_path):
    import time

    centers, docs = _clustered(4000, 16, 8, seed=34)
    keys = [f"d{i}" for i in range(4000)]
    rng = np.random.default_rng(35)
    q = (centers[np.zeros(16, dtype=int)] + rng.normal(size=(16, 16))).astype(
        np.float32
    )
    tiered = _int8_store(
        16, 8, 2,
        hbm_budget_bytes=30_000,
        spill_store=DirSpillStore(str(tmp_path / "spill")),
    )
    allhot = _int8_store(16, 8, 2)
    tiered.add_many(keys, docs)
    allhot.add_many(keys, docs)
    for _ in range(6):  # settle the EWMA; spill + demotion engage
        rt = tiered.search_batch(q, 10)
        rh = allhot.search_batch(q, 10)
    time.sleep(0.3)  # the prefetch worker drains its staging queue
    rt = tiered.search_batch(q, 10)
    rh = allhot.search_batch(q, 10)
    stats = tiered.tier_stats()
    assert stats["spilled"] > 0 or stats["spills"] > 0, stats
    np.testing.assert_array_equal(rt[0], rh[0])
    np.testing.assert_array_equal(rt[1], rh[1])
    tiered.close()
    allhot.close()


def test_sidecars_survive_blob_roundtrip_bit_exact():
    rng = np.random.default_rng(36)
    n = PAGE + 40  # two pages, second partial
    vecs = rng.normal(scale=3.0, size=(n, 12)).astype(np.float32)
    norms = np.sum(vecs * vecs, axis=1)
    block = _ClusterPages(12, cap=2 * PAGE, quant=True)
    block.append(np.arange(n, dtype=np.int64), vecs, norms)
    thawed = _ClusterPages.from_blob(12, block.to_blob(), quant=True)
    np.testing.assert_array_equal(thawed.qvecs[:n], block.qvecs[:n])
    np.testing.assert_array_equal(thawed.qscale, block.qscale)
    np.testing.assert_array_equal(thawed.qzero, block.qzero)


def test_recalibrated_scale_wins_blob_roundtrip():
    """A recalibration that tightened the scales pre-freeze must survive the
    spill round-trip by COPY: the thawed block serves the recalibrated codes,
    not an append-time re-derivation from the fp32 rows."""
    rng = np.random.default_rng(37)
    n = PAGE
    vecs = rng.normal(size=(n, 12)).astype(np.float32)
    norms = np.sum(vecs * vecs, axis=1)
    block = _ClusterPages(12, cap=PAGE, quant=True)
    block.append(np.arange(n, dtype=np.int64), vecs, norms)
    derived_scale = float(block.qscale[0])
    # recalibrate to a DIFFERENT (tighter) scale than append would derive —
    # e.g. after the max-magnitude row died; install codes to match
    tight = np.float32(derived_scale / 2.0)
    block.qscale[0] = tight
    block.qvecs[:n] = knn_quant.quantize_rows(vecs, float(tight))
    block._drop_quant_caches()
    thawed = _ClusterPages.from_blob(12, block.to_blob(), quant=True)
    assert thawed.qscale[0] == tight != np.float32(derived_scale)
    np.testing.assert_array_equal(thawed.qvecs[:n], block.qvecs[:n])


def test_pre_quant_blob_thaws_into_quant_store():
    """A blob frozen BEFORE quantization was enabled carries no sidecars:
    thawing it under quant=True re-derives codes instead of failing."""
    rng = np.random.default_rng(38)
    vecs = rng.normal(size=(PAGE, 12)).astype(np.float32)
    norms = np.sum(vecs * vecs, axis=1)
    plain = _ClusterPages(12, cap=PAGE, quant=False)
    plain.append(np.arange(PAGE, dtype=np.int64), vecs, norms)
    thawed = _ClusterPages.from_blob(12, plain.to_blob(), quant=True)
    assert thawed.quant
    want_codes, want_scale, _ = knn_quant.quantize_block(thawed.vecs)
    np.testing.assert_array_equal(thawed.qvecs[:PAGE], want_codes[:PAGE])
    np.testing.assert_array_equal(thawed.qscale, want_scale)


# -- descriptor / membership replication --------------------------------------


def test_rebuild_descriptor_carries_quant_state_and_roundtrips(monkeypatch):
    from pathway_tpu.ops.knn import IvfKnnIndex

    monkeypatch.setenv("PATHWAY_IVF_QUANT", "int8")
    monkeypatch.setenv("PATHWAY_IVF_TIERED", "on")
    _, docs = _clustered(1200, 16, 6, seed=39)
    keys = [f"d{i}" for i in range(1200)]
    src = IvfKnnIndex(16, n_clusters=6, n_probe=6, tiered=True)
    for key, vec in zip(keys, docs):
        src.add(key, vec)
    src.store.search_batch(docs[:4], 5)
    desc = src.rebuild_descriptor()
    assert desc is not None
    assert desc["quant"]["mode"] == "int8"
    assert desc["quant"]["dtype"] == "int8"
    clusters = desc["quant"]["clusters"]
    assert clusters, "resident clusters must publish their sidecars"
    for entry in clusters.values():
        assert entry["qscale"].dtype == np.float32
        assert entry["qzero"].dtype == np.float32
        assert entry["rows"] > 0
    dst = IvfKnnIndex(16, n_clusters=6, n_probe=6, tiered=True)
    dst.install_rebuild_descriptor(desc)
    q = docs[:16]
    exact = _exact_top(docs, q, 5)
    scores, idx, _valid = dst.store.search_batch(q, 5)
    for r in range(16):
        got = {dst.store.key_of[int(i)] for i in idx[r] if i >= 0}
        assert got == {f"d{j}" for j in exact[r]}
    _assert_rescore_bitwise(dst.store, q, scores, idx)


def test_rebuild_descriptor_mode_mismatch_is_typed_refusal(monkeypatch):
    from pathway_tpu.ops.knn import IvfKnnIndex

    monkeypatch.setenv("PATHWAY_IVF_QUANT", "int8")
    _, docs = _clustered(400, 8, 4, seed=40)
    src = IvfKnnIndex(8, n_clusters=4, n_probe=4, tiered=True)
    for i in range(400):
        src.add(f"d{i}", docs[i])
    desc = src.rebuild_descriptor()
    assert desc["quant"]["mode"] == "int8"
    monkeypatch.setenv("PATHWAY_IVF_QUANT", "off")
    plain = IvfKnnIndex(8, n_clusters=4, n_probe=4, tiered=True)
    with pytest.raises(QuantConfigError, match="quant mode"):
        plain.install_rebuild_descriptor(desc)


def test_sharded_store_aggregates_quant_state():
    from pathway_tpu.parallel import ShardedIvfKnnStore, make_mesh

    mesh = make_mesh(8)
    _, docs = _clustered(600, 16, 4, seed=41)
    keys = [f"d{i}" for i in range(600)]
    sharded = ShardedIvfKnnStore(
        mesh, 16, n_clusters=4, n_probe=4, tiered=True, quant="int8"
    )
    assert sharded.quant == "int8"
    sharded.add_many(keys, docs)
    sharded.search_batch(docs[:4], 5)
    state = sharded.quant_state()
    assert state["mode"] == "int8"
    assert state["clusters"], "per-shard sidecars must aggregate"
    assert all(":" in cid for cid in state["clusters"])  # shard-prefixed
    # search through the quantized shards still matches exact top-k
    q = docs[:12]
    exact = _exact_top(docs, q, 5)
    _s, idx, valid = sharded.search_batch(q, 5)
    assert valid.all()
    for r in range(12):
        got = {sharded.key_of[int(x)] for x in idx[r] if x >= 0}
        assert got == {f"d{j}" for j in exact[r]}
    # the flat (non-tiered) sharded store has no quantized blocks: the
    # resolved mode must SAY so, not pretend
    flat = ShardedIvfKnnStore(
        mesh, 16, n_clusters=4, n_probe=4, tiered=False, quant="int8"
    )
    assert flat.quant == "off"
    assert flat.quant_state() == {"mode": "off"}


# -- recalibration + chaos ----------------------------------------------------


def test_scale_recalibration_rides_maintenance_after_churn():
    _, docs = _clustered(2000, 16, 4, seed=42)
    keys = [f"d{i}" for i in range(2000)]
    store = _int8_store(16, 4, 4)
    store.add_many(keys, docs)
    store.search_batch(docs[:4], 5)
    # kill a third of every cluster: dead rows may pin page scales
    for i in range(0, 2000, 3):
        store.remove(f"d{i}")
    for cid in range(store.n_clusters):
        store._maintain_cluster(cid)
    assert store.stats["quant_recalibrations"] >= 1, store.stats
    q = docs[1:33]
    live = [i for i in range(2000) if i % 3 != 0]
    exact = _exact_top(docs[live], q, 5)
    scores, idx, _v = store.search_batch(q, 5)
    for r in range(32):
        got = {store.key_of.get(int(i)) for i in idx[r] if i >= 0}
        assert got == {f"d{live[j]}" for j in exact[r]}
    _assert_rescore_bitwise(store, q, scores, idx)
    store.close()


@pytest.mark.chaos
def test_chaos_quant_kill_serves_old_scales_then_recovers(monkeypatch):
    """Injected ``quant`` chaos op at recalibration attempt 0: the freshly
    computed sidecars are discarded BEFORE anything re-points, the old scales
    keep serving (results still exact — the fp32 rescore is untouched), and
    the next maintenance pass recalibrates cleanly."""
    from pathway_tpu.internals.chaos import reset_chaos

    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps({"index": [{"op": "quant", "rank": 0, "at": 0}]}),
    )
    monkeypatch.setenv("PATHWAY_CHAOS_SEED", "5")
    reset_chaos()
    try:
        _, docs = _clustered(1200, 16, 4, seed=43)
        keys = [f"d{i}" for i in range(1200)]
        store = _int8_store(16, 4, 4)
        store.add_many(keys, docs)
        store.search_batch(docs[:4], 5)
        # churn enough rows that maintenance wants to recalibrate; the plan
        # gates on rebuild attempt 0, so EVERY recalibration in this window
        # aborts before install (drift-triggered ones from remove() included)
        for i in range(0, 1200, 2):
            store.remove(f"d{i}")
        for cid in range(store.n_clusters):
            store._maintain_cluster(cid)
        assert store.stats["quant_chaos_aborts"] >= 1, store.stats
        assert store.stats["quant_recalibrations"] == 0, store.stats
        # old scales keep serving: results stay EXACT (the fp32 rescore
        # epilogue never depended on the sidecars that got discarded)
        q = docs[1:17]
        live = [i for i in range(1200) if i % 2 == 1]
        exact = _exact_top(docs[live], q, 5)
        scores, idx, _v = store.search_batch(q, 5)
        for r in range(16):
            got = {store.key_of.get(int(i)) for i in idx[r] if i >= 0}
            assert got == {f"d{live[j]}" for j in exact[r]}
        _assert_rescore_bitwise(store, q, scores, idx)
        # chaos lifted (process restarted / plan expired): the next
        # maintenance pass recalibrates and installs cleanly
        aborts = store.stats["quant_chaos_aborts"]
        monkeypatch.setenv("PATHWAY_CHAOS_PLAN", "{}")
        reset_chaos()
        for cid in range(store.n_clusters):
            store._maintain_cluster(cid)
        assert store.stats["quant_chaos_aborts"] == aborts
        assert store.stats["quant_recalibrations"] >= 1, store.stats
        scores, idx, _v = store.search_batch(q, 5)
        for r in range(16):
            got = {store.key_of.get(int(i)) for i in idx[r] if i >= 0}
            assert got == {f"d{live[j]}" for j in exact[r]}
        store.close()
    finally:
        reset_chaos()


# -- kernels / caches / observability -----------------------------------------


def test_quant_kernels_registered_in_cache_sizes():
    from pathway_tpu.ops.knn import kernel_cache_sizes

    sizes = kernel_cache_sizes()
    assert "quant_probe" in sizes
    assert "quant_score" in sizes


def test_device_kernel_parity_with_host_path():
    """The jitted block kernel and the host epilogue run the same operations
    in the same order — but the COMPILER may still contract the epilogue's
    multiply+add into an FMA (XLA-CPU does, for the l2sq branch), which is a
    1-ulp divergence numpy cannot reproduce. That is precisely why the store
    runs a FIRST-USE PARITY PROBE instead of trusting the lockstep: any byte
    of disagreement permanently downgrades that store to the host path, so
    served scores stay pinned to the host bytes either way. Here we pin the
    contract the probe relies on: agreement within 1 ulp everywhere (same
    math), and bitwise where no mul+add contraction is available to fuse."""
    import jax.numpy as jnp

    rng = np.random.default_rng(44)
    cap, dim, nq = PAGE, 16, 8
    vecs = rng.normal(scale=3.0, size=(cap, dim)).astype(np.float32)
    norms = np.sum(vecs * vecs, axis=1)
    qvecs, qscale, _qzero = knn_quant.quantize_block(vecs)
    srow = knn_quant.row_scales(qscale, cap)
    mask = np.where(rng.random(cap) < 0.9, np.float32(0.0), np.float32(-np.inf))
    queries = rng.normal(size=(nq, dim)).astype(np.float32)
    q_codes, q_scales = quantize_queries(queries)
    qn = np.sum(queries * queries, axis=1)
    for metric in ("l2sq", "cos", "ip"):
        host = knn_quant.approx_scores(
            q_codes.astype(np.float32), q_scales, qn,
            qvecs.astype(np.float32), srow, norms, metric, maskadd=mask,
        )
        dev = np.asarray(
            knn_quant.quant_score_block_kernel(
                jnp.asarray(qvecs), jnp.asarray(srow), jnp.asarray(norms),
                jnp.asarray(mask), jnp.asarray(q_codes),
                jnp.asarray(q_scales), jnp.asarray(qn), metric,
            )
        )
        finite = np.isfinite(host)
        assert np.array_equal(finite, np.isfinite(dev)), metric
        ulp = np.spacing(np.maximum(np.abs(host[finite]), np.abs(dev[finite])))
        assert np.all(np.abs(host[finite] - dev[finite]) <= ulp), metric
        np.testing.assert_array_equal(host[~finite], dev[~finite])
        if metric == "ip":  # scale*dot then separate mask add: nothing to fuse
            np.testing.assert_array_equal(host, dev)


def test_device_parity_probe_downgrades_or_matches_end_to_end():
    """Whatever the compiler does, a store WITH a hot device mirror must
    serve byte-identical results to a host-only store: either the kernel
    agrees bitwise, or the first-use probe flags it and the store scores on
    host forever after. Both branches land on the same bytes."""
    _, docs = _clustered(1500, 16, 4, seed=49)
    keys = [f"d{i}" for i in range(1500)]
    mirrored = _int8_store(16, 4, 4)  # default budget: everything hot-mirrors
    hostonly = _int8_store(16, 4, 4, hbm_budget_bytes=0)
    mirrored.add_many(keys, docs)
    hostonly.add_many(keys, docs)
    q = docs[:24]
    for _ in range(4):  # settle: give mirrors time to stage + probe to fire
        rm = mirrored.search_batch(q, 10)
        rh = hostonly.search_batch(q, 10)
    np.testing.assert_array_equal(rm[0], rh[0])
    np.testing.assert_array_equal(rm[1], rh[1])
    mirrored.close()
    hostonly.close()


def test_negnorm_fused_epilogue_bitwise_equals_unfused():
    rng = np.random.default_rng(45)
    cap, dim, nq = 64, 12, 4
    vecs = rng.normal(size=(cap, dim)).astype(np.float32)
    norms = np.sum(vecs * vecs, axis=1)
    qvecs, qscale, _ = knn_quant.quantize_block(vecs)
    srow = knn_quant.row_scales(qscale, cap)
    mask = np.where(rng.random(cap) < 0.8, np.float32(0.0), np.float32(-np.inf))
    queries = rng.normal(size=(nq, dim)).astype(np.float32)
    q_codes, q_scales = quantize_queries(queries)
    qn = np.sum(queries * queries, axis=1)
    qf = q_codes.astype(np.float32)
    df = qvecs.astype(np.float32)
    unfused = knn_quant.approx_scores(
        qf, q_scales, qn, df, srow, norms, "l2sq", maskadd=mask
    )
    fused = knn_quant.approx_scores(
        qf, q_scales, qn, df, srow, norms, "l2sq",
        negnorm=(mask - norms).astype(np.float32),
    )
    np.testing.assert_array_equal(unfused, fused)


def test_block_maskadd_and_negn_caches_invalidate_on_mutation():
    rng = np.random.default_rng(46)
    vecs = rng.normal(size=(PAGE, 8)).astype(np.float32)
    norms = np.sum(vecs * vecs, axis=1)
    block = _ClusterPages(8, cap=PAGE, quant=True)
    block.append(np.arange(PAGE, dtype=np.int64), vecs, norms)
    m0 = block.maskadd(PAGE)
    n0 = block.negn(PAGE)
    assert block.maskadd(PAGE) is m0  # cached handle, no rebuild
    assert block.negn(PAGE) is n0
    assert np.all(m0 == 0.0)
    # kill a row the way the store does: validity flip + mutation bump
    block.valid[3] = False
    block.n_live -= 1
    block.mutations += 1
    m1 = block.maskadd(PAGE)
    n1 = block.negn(PAGE)
    assert m1 is not m0 and n1 is not n0
    assert m1[3] == -np.inf and np.isneginf(n1[3])
    np.testing.assert_array_equal(
        np.delete(n1, 3), np.delete((m1 - norms).astype(np.float32), 3)
    )


def test_quant_metrics_on_openmetrics_strict():
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.http_server import ProberStats
    from pathway_tpu.engine.profile import histograms

    from .utils import validate_openmetrics

    _, docs = _clustered(800, 8, 4, seed=47)
    store = _int8_store(8, 4, 4)
    store.add_many([f"d{i}" for i in range(800)], docs)
    store.search_batch(docs[:8], 5)
    ratio = store.quant_recall_audit(docs[:16], k=5)
    assert ratio == 1.0
    assert histograms()["pathway_ivf_quant_rescore_depth"].count > 0
    assert histograms()["pathway_ivf_quant_recall_ratio"].count > 0
    text = ProberStats().to_openmetrics()
    validate_openmetrics(text)
    assert "pathway_ivf_quant_rescore_depth" in text
    assert "pathway_ivf_quant_recall_ratio" in text
    assert 'pathway_stage_total{stage="index.quant.batches"}' in text
    assert telemetry.stage_snapshot().get("index.quant.batches", 0) > 0
    store.close()


# -- quantized query encode ---------------------------------------------------


def test_quant_encode_gating_follows_index_mode(monkeypatch):
    from pathway_tpu.models.encoder import quant_encode_enabled

    monkeypatch.delenv("PATHWAY_IVF_QUANT_ENCODE", raising=False)
    monkeypatch.setenv("PATHWAY_IVF_QUANT", "int8")
    assert quant_encode_enabled()  # auto follows the index mode
    monkeypatch.setenv("PATHWAY_IVF_QUANT", "off")
    assert not quant_encode_enabled()
    monkeypatch.setenv("PATHWAY_IVF_QUANT_ENCODE", "on")
    assert quant_encode_enabled()  # forced on, index fp32
    monkeypatch.setenv("PATHWAY_IVF_QUANT", "int8")
    monkeypatch.setenv("PATHWAY_IVF_QUANT_ENCODE", "off")
    assert not quant_encode_enabled()  # forced off, index int8


def test_lattice_encoded_queries_requantize_code_stable():
    """The encoder's quantized tower folds ``round(v/s) * s`` into the
    forward; re-quantizing those lattice rows must reproduce the codes
    EXACTLY (the row max is itself a lattice point) — zero added rounding
    between the encode and the int8 scorer."""
    rng = np.random.default_rng(48)
    raw = rng.normal(size=(32, 24)).astype(np.float32)
    codes1, scales1 = quantize_queries(raw)
    lattice = (codes1.astype(np.float32) * scales1[:, None]).astype(np.float32)
    codes2, _scales2 = quantize_queries(lattice)
    np.testing.assert_array_equal(codes1, codes2)


def test_embed_and_semantic_caches_key_on_quant_mode():
    from pathway_tpu.models.embed_pipeline import EmbedCache
    from pathway_tpu.models.encoder_service import SemanticQueryCache

    vec = np.arange(4, dtype=np.float32)
    plain = EmbedCache(16, model="m")
    tagged = EmbedCache(16, model="m|quant:int8")
    plain.put("hello", vec)
    assert plain.get("hello") is not None
    assert tagged.get("hello") is None  # geometry flip misses, never serves
    sem_plain = SemanticQueryCache(16, mode="exact")
    sem_tagged = SemanticQueryCache(16, mode="exact", key_tag="quant:int8")
    sem_plain.put("hello world", vec)
    assert sem_plain.get("hello world") is not None
    assert sem_tagged.get("hello world") is None
