"""Temporal behavior mechanics: buffer (delay), freeze+forget (cutoff), exactly-once.

Mirrors the reference's window-behavior test surface (``python/pathway/tests/temporal/``,
engine semantics from ``src/engine/dataflow/operators/time_column.rs``).
"""

from __future__ import annotations

import pathway_tpu as pw

from .utils import T, capture_rows, capture_update_stream


def _win_rows(res):
    return sorted(
        (r["_pw_window_start"], r["cnt"]) for r in capture_rows(res)
    )


def test_tumbling_delay_buffers_until_time_passes():
    t = T(
        """
        t | __time__
        1 | 0
        3 | 2
        9 | 4
        """
    )
    w = t.windowby(
        t.t,
        window=pw.temporal.tumbling(duration=2),
        behavior=pw.temporal.common_behavior(delay=2),
    )
    res = w.reduce(pw.this._pw_window_start, cnt=pw.reducers.count())
    stream = capture_update_stream(res)
    # all three windows present at the end (close flushes the buffer)
    finals = sorted(
        (r["_pw_window_start"], r["cnt"]) for r in stream if r["__diff__"] > 0
    )
    assert finals == [(0, 1), (2, 1), (8, 1)]
    # window [0,2) (threshold start+2=2) must not be emitted before the row with t=3
    # arrived (engine commit time 2)
    w0 = [r for r in stream if r["_pw_window_start"] == 0]
    assert all(r["__time__"] >= 2 for r in w0)


def test_exactly_once_single_emission_per_window():
    t = T(
        """
        t | __time__
        0 | 0
        1 | 2
        5 | 4
        """
    )
    w = t.windowby(
        t.t,
        window=pw.temporal.tumbling(duration=2),
        behavior=pw.temporal.exactly_once_behavior(),
    )
    res = w.reduce(pw.this._pw_window_start, cnt=pw.reducers.count())
    stream = capture_update_stream(res)
    # window [0,2) holds two rows arriving in different commits; exactly-once means a
    # single insertion with the final count and no retraction ever
    w0 = [r for r in stream if r["_pw_window_start"] == 0]
    assert [(r["cnt"], r["__diff__"]) for r in w0] == [(2, 1)]
    assert all(r["__diff__"] > 0 for r in stream)


def test_cutoff_ignores_late_rows_keep_results():
    t = T(
        """
        t | __time__
        1 | 0
        5 | 2
        1 | 4
        """
    )
    # cutoff=0: window [0,2) stops accepting once time reaches its end; the late t=1 row
    # at commit 4 is ignored, but delivered results stay (keep_results=True default)
    w = t.windowby(
        t.t,
        window=pw.temporal.tumbling(duration=2),
        behavior=pw.temporal.common_behavior(cutoff=0),
    )
    res = w.reduce(pw.this._pw_window_start, cnt=pw.reducers.count())
    assert _win_rows(res) == [(0, 1), (4, 1)]


def test_cutoff_keep_results_false_removes_closed_windows():
    t = T(
        """
        t | __time__
        1 | 0
        9 | 2
        """
    )
    w = t.windowby(
        t.t,
        window=pw.temporal.tumbling(duration=2),
        behavior=pw.temporal.common_behavior(cutoff=0, keep_results=False),
    )
    res = w.reduce(pw.this._pw_window_start, cnt=pw.reducers.count())
    # window [0,2) was forgotten (time passed 2+cutoff) and results removed;
    # window [8,10) never hit its cutoff so it stays
    assert _win_rows(res) == [(8, 1)]


def test_table_buffer_operator_order():
    t = T(
        """
        v | __time__
        4 | 0
        1 | 2
        2 | 4
        """
    )
    # buffer until the stream's time (v values) reaches v: v=4 arrives first but is only
    # emitted once now >= 4 — which never happens from later rows, so it flushes at close
    buffered = t._buffer(pw.this.v, pw.this.v)
    stream = capture_update_stream(buffered)
    emitted = [(r["v"], r["__time__"]) for r in stream if r["__diff__"] > 0]
    assert sorted(v for v, _ in emitted) == [1, 2, 4]
    t1 = dict(emitted)[1]
    t2 = dict(emitted)[2]
    assert t1 <= t2


def test_intervals_over_outer_emits_empty_windows():
    data = T(
        """
        t  | v
        2  | 10
        3  | 20
        """
    )
    probes = T(
        """
        at
        2
        6
        """
    )
    w = data.windowby(
        data.t,
        window=pw.temporal.intervals_over(
            at=probes.at, lower_bound=-1, upper_bound=0, is_outer=True
        ),
    )
    res = w.reduce(pw.this._pw_window_start, cnt=pw.reducers.count())
    rows = sorted(
        (r["_pw_window_start"], r["cnt"]) for r in capture_rows(res)
    )
    # at=2 sees rows t in [1,2] -> just t=2; at=6 sees nothing but still yields a window
    assert rows == [(2, 1), (6, None)]
