"""Metrics plane: log-bucketed histograms, per-operator commit profiles, the
flight recorder ring, and the strict-grammar OpenMetrics exporter."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.http_server import MonitoringServer, ProberStats
from pathway_tpu.engine.profile import (
    CommitProfile,
    FlightRecorder,
    LogHistogram,
    get_profiler,
    histogram,
    reset_profile,
)
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G

from .utils import validate_openmetrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_small_graph():
    G.clear()
    t = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    out = t.groupby(pw.this.a).reduce(pw.this.a, n=pw.reducers.count())
    pw.io.subscribe(out, lambda *a, **k: None)
    runner = GraphRunner(G._current)
    runner.run()
    return runner


# -- LogHistogram -------------------------------------------------------------


def test_log_histogram_quantiles_track_truth():
    import random

    rng = random.Random(7)
    h = LogHistogram()
    values = sorted(rng.uniform(0.0005, 0.2) for _ in range(5000))
    for v in values:
        h.observe(v)
    for q in (0.5, 0.95, 0.99):
        true = values[int(q * len(values)) - 1]
        est = h.quantile(q)
        # log2 buckets bound the error to one octave
        assert true / 2 <= est <= true * 2, (q, est, true)
    pct = h.percentiles()
    assert pct["p50"] <= pct["p95"] <= pct["p99"]
    assert h.count == 5000


def test_log_histogram_edges_and_reset():
    h = LogHistogram()
    h.observe(0.0)  # below the first bound
    h.observe(1e9)  # beyond the last bound -> +Inf overflow
    h.observe(h.bounds[3])  # exactly a bound: le is inclusive
    assert h.counts[0] == 1
    assert h.counts[-1] == 1
    assert h.counts[3] == 1
    assert h.quantile(0.5) > 0
    h.reset()
    assert h.count == 0 and h.quantile(0.5) == 0.0


def test_log_histogram_openmetrics_shape():
    h = LogHistogram()
    for v in (0.001, 0.004, 0.1, 3.0):
        h.observe(v)
    text = "\n".join(h.openmetrics_lines("x_seconds", "test hist")) + "\n# EOF\n"
    fams = validate_openmetrics(text)
    assert fams["x_seconds"]["type"] == "histogram"


# -- per-operator profiles ----------------------------------------------------


@pytest.mark.telemetry
def test_commit_profiles_capture_operator_timings():
    reset_profile()
    _run_small_graph()
    prof = get_profiler()
    assert prof.commits >= 1
    # daemon runners leaked by OTHER tests (REST servers never stop) also feed
    # the process-wide profiler — assert on THIS graph's operators existing,
    # not on exclusive ownership of the totals
    groupbys = [e for e in prof.operator_totals() if e["kind"] == "groupby"]
    inputs = [e for e in prof.operator_totals() if e["kind"] == "input"]
    assert groupbys and inputs
    assert any(e["rows"] == 3 for e in groupbys)
    assert all(e["seconds"] > 0 for e in groupbys)
    assert all(e["calls"] >= 1 for e in groupbys)
    snap = prof.snapshot()
    assert snap["commits"] >= 1
    assert snap["commit_duration_ms"]["p50"] > 0
    assert snap["operators"][0]["seconds"] >= snap["operators"][-1]["seconds"]


@pytest.mark.telemetry
def test_profile_env_gate_disables_operator_timing(monkeypatch):
    """The runner-level gate: with PATHWAY_PROFILE=0 the runner never binds
    the profiler (asserted on the runner, not on global totals — daemon
    runners leaked by other tests feed the process-wide profiler forever)."""
    monkeypatch.setenv("PATHWAY_PROFILE", "0")
    runner = _run_small_graph()
    assert runner._profiler is None
    assert runner._profile_ops is None
    monkeypatch.setenv("PATHWAY_PROFILE", "1")
    runner = _run_small_graph()
    assert runner._profiler is not None


@pytest.mark.telemetry
def test_retractions_counted_per_operator():
    reset_profile()
    t = pw.debug.table_from_markdown(
        """
        a | __time__ | __diff__
        1 | 2        | 1
        2 | 2        | 1
        1 | 4        | -1
        """
    )
    pw.io.subscribe(t, lambda *a, **k: None)
    GraphRunner(G._current).run()
    inputs = [e for e in get_profiler().operator_totals() if e["kind"] == "input"]
    assert any(e["retractions"] == 1 for e in inputs), inputs


# -- OpenMetrics exporter -----------------------------------------------------


@pytest.mark.telemetry
def test_metrics_endpoint_full_plane_passes_strict_grammar():
    """The acceptance surface: /metrics exposes per-operator time/rows series
    and commit-duration histogram buckets, all valid OpenMetrics."""
    from pathway_tpu.engine import telemetry

    reset_profile()
    telemetry.stage_reset()
    telemetry.stage_add("embed.cache_hits", 5)
    telemetry.stage_add("exchange.peer1.bytes_sent", 1024)
    histogram("pathway_rest_latency_seconds").observe(0.004)
    runner = _run_small_graph()
    stats = runner.prober_stats
    server = MonitoringServer(stats, 0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).read().decode()
    finally:
        server.close()
    fams = validate_openmetrics(body)
    assert fams["pathway_operator_seconds"]["type"] == "counter"
    op_samples = fams["pathway_operator_seconds"]["samples"]
    kinds = {s[1]["kind"] for s in op_samples}
    assert "groupby" in kinds
    assert any(s[2] > 0 for s in op_samples)
    assert fams["pathway_operator_rows"]["samples"]
    assert fams["pathway_commit_duration_seconds"]["type"] == "histogram"
    assert fams["pathway_rest_latency_seconds"]["type"] == "histogram"
    stage_samples = {s[1]["stage"]: s[2] for s in fams["pathway_stage"]["samples"]}
    assert stage_samples["embed.cache_hits"] == 5
    assert stage_samples["exchange.peer1.bytes_sent"] == 1024


@pytest.mark.telemetry
def test_openmetrics_label_escaping():
    from pathway_tpu.engine import telemetry

    reset_profile()
    telemetry.stage_reset()
    # quotes/backslashes must escape; commas and braces are LEGAL inside a
    # quoted label value (user-settable operator names) and must round-trip
    # through the strict checker
    telemetry.stage_add('we"ird\\stage', 1)
    telemetry.stage_add("join(a,b){x}", 2)
    try:
        stats = ProberStats()
        fams = validate_openmetrics(stats.to_openmetrics())
        values = {s[1]["stage"]: s[2] for s in fams["pathway_stage"]["samples"]}
        assert values['we\\"ird\\\\stage'] == 1
        assert values["join(a,b){x}"] == 2
    finally:
        telemetry.stage_reset()


# -- /v1/statistics -----------------------------------------------------------


@pytest.mark.telemetry
def test_statistics_query_surfaces_engine_snapshot():
    from .test_xpack_llm import _store
    from .utils import capture_rows

    reset_profile()
    _run_small_graph()  # the snapshot reports PRIOR commits (it is read
    G.clear()  # mid-commit, before the current commit's profile lands)
    store = _store()
    stats_q = pw.debug.table_from_rows(pw.schema_builder({"dummy": int}), [(1,)])
    rows = capture_rows(store.statistics_query(stats_q))
    stats = rows[0]["result"].value
    assert "engine" in stats
    assert stats["engine"]["commits"] >= 1
    assert "p95" in stats["engine"]["commit_duration_ms"]
    assert any(op["kind"] == "input" for op in stats["engine"]["operators"])


# -- flight recorder ----------------------------------------------------------


def _profile_for(commit: int) -> CommitProfile:
    return CommitProfile(
        commit=commit,
        rank=0,
        duration_s=0.01 * (commit + 1),
        input_rows=commit,
        output_rows=commit,
        neu=False,
        ops=[(1, "groupby", "groupby", 0.005, commit, 0, False)],
    )


@pytest.mark.telemetry
def test_flight_recorder_ring_is_bounded_and_dump_has_summary(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER_COMMITS", "4")
    rec = FlightRecorder()
    for c in range(10):
        rec.record_commit(_profile_for(c))
    rec.record_event("fence", commit=9, epoch=1)
    rec.note_barrier(b"18:3:i0")
    path = rec.dump("crash: TestError", directory=str(tmp_path))
    assert path is not None and os.path.exists(path)
    payload = json.loads(open(path).read())
    profiles = payload["profiles"]
    assert len(profiles) == 4, "ring must hold only the last N profiles"
    assert [p["commit"] for p in profiles] == [6, 7, 8, 9]
    assert payload["summary"]["last_commit"] == 9
    assert payload["summary"]["slowest_operator"]["name"] == "groupby"
    assert payload["summary"]["pending_barrier"] == "18:3:i0"
    assert payload["reason"] == "crash: TestError"
    assert payload["events"][-1]["kind"] == "fence"


@pytest.mark.telemetry
def test_flight_recorder_env_gate(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER", "0")
    rec = FlightRecorder()
    rec.record_commit(_profile_for(1))
    assert rec.dump("crash", directory=str(tmp_path)) is None
    assert not list(tmp_path.iterdir())


@pytest.mark.telemetry
def test_run_crash_dumps_flight_record(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_FLIGHT_RECORDER_DIR", str(tmp_path))
    reset_profile()
    t = pw.debug.table_from_markdown(
        """
        a
        1
        """
    )

    def boom(x: int) -> int:
        raise RuntimeError("operator exploded")

    out = t.select(b=pw.apply_with_type(boom, int, pw.this.a))
    pw.io.subscribe(out, lambda *a, **k: None)
    with pytest.raises(Exception):
        GraphRunner(G._current).run()
    path = tmp_path / "flight-rank-0.json"
    assert path.exists(), "a crashing run must leave its black box behind"
    payload = json.loads(path.read_text())
    assert payload["reason"].startswith("crash:")
    assert payload["rank"] == 0


@pytest.mark.telemetry
def test_noop_telemetry_path_stays_import_free():
    """Tier-1 guard for the deferred-import discipline in engine/telemetry.py:
    with telemetry off, importing pathway_tpu and running a pipeline must not
    pull opentelemetry into sys.modules (its import scans every installed
    distribution's entry points)."""
    code = (
        "import sys\n"
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_markdown('a\\n1\\n2')\n"
        "pw.io.subscribe(t, lambda *a, **k: None)\n"
        "pw.run(monitoring_level=pw.MonitoringLevel.NONE)\n"
        "bad = [m for m in sys.modules if m.startswith('opentelemetry')]\n"
        "assert not bad, f'telemetry-off run imported {bad}'\n"
    )
    env = os.environ.copy()
    env.pop("PATHWAY_TELEMETRY", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
