"""Error traces point at user code (reference internals/trace.py semantics)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.trace import EngineErrorWithTrace
from tests.utils import T


def test_runtime_error_carries_user_frame():
    t = T(
        """
        | a
    1   | 1
    """
    )

    def boom(x):
        raise ValueError("user function exploded")

    bad = t.select(b=pw.apply(boom, t.a))  # <- the user line the trace must cite
    rows = {}
    pw.io.subscribe(bad, lambda key, row, time, is_addition: rows.update({key: row}))
    with pytest.raises(EngineErrorWithTrace) as err:
        GraphRunner(G._current).run()
    message = str(err.value)
    assert "test_trace.py" in message
    assert "user function exploded" in message or "ValueError" in message
