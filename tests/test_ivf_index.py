"""IVF-Flat approximate index (the reference's ANN slot — USearch HNSW,
``usearch_integration.rs:20`` — filled TPU-first: centroid matmul probing +
padded inverted lists in one fused kernel, ``ops/knn_ivf.py``)."""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.ops.knn import BruteForceKnnIndex, IvfKnnIndex

from .utils import T, capture_rows


def _clustered(n, dim, n_clusters, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(n_clusters, dim)).astype(np.float32)
    labels = rng.integers(0, n_clusters, n)
    docs = (centers[labels] + rng.normal(size=(n, dim))).astype(np.float32)
    return centers, docs


def test_ivf_recall_against_brute_force():
    centers, docs = _clustered(4000, 32, 16)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(32, initial_capacity=8192)
    ivf = IvfKnnIndex(32, initial_capacity=8192, n_clusters=16, n_probe=4)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    rng = np.random.default_rng(1)
    queries = (
        centers[rng.integers(0, 16, 50)] + rng.normal(size=(50, 32))
    ).astype(np.float32)
    bf_res = bf.search_many(list(queries), [10] * 50)
    ivf_res = ivf.search_many(list(queries), [10] * 50)
    hits = sum(
        len({k for k, _ in b} & {k for k, _ in v}) for b, v in zip(bf_res, ivf_res)
    )
    assert hits / 500 >= 0.95  # clustered data, 4/16 probes


def test_ivf_full_probe_is_exact():
    _, docs = _clustered(500, 16, 8, seed=2)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(16, initial_capacity=1024)
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    qs = list(docs[:20])
    bf_res = bf.search_many(qs, [5] * 20)
    ivf_res = ivf.search_many(qs, [5] * 20)
    for b, v in zip(bf_res, ivf_res):
        assert {k for k, _ in b} == {k for k, _ in v}  # n_probe == n_clusters


def test_ivf_incremental_adds_and_removals():
    _, docs = _clustered(600, 16, 8, seed=3)
    keys = [f"d{i}" for i in range(len(docs))]
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    ivf.add_many(keys[:300], list(docs[:300]))
    _ = ivf.search_many([docs[0]], [1])  # trains on the first half
    ivf.add_many(keys[300:], list(docs[300:]))  # triggers retrain (size doubled)
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] == "d450"  # post-retrain rows are findable
    ivf.remove("d450")
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] != "d450"


def test_ivf_through_data_index():
    """Factory + DataIndex + engine: the full as-of-now query path."""
    from pathway_tpu.stdlib.indexing import IvfKnnFactory

    from .mocks import fake_embedding

    @pw.udf
    def embed(text: str) -> np.ndarray:
        # md5-based: distinct texts get distinct vectors under ANY hash seed
        # (builtin hash(text) % 8 collides for ~1 in 8 seed choices, making the
        # top-1 result a tie-break coin flip)
        return fake_embedding(text, 8)

    docs = T(
        """
        text
        alpha
        beta
        gamma
        delta
        """
    )
    factory = IvfKnnFactory(dimensions=8, n_clusters=2, n_probe=2, embedder=embed)
    index = factory.build_index(docs.text, docs)
    queries = T(
        """
        q
        alpha
        """
    )
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    assert rows[0]["text"] == ("alpha",)  # exact self-match through the engine


def test_ivf_manifold_recall_and_balanced_buckets():
    """Bench-shaped corpus (base points + noise at 25% of mean-NN distance as
    the DISPLACEMENT NORM — the distribution real embeddings present, unlike
    uniform sphere noise): IVF with a sub-1%-of-clusters probe budget must stay
    >= 0.9 recall@10 vs exact, and the padded bucket width must stay within the
    rebalanced cap (~2x mean occupancy rounded up to pow2), not track the most
    bloated cluster."""
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import DenseKNNStore
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    rng = np.random.default_rng(7)
    dim, n_modes, n_docs, n_q, k = 64, 300, 8000, 64, 10
    base = rng.normal(size=(n_modes, dim)).astype(np.float32)
    base /= np.linalg.norm(base, axis=1, keepdims=True)
    d2 = (
        np.sum(base * base, 1)[:, None]
        + np.sum(base * base, 1)[None, :]
        - 2 * base @ base.T
    )
    np.fill_diagonal(d2, np.inf)
    sigma = 0.25 * float(np.mean(np.sqrt(np.maximum(d2.min(axis=1), 0)))) / np.sqrt(dim)
    docs = base[rng.integers(0, n_modes, n_docs)] + rng.normal(
        scale=sigma, size=(n_docs, dim)
    ).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    docs = docs.astype(np.float32)
    queries = base[rng.integers(0, n_modes, n_q)] + rng.normal(
        scale=sigma, size=(n_q, dim)
    ).astype(np.float32)
    queries = (queries / np.linalg.norm(queries, axis=1, keepdims=True)).astype(np.float32)

    exact = DenseKNNStore(dim, metric="l2sq", initial_capacity=n_docs)
    exact.add_many(list(range(n_docs)), docs)
    _, ei, _ = exact.search_batch(queries, k)
    exact_keys = np.vectorize(lambda s: exact.key_of.get(int(s), -1))(ei)

    ivf = IvfKnnStore(
        dim, metric="l2sq", initial_capacity=n_docs,
        n_clusters=64, n_probe=6, dtype=jnp.bfloat16,
    )
    ivf.add_many(list(range(n_docs)), docs)
    _, ii, _ = ivf.search_batch(queries, k)
    ivf_keys = np.vectorize(lambda s: ivf.key_of.get(int(s), -1))(ii)
    recall = np.mean(
        [len(set(ivf_keys[r]) & set(exact_keys[r])) / k for r in range(n_q)]
    )
    assert recall >= 0.9, recall
    mean_occ = n_docs // 64
    cap = 8
    while cap < (3 * mean_occ + 1) // 2:
        cap *= 2
    assert int(ivf._buckets.shape[1]) <= 2 * cap, ivf._buckets.shape


def test_bf16_storage_matches_f32_results():
    """bfloat16-resident corpora (the HBM-capacity mode for 10M x 384 on one
    chip) must rank the same neighbors as f32 storage: MXU consumes bf16 with
    f32 accumulation, query norms stay f32."""
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import DenseKNNStore
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    rng = np.random.default_rng(3)
    docs = rng.normal(size=(2000, 48)).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    queries = docs[rng.integers(0, 2000, 32)] + 0.01 * rng.normal(size=(32, 48)).astype(np.float32)

    f32 = DenseKNNStore(48, metric="l2sq", initial_capacity=2048)
    b16 = DenseKNNStore(48, metric="l2sq", initial_capacity=2048, dtype=jnp.bfloat16)
    for store in (f32, b16):
        store.add_many(list(range(2000)), docs)
        store._flush()
    _s1, i1, _ = f32.search_batch(queries.astype(np.float32), 10)
    _s2, i2, _ = b16.search_batch(queries.astype(np.float32), 10)
    overlap = np.mean([len(set(i1[r]) & set(i2[r])) / 10 for r in range(32)])
    assert overlap >= 0.97, overlap  # bf16 quantization may swap distant ties only
    # the nearest neighbor itself must never flip
    assert (i1[:, 0] == i2[:, 0]).mean() >= 0.97

    ivf = IvfKnnStore(
        48, metric="l2sq", initial_capacity=2048, n_clusters=8, n_probe=8,
        dtype=jnp.bfloat16,
    )
    ivf.add_many(list(range(2000)), docs)
    _s3, i3, _ = ivf.search_batch(queries.astype(np.float32), 10)
    # full probe (8/8): bf16 IVF is exact up to the same quantization
    overlap = np.mean([len(set(i1[r]) & set(i3[r])) / 10 for r in range(32)])
    assert overlap >= 0.97, overlap
