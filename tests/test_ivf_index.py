"""IVF-Flat approximate index (the reference's ANN slot — USearch HNSW,
``usearch_integration.rs:20`` — filled TPU-first: centroid matmul probing +
padded inverted lists in one fused kernel, ``ops/knn_ivf.py``)."""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.ops.knn import BruteForceKnnIndex, IvfKnnIndex

from .utils import T, capture_rows


def _clustered(n, dim, n_clusters, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(n_clusters, dim)).astype(np.float32)
    labels = rng.integers(0, n_clusters, n)
    docs = (centers[labels] + rng.normal(size=(n, dim))).astype(np.float32)
    return centers, docs


def test_ivf_recall_against_brute_force():
    centers, docs = _clustered(4000, 32, 16)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(32, initial_capacity=8192)
    ivf = IvfKnnIndex(32, initial_capacity=8192, n_clusters=16, n_probe=4)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    rng = np.random.default_rng(1)
    queries = (
        centers[rng.integers(0, 16, 50)] + rng.normal(size=(50, 32))
    ).astype(np.float32)
    bf_res = bf.search_many(list(queries), [10] * 50)
    ivf_res = ivf.search_many(list(queries), [10] * 50)
    hits = sum(
        len({k for k, _ in b} & {k for k, _ in v}) for b, v in zip(bf_res, ivf_res)
    )
    assert hits / 500 >= 0.95  # clustered data, 4/16 probes


def test_ivf_full_probe_is_exact():
    _, docs = _clustered(500, 16, 8, seed=2)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(16, initial_capacity=1024)
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    qs = list(docs[:20])
    bf_res = bf.search_many(qs, [5] * 20)
    ivf_res = ivf.search_many(qs, [5] * 20)
    for b, v in zip(bf_res, ivf_res):
        assert {k for k, _ in b} == {k for k, _ in v}  # n_probe == n_clusters


def test_ivf_incremental_adds_and_removals():
    _, docs = _clustered(600, 16, 8, seed=3)
    keys = [f"d{i}" for i in range(len(docs))]
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    ivf.add_many(keys[:300], list(docs[:300]))
    _ = ivf.search_many([docs[0]], [1])  # trains on the first half
    ivf.add_many(keys[300:], list(docs[300:]))  # triggers retrain (size doubled)
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] == "d450"  # post-retrain rows are findable
    ivf.remove("d450")
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] != "d450"


def test_ivf_through_data_index():
    """Factory + DataIndex + engine: the full as-of-now query path."""
    from pathway_tpu.stdlib.indexing import IvfKnnFactory

    @pw.udf
    def embed(text: str) -> np.ndarray:
        v = np.zeros(8, dtype=np.float32)
        v[hash(text) % 8] = 1.0
        v[len(text) % 8] += 0.5
        return v

    docs = T(
        """
        text
        alpha
        beta
        gamma
        delta
        """
    )
    factory = IvfKnnFactory(dimensions=8, n_clusters=2, n_probe=2, embedder=embed)
    index = factory.build_index(docs.text, docs)
    queries = T(
        """
        q
        alpha
        """
    )
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    assert rows[0]["text"] == ("alpha",)  # exact self-match through the engine


def test_bf16_storage_matches_f32_results():
    """bfloat16-resident corpora (the HBM-capacity mode for 10M x 384 on one
    chip) must rank the same neighbors as f32 storage: MXU consumes bf16 with
    f32 accumulation, query norms stay f32."""
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import DenseKNNStore
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    rng = np.random.default_rng(3)
    docs = rng.normal(size=(2000, 48)).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    queries = docs[rng.integers(0, 2000, 32)] + 0.01 * rng.normal(size=(32, 48)).astype(np.float32)

    f32 = DenseKNNStore(48, metric="l2sq", initial_capacity=2048)
    b16 = DenseKNNStore(48, metric="l2sq", initial_capacity=2048, dtype=jnp.bfloat16)
    for store in (f32, b16):
        store.add_many(list(range(2000)), docs)
        store._flush()
    _s1, i1, _ = f32.search_batch(queries.astype(np.float32), 10)
    _s2, i2, _ = b16.search_batch(queries.astype(np.float32), 10)
    overlap = np.mean([len(set(i1[r]) & set(i2[r])) / 10 for r in range(32)])
    assert overlap >= 0.97, overlap  # bf16 quantization may swap distant ties only
    # the nearest neighbor itself must never flip
    assert (i1[:, 0] == i2[:, 0]).mean() >= 0.97

    ivf = IvfKnnStore(
        48, metric="l2sq", initial_capacity=2048, n_clusters=8, n_probe=8,
        dtype=jnp.bfloat16,
    )
    ivf.add_many(list(range(2000)), docs)
    _s3, i3, _ = ivf.search_batch(queries.astype(np.float32), 10)
    # full probe (8/8): bf16 IVF is exact up to the same quantization
    overlap = np.mean([len(set(i1[r]) & set(i3[r])) / 10 for r in range(32)])
    assert overlap >= 0.97, overlap
