"""IVF-Flat approximate index (the reference's ANN slot — USearch HNSW,
``usearch_integration.rs:20`` — filled TPU-first: centroid matmul probing +
padded inverted lists in one fused kernel, ``ops/knn_ivf.py``)."""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.ops.knn import BruteForceKnnIndex, IvfKnnIndex

from .utils import T, capture_rows


def _clustered(n, dim, n_clusters, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(n_clusters, dim)).astype(np.float32)
    labels = rng.integers(0, n_clusters, n)
    docs = (centers[labels] + rng.normal(size=(n, dim))).astype(np.float32)
    return centers, docs


def test_ivf_recall_against_brute_force():
    centers, docs = _clustered(4000, 32, 16)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(32, initial_capacity=8192)
    ivf = IvfKnnIndex(32, initial_capacity=8192, n_clusters=16, n_probe=4)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    rng = np.random.default_rng(1)
    queries = (
        centers[rng.integers(0, 16, 50)] + rng.normal(size=(50, 32))
    ).astype(np.float32)
    bf_res = bf.search_many(list(queries), [10] * 50)
    ivf_res = ivf.search_many(list(queries), [10] * 50)
    hits = sum(
        len({k for k, _ in b} & {k for k, _ in v}) for b, v in zip(bf_res, ivf_res)
    )
    assert hits / 500 >= 0.95  # clustered data, 4/16 probes


def test_ivf_full_probe_is_exact():
    _, docs = _clustered(500, 16, 8, seed=2)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(16, initial_capacity=1024)
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    qs = list(docs[:20])
    bf_res = bf.search_many(qs, [5] * 20)
    ivf_res = ivf.search_many(qs, [5] * 20)
    for b, v in zip(bf_res, ivf_res):
        assert {k for k, _ in b} == {k for k, _ in v}  # n_probe == n_clusters


def test_ivf_incremental_adds_and_removals():
    _, docs = _clustered(600, 16, 8, seed=3)
    keys = [f"d{i}" for i in range(len(docs))]
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    ivf.add_many(keys[:300], list(docs[:300]))
    _ = ivf.search_many([docs[0]], [1])  # trains on the first half
    ivf.add_many(keys[300:], list(docs[300:]))  # triggers retrain (size doubled)
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] == "d450"  # post-retrain rows are findable
    ivf.remove("d450")
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] != "d450"


def test_ivf_through_data_index():
    """Factory + DataIndex + engine: the full as-of-now query path."""
    from pathway_tpu.stdlib.indexing import IvfKnnFactory

    @pw.udf
    def embed(text: str) -> np.ndarray:
        v = np.zeros(8, dtype=np.float32)
        v[hash(text) % 8] = 1.0
        v[len(text) % 8] += 0.5
        return v

    docs = T(
        """
        text
        alpha
        beta
        gamma
        delta
        """
    )
    factory = IvfKnnFactory(dimensions=8, n_clusters=2, n_probe=2, embedder=embed)
    index = factory.build_index(docs.text, docs)
    queries = T(
        """
        q
        alpha
        """
    )
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    assert rows[0]["text"] == ("alpha",)  # exact self-match through the engine
