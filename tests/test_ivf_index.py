"""IVF-Flat approximate index (the reference's ANN slot — USearch HNSW,
``usearch_integration.rs:20`` — filled TPU-first: centroid matmul probing +
padded inverted lists in one fused kernel, ``ops/knn_ivf.py``)."""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.ops.knn import BruteForceKnnIndex, IvfKnnIndex

from .utils import T, capture_rows


def _clustered(n, dim, n_clusters, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(n_clusters, dim)).astype(np.float32)
    labels = rng.integers(0, n_clusters, n)
    docs = (centers[labels] + rng.normal(size=(n, dim))).astype(np.float32)
    return centers, docs


def test_ivf_recall_against_brute_force():
    centers, docs = _clustered(4000, 32, 16)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(32, initial_capacity=8192)
    ivf = IvfKnnIndex(32, initial_capacity=8192, n_clusters=16, n_probe=4)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    rng = np.random.default_rng(1)
    queries = (
        centers[rng.integers(0, 16, 50)] + rng.normal(size=(50, 32))
    ).astype(np.float32)
    bf_res = bf.search_many(list(queries), [10] * 50)
    ivf_res = ivf.search_many(list(queries), [10] * 50)
    hits = sum(
        len({k for k, _ in b} & {k for k, _ in v}) for b, v in zip(bf_res, ivf_res)
    )
    assert hits / 500 >= 0.95  # clustered data, 4/16 probes


def test_ivf_full_probe_is_exact():
    _, docs = _clustered(500, 16, 8, seed=2)
    keys = [f"d{i}" for i in range(len(docs))]
    bf = BruteForceKnnIndex(16, initial_capacity=1024)
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    bf.add_many(keys, list(docs))
    ivf.add_many(keys, list(docs))
    qs = list(docs[:20])
    bf_res = bf.search_many(qs, [5] * 20)
    ivf_res = ivf.search_many(qs, [5] * 20)
    for b, v in zip(bf_res, ivf_res):
        assert {k for k, _ in b} == {k for k, _ in v}  # n_probe == n_clusters


def test_ivf_incremental_adds_and_removals():
    _, docs = _clustered(600, 16, 8, seed=3)
    keys = [f"d{i}" for i in range(len(docs))]
    ivf = IvfKnnIndex(16, initial_capacity=1024, n_clusters=8, n_probe=8)
    ivf.add_many(keys[:300], list(docs[:300]))
    _ = ivf.search_many([docs[0]], [1])  # trains on the first half
    ivf.add_many(keys[300:], list(docs[300:]))  # triggers retrain (size doubled)
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] == "d450"  # post-retrain rows are findable
    ivf.remove("d450")
    res = ivf.search_many([docs[450]], [1])
    assert res[0][0][0] != "d450"


def test_ivf_through_data_index():
    """Factory + DataIndex + engine: the full as-of-now query path."""
    from pathway_tpu.stdlib.indexing import IvfKnnFactory

    from .mocks import fake_embedding

    @pw.udf
    def embed(text: str) -> np.ndarray:
        # md5-based: distinct texts get distinct vectors under ANY hash seed
        # (builtin hash(text) % 8 collides for ~1 in 8 seed choices, making the
        # top-1 result a tie-break coin flip)
        return fake_embedding(text, 8)

    docs = T(
        """
        text
        alpha
        beta
        gamma
        delta
        """
    )
    factory = IvfKnnFactory(dimensions=8, n_clusters=2, n_probe=2, embedder=embed)
    index = factory.build_index(docs.text, docs)
    queries = T(
        """
        q
        alpha
        """
    )
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    assert rows[0]["text"] == ("alpha",)  # exact self-match through the engine


def test_ivf_manifold_recall_and_balanced_buckets():
    """Bench-shaped corpus (base points + noise at 25% of mean-NN distance as
    the DISPLACEMENT NORM — the distribution real embeddings present, unlike
    uniform sphere noise): IVF with a sub-1%-of-clusters probe budget must stay
    >= 0.9 recall@10 vs exact, and the padded bucket width must stay within the
    rebalanced cap (~2x mean occupancy rounded up to pow2), not track the most
    bloated cluster."""
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import DenseKNNStore
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    rng = np.random.default_rng(7)
    dim, n_modes, n_docs, n_q, k = 64, 300, 8000, 64, 10
    base = rng.normal(size=(n_modes, dim)).astype(np.float32)
    base /= np.linalg.norm(base, axis=1, keepdims=True)
    d2 = (
        np.sum(base * base, 1)[:, None]
        + np.sum(base * base, 1)[None, :]
        - 2 * base @ base.T
    )
    np.fill_diagonal(d2, np.inf)
    sigma = 0.25 * float(np.mean(np.sqrt(np.maximum(d2.min(axis=1), 0)))) / np.sqrt(dim)
    docs = base[rng.integers(0, n_modes, n_docs)] + rng.normal(
        scale=sigma, size=(n_docs, dim)
    ).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    docs = docs.astype(np.float32)
    queries = base[rng.integers(0, n_modes, n_q)] + rng.normal(
        scale=sigma, size=(n_q, dim)
    ).astype(np.float32)
    queries = (queries / np.linalg.norm(queries, axis=1, keepdims=True)).astype(np.float32)

    exact = DenseKNNStore(dim, metric="l2sq", initial_capacity=n_docs)
    exact.add_many(list(range(n_docs)), docs)
    _, ei, _ = exact.search_batch(queries, k)
    exact_keys = np.vectorize(lambda s: exact.key_of.get(int(s), -1))(ei)

    ivf = IvfKnnStore(
        dim, metric="l2sq", initial_capacity=n_docs,
        n_clusters=64, n_probe=6, dtype=jnp.bfloat16,
    )
    ivf.add_many(list(range(n_docs)), docs)
    _, ii, _ = ivf.search_batch(queries, k)
    ivf_keys = np.vectorize(lambda s: ivf.key_of.get(int(s), -1))(ii)
    recall = np.mean(
        [len(set(ivf_keys[r]) & set(exact_keys[r])) / k for r in range(n_q)]
    )
    assert recall >= 0.9, recall
    mean_occ = n_docs // 64
    cap = 8
    while cap < (3 * mean_occ + 1) // 2:
        cap *= 2
    # rebalanced CSR: the largest inverted list must stay within the spill cap,
    # not track the most bloated k-means cluster
    occ = int(np.max(np.diff(ivf._csr_offsets)))
    assert occ <= 2 * cap, occ


def test_bf16_storage_matches_f32_results():
    """bfloat16-resident corpora (the HBM-capacity mode for 10M x 384 on one
    chip) must rank the same neighbors as f32 storage: MXU consumes bf16 with
    f32 accumulation, query norms stay f32."""
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import DenseKNNStore
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    rng = np.random.default_rng(3)
    docs = rng.normal(size=(2000, 48)).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    queries = docs[rng.integers(0, 2000, 32)] + 0.01 * rng.normal(size=(32, 48)).astype(np.float32)

    f32 = DenseKNNStore(48, metric="l2sq", initial_capacity=2048)
    b16 = DenseKNNStore(48, metric="l2sq", initial_capacity=2048, dtype=jnp.bfloat16)
    for store in (f32, b16):
        store.add_many(list(range(2000)), docs)
        store._flush()
    _s1, i1, _ = f32.search_batch(queries.astype(np.float32), 10)
    _s2, i2, _ = b16.search_batch(queries.astype(np.float32), 10)
    overlap = np.mean([len(set(i1[r]) & set(i2[r])) / 10 for r in range(32)])
    assert overlap >= 0.97, overlap  # bf16 quantization may swap distant ties only
    # the nearest neighbor itself must never flip
    assert (i1[:, 0] == i2[:, 0]).mean() >= 0.97

    ivf = IvfKnnStore(
        48, metric="l2sq", initial_capacity=2048, n_clusters=8, n_probe=8,
        dtype=jnp.bfloat16,
    )
    ivf.add_many(list(range(2000)), docs)
    _s3, i3, _ = ivf.search_batch(queries.astype(np.float32), 10)
    # full probe (8/8): bf16 IVF is exact up to the same quantization
    overlap = np.mean([len(set(i1[r]) & set(i3[r])) / 10 for r in range(32)])
    assert overlap >= 0.97, overlap


# -- fused kernel paths (PR 1: CSR + paged layout, Pallas/XLA contract) --------


def _int_store(n=1500, dim=32, n_clusters=8, n_probe=3, seed=5):
    """Integer-valued vectors: every dot product is exact in f32 regardless of
    accumulation order, so the Pallas kernel and the XLA composite must agree
    BITWISE — parity assertions need no tolerance."""
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    rng = np.random.default_rng(seed)
    docs = rng.integers(-8, 9, size=(n, dim)).astype(np.float32)
    queries = rng.integers(-8, 9, size=(24, dim)).astype(np.float32)
    ivf = IvfKnnStore(
        dim, metric="l2sq", initial_capacity=2 * n,
        n_clusters=n_clusters, n_probe=n_probe,
    )
    ivf.add_many(list(range(n)), docs)
    ivf.search_batch(queries[:1], 1)  # train + build index
    return ivf, queries


def test_ivf_device_xla_matches_numpy_path():
    """The XLA composite (the device production path) and the CPU BLAS path
    walk the same CSR and must return the same neighbors and scores
    (continuous float corpus: distinct distances, so the comparison is strict
    up to float accumulation order)."""
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    _c, docs = _clustered(1500, 32, 8, seed=5)
    ivf = IvfKnnStore(32, metric="l2sq", initial_capacity=4096, n_clusters=8, n_probe=3)
    ivf.add_many(list(range(len(docs))), docs)
    rng = np.random.default_rng(6)
    queries = docs[rng.integers(0, len(docs), 24)] + 0.1 * rng.normal(
        size=(24, 32)
    ).astype(np.float32)
    queries = queries.astype(np.float32)
    ivf.search_batch(queries[:1], 1)  # train + build index
    ns, ni = ivf._search_numpy(queries, 10)
    ds, di = ivf._search_device(queries, 10, impl="xla")
    np.testing.assert_allclose(ds, ns, rtol=1e-4, atol=1e-3)
    overlap = np.mean(
        [
            len({int(x) for x in di[r] if x >= 0} & {int(x) for x in ni[r] if x >= 0}) / 10
            for r in range(len(queries))
        ]
    )
    assert overlap >= 0.99, overlap  # only float-noise boundary ties may differ


def test_pallas_kernel_parity_with_xla_composite():
    """Acceptance: the pallas_call kernel must prove parity with the XLA
    composite fallback on any backend (interpret mode here). Integer vectors
    make parity exact — identical slots AND identical scores."""
    for metric in ("l2sq", "cos", "ip"):
        from pathway_tpu.ops.knn_ivf import IvfKnnStore

        ivf, queries = _int_store(seed=7)
        ivf.metric = metric
        xs, xi = ivf._search_device(queries, 10, impl="xla")
        ps, pi = ivf._search_device(queries, 10, impl="pallas_interpret")
        np.testing.assert_allclose(ps, xs, rtol=1e-6, atol=1e-6)
        assert (pi == xi).all(), metric


def test_jit_cache_bounded_over_batch_sizes():
    """Acceptance: ragged query batch sizes across a run must trigger a bounded
    (<= pow2-bucket-count) number of kernel compilations."""
    from pathway_tpu.ops.knn import next_pow2
    from pathway_tpu.ops.knn_ivf import _ivf_query_fused

    ivf, queries = _int_store()
    rng = np.random.default_rng(0)
    base = int(_ivf_query_fused._cache_size())
    sizes = list(range(1, 25)) + [1, 13, 24, 5]
    for nq in sizes:
        q = rng.integers(-8, 9, size=(nq, 32)).astype(np.float32)
        ivf._search_device(q, 5, impl="xla")
    buckets = {next_pow2(max(8, nq)) for nq in sizes}
    grown = int(_ivf_query_fused._cache_size()) - base
    assert grown <= len(buckets), (grown, buckets)
    assert len(ivf.search_shape_buckets) <= len(buckets) + 1  # +1: the build call


def test_ivf_shape_buckets_tracked_on_cpu_path():
    """search_batch records pow2 (q, k) buckets on every path — the bench's
    recompile-observability counter."""
    ivf, queries = _int_store()
    ivf.search_shape_buckets.clear()
    for nq in (1, 2, 3, 5, 7, 8):
        ivf.search_batch(queries[:nq], 3)
    assert ivf.search_shape_buckets == {(8, 4)}


def test_ivf_csr_pages_consistent():
    """Every live slot appears exactly once in the CSR, page geometry is pow2
    padded with an all-pad sentinel page, and page contents mirror the CSR."""
    from pathway_tpu.ops.knn_ivf import PAGE

    ivf, _q = _int_store()
    ivf._ensure_index()
    live = sorted(ivf.slot_of.values())
    assert sorted(ivf._csr_rows.tolist()) == live
    offsets = ivf._csr_offsets
    n_pages_total = len(ivf._page_rows) // PAGE
    assert n_pages_total & (n_pages_total - 1) == 0  # pow2
    assert (ivf._page_rows[-PAGE:] == -1).all()  # sentinel page all-pad
    packed_live = ivf._page_rows[ivf._page_rows >= 0]
    assert sorted(packed_live.tolist()) == live
    for c in range(ivf.n_clusters):
        members = set(ivf._csr_rows[offsets[c] : offsets[c + 1]].tolist())
        start = int(ivf._first_page[c]) * PAGE
        span = int(ivf._n_pages[c]) * PAGE
        paged = ivf._page_rows[start : start + span]
        assert {int(x) for x in paged if x >= 0} == members


def test_sharded_ivf_matches_single_store():
    """Mesh-sharded IVF: per-shard fused search + top-k merge must return the
    same neighbors as one unsharded store at full probe."""
    from pathway_tpu.ops.knn_ivf import IvfKnnStore
    from pathway_tpu.parallel import ShardedIvfKnnStore, make_mesh

    mesh = make_mesh(8)  # data axis = 2 shards on the virtual CPU mesh
    rng = np.random.default_rng(9)
    dim, n, k = 16, 600, 5
    docs = rng.integers(-8, 9, size=(n, dim)).astype(np.float32)
    queries = rng.integers(-8, 9, size=(12, dim)).astype(np.float32)
    single = IvfKnnStore(dim, initial_capacity=1024, n_clusters=4, n_probe=4)
    sharded = ShardedIvfKnnStore(
        mesh, dim, initial_capacity=1024, n_clusters=4, n_probe=4
    )
    keys = [f"d{i}" for i in range(n)]
    single.add_many(keys, docs)
    sharded.add_many(keys, docs)
    ss, si, sv = single.search_batch(queries, k)
    hs, hi, hv = sharded.search_batch(queries, k)
    assert hv.all()
    np.testing.assert_allclose(np.sort(hs, axis=1), np.sort(ss, axis=1), atol=1e-4)
    for r in range(len(queries)):
        a = {single.key_of[int(x)] for x in si[r] if x >= 0}
        b = {sharded.key_of[int(x)] for x in hi[r] if x >= 0}
        assert a == b
    # removals route to the owning shard
    sharded.remove("d0")
    assert len(sharded) == n - 1
    _s, i2, _v = sharded.search_batch(docs[:1], 1)
    assert sharded.key_of.get(int(i2[0, 0])) != "d0"


def test_vector_store_server_accepts_ivf_factory():
    """index_factory='ivf' threads the IVF retriever end-to-end into the
    DocumentStore (constructor-level wiring; the engine query path is covered
    by test_ivf_through_data_index)."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.stdlib.indexing.nearest_neighbors import IvfKnnFactory
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    from .mocks import fake_embedding

    @pw.udf
    def embed(text: str) -> np.ndarray:
        return fake_embedding(text, 8)

    pg.G.clear()
    docs = T(
        """
        data | _metadata
        alpha | {}
        """
    )
    server = VectorStoreServer(docs, embedder=embed, index_factory="ivf")
    assert isinstance(server.store.retriever_factory, IvfKnnFactory)
    pg.G.clear()
