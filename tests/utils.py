"""Test fixtures mirroring the reference's ``python/pathway/tests/utils.py``:
``T`` (markdown tables), ``assert_table_equality[_wo_index]``, update-stream checks."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.debug import _capture_table, _capture_update_stream, table_from_markdown

T = table_from_markdown


def _rows_of(table: pw.Table) -> dict:
    captured = _capture_table(table)
    return {
        kb: tuple(_norm(row[c]) for c in table.column_names())
        for kb, row in captured.items()
    }


def _norm(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return ("nd", v.dtype.kind, v.shape, v.tobytes())
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, tuple):
        return tuple(_norm(x) for x in v)
    return v


def assert_table_equality(a: pw.Table, b: pw.Table) -> None:
    """Same keys, same column values (column names may differ positionally)."""
    rows_a = _rows_of(a)
    rows_b = _rows_of(b)
    assert rows_a == rows_b, f"tables differ:\n  A={rows_a}\n  B={rows_b}"


def assert_table_equality_wo_index(a: pw.Table, b: pw.Table) -> None:
    """Same multiset of rows, ignoring keys."""
    rows_a = sorted(_rows_of(a).values(), key=repr)
    rows_b = sorted(_rows_of(b).values(), key=repr)
    assert rows_a == rows_b, f"tables differ (wo index):\n  A={rows_a}\n  B={rows_b}"


assert_table_equality_wo_types = assert_table_equality
assert_table_equality_wo_index_types = assert_table_equality_wo_index


def capture_rows(table: pw.Table) -> list[dict]:
    captured = _capture_table(table)
    return [
        {c: row[c] for c in table.column_names()} for row in captured.values()
    ]


def capture_update_stream(table: pw.Table) -> list[dict]:
    return _capture_update_stream(table)


# -- update-stream fixtures (reference tests/utils.py:119-214, 544-556) -----------


@dataclass(order=True)
class DiffEntry:
    """One expected update-stream event for a key: events for a fixed key must
    arrive ordered by (order, insertion), matching the reference's
    ``CheckKeyEntriesInStreamCallback`` contract."""

    key: Any
    order: int
    insertion: bool
    row: dict

    @staticmethod
    def create(
        pk_columns: dict,
        order: int,
        insertion: bool,
        row: dict,
    ) -> "DiffEntry":
        from pathway_tpu.internals.keys import pointer_from

        key = pointer_from(*pk_columns.values())
        return DiffEntry(key, order, insertion, row)

    def final_cleanup_entry(self) -> "DiffEntry":
        return DiffEntry(self.key, self.order + 1, False, self.row)


def assert_key_entries_in_stream_consistent(expected: list, table: pw.Table) -> None:
    """Run the graph and verify each key's update events arrive in the expected
    per-key order with the expected rows (reference ``assert_key_entries_in_
    stream_consistent``). Events for keys not listed are failures."""
    import collections

    state: dict = collections.defaultdict(collections.deque)
    for entry in sorted(expected):
        state[entry.key].append(entry)
    problems: list[str] = []

    def on_change(key, row, time, is_addition):
        queue = state.get(key)
        if not queue:
            problems.append(f"unexpected event for key {key}: {row} add={is_addition}")
            return
        head = queue.popleft()
        got = {k: _norm(v) for k, v in row.items()}
        want = {k: _norm(v) for k, v in head.row.items()}
        if head.insertion != is_addition or got != want:
            problems.append(
                f"key {key}: expected add={head.insertion} row={want}, "
                f"got add={is_addition} row={got}"
            )

    pw.io.subscribe(table, on_change)
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.parse_graph import G

    GraphRunner(G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert not problems, "\n".join(problems)
    leftovers = {k: list(v) for k, v in state.items() if v}
    assert not leftovers, f"expected events never arrived: {leftovers}"


def _stream_groups(table: pw.Table) -> list:
    """Captured update stream as per-commit groups of (row values, diff), with times
    normalized to their dense rank (engine commit times are implementation detail;
    the GROUPING and ordering are the contract — reference
    assert_stream_split_into_groups)."""
    events = _capture_update_stream(table)
    names = [c for c in table.column_names()]
    times = sorted({e["__time__"] for e in events})
    rank = {t: i for i, t in enumerate(times)}
    groups: dict[int, list] = {}
    for e in events:
        groups.setdefault(rank[e["__time__"]], []).append(
            (tuple(_norm(e[c]) for c in names), e["__diff__"])
        )
    return [sorted(groups[i], key=repr) for i in sorted(groups)]


def assert_stream_equality(a: pw.Table, b: pw.Table) -> None:
    """Same update stream: identical per-commit groups of (row, diff), in the same
    commit order, with times compared by rank (reference assert_stream_equality
    up to engine-time renumbering)."""
    ga, gb = _stream_groups(a), _stream_groups(b)
    assert ga == gb, f"update streams differ:\n  A={ga}\n  B={gb}"


assert_stream_equality_wo_index = assert_stream_equality
