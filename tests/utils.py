"""Test fixtures mirroring the reference's ``python/pathway/tests/utils.py``:
``T`` (markdown tables), ``assert_table_equality[_wo_index]``, update-stream checks."""

from __future__ import annotations

from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.debug import _capture_table, _capture_update_stream, table_from_markdown

T = table_from_markdown


def _rows_of(table: pw.Table) -> dict:
    captured = _capture_table(table)
    return {
        kb: tuple(_norm(row[c]) for c in table.column_names())
        for kb, row in captured.items()
    }


def _norm(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return ("nd", v.dtype.kind, v.shape, v.tobytes())
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, tuple):
        return tuple(_norm(x) for x in v)
    return v


def assert_table_equality(a: pw.Table, b: pw.Table) -> None:
    """Same keys, same column values (column names may differ positionally)."""
    rows_a = _rows_of(a)
    rows_b = _rows_of(b)
    assert rows_a == rows_b, f"tables differ:\n  A={rows_a}\n  B={rows_b}"


def assert_table_equality_wo_index(a: pw.Table, b: pw.Table) -> None:
    """Same multiset of rows, ignoring keys."""
    rows_a = sorted(_rows_of(a).values(), key=repr)
    rows_b = sorted(_rows_of(b).values(), key=repr)
    assert rows_a == rows_b, f"tables differ (wo index):\n  A={rows_a}\n  B={rows_b}"


assert_table_equality_wo_types = assert_table_equality
assert_table_equality_wo_index_types = assert_table_equality_wo_index


def capture_rows(table: pw.Table) -> list[dict]:
    captured = _capture_table(table)
    return [
        {c: row[c] for c in table.column_names()} for row in captured.values()
    ]


def capture_update_stream(table: pw.Table) -> list[dict]:
    return _capture_update_stream(table)
