"""Test fixtures mirroring the reference's ``python/pathway/tests/utils.py``:
``T`` (markdown tables), ``assert_table_equality[_wo_index]``, update-stream checks."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.debug import _capture_table, _capture_update_stream, table_from_markdown

T = table_from_markdown


def _rows_of(table: pw.Table) -> dict:
    captured = _capture_table(table)
    return {
        kb: tuple(_norm(row[c]) for c in table.column_names())
        for kb, row in captured.items()
    }


def _norm(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return ("nd", v.dtype.kind, v.shape, v.tobytes())
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, tuple):
        return tuple(_norm(x) for x in v)
    return v


def assert_table_equality(a: pw.Table, b: pw.Table) -> None:
    """Same keys, same column values (column names may differ positionally)."""
    rows_a = _rows_of(a)
    rows_b = _rows_of(b)
    assert rows_a == rows_b, f"tables differ:\n  A={rows_a}\n  B={rows_b}"


def assert_table_equality_wo_index(a: pw.Table, b: pw.Table) -> None:
    """Same multiset of rows, ignoring keys."""
    rows_a = sorted(_rows_of(a).values(), key=repr)
    rows_b = sorted(_rows_of(b).values(), key=repr)
    assert rows_a == rows_b, f"tables differ (wo index):\n  A={rows_a}\n  B={rows_b}"


assert_table_equality_wo_types = assert_table_equality
assert_table_equality_wo_index_types = assert_table_equality_wo_index


def capture_rows(table: pw.Table) -> list[dict]:
    captured = _capture_table(table)
    return [
        {c: row[c] for c in table.column_names()} for row in captured.values()
    ]


def capture_update_stream(table: pw.Table) -> list[dict]:
    return _capture_update_stream(table)


# -- update-stream fixtures (reference tests/utils.py:119-214, 544-556) -----------


@dataclass(order=True)
class DiffEntry:
    """One expected update-stream event for a key: events for a fixed key must
    arrive ordered by (order, insertion), matching the reference's
    ``CheckKeyEntriesInStreamCallback`` contract."""

    key: Any
    order: int
    insertion: bool
    row: dict

    @staticmethod
    def create(
        pk_columns: dict,
        order: int,
        insertion: bool,
        row: dict,
    ) -> "DiffEntry":
        from pathway_tpu.internals.keys import pointer_from

        key = pointer_from(*pk_columns.values())
        return DiffEntry(key, order, insertion, row)

    def final_cleanup_entry(self) -> "DiffEntry":
        return DiffEntry(self.key, self.order + 1, False, self.row)


def assert_key_entries_in_stream_consistent(expected: list, table: pw.Table) -> None:
    """Run the graph and verify each key's update events arrive in the expected
    per-key order with the expected rows (reference ``assert_key_entries_in_
    stream_consistent``). Events for keys not listed are failures."""
    import collections

    state: dict = collections.defaultdict(collections.deque)
    for entry in sorted(expected):
        state[entry.key].append(entry)
    problems: list[str] = []

    def on_change(key, row, time, is_addition):
        queue = state.get(key)
        if not queue:
            problems.append(f"unexpected event for key {key}: {row} add={is_addition}")
            return
        head = queue.popleft()
        got = {k: _norm(v) for k, v in row.items()}
        want = {k: _norm(v) for k, v in head.row.items()}
        if head.insertion != is_addition or got != want:
            problems.append(
                f"key {key}: expected add={head.insertion} row={want}, "
                f"got add={is_addition} row={got}"
            )

    pw.io.subscribe(table, on_change)
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.parse_graph import G

    GraphRunner(G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    assert not problems, "\n".join(problems)
    leftovers = {k: list(v) for k, v in state.items() if v}
    assert not leftovers, f"expected events never arrived: {leftovers}"


def _stream_groups(table: pw.Table) -> list:
    """Captured update stream as per-commit groups of (row values, diff), with times
    normalized to their dense rank (engine commit times are implementation detail;
    the GROUPING and ordering are the contract — reference
    assert_stream_split_into_groups)."""
    events = _capture_update_stream(table)
    names = [c for c in table.column_names()]
    times = sorted({e["__time__"] for e in events})
    rank = {t: i for i, t in enumerate(times)}
    groups: dict[int, list] = {}
    for e in events:
        groups.setdefault(rank[e["__time__"]], []).append(
            (tuple(_norm(e[c]) for c in names), e["__diff__"])
        )
    return [sorted(groups[i], key=repr) for i in sorted(groups)]


def assert_stream_equality(a: pw.Table, b: pw.Table) -> None:
    """Same update stream: identical per-commit groups of (row, diff), in the same
    commit order, with times compared by rank (reference assert_stream_equality
    up to engine-time renumbering)."""
    ga, gb = _stream_groups(a), _stream_groups(b)
    assert ga == gb, f"update streams differ:\n  A={ga}\n  B={gb}"


assert_stream_equality_wo_index = assert_stream_equality


# -- strict OpenMetrics line-grammar checker ----------------------------------
# Guards the /metrics exporter: a malformed exposition breaks Prometheus
# scrapes SILENTLY (the scraper drops the target), so regressions must fail
# tier-1 instead. Checks: metadata-before-samples ordering, one contiguous
# block per family, counter samples named <family>_total, histogram bucket
# monotonicity + le ordering + +Inf == _count, and the # EOF terminator.

import re as _re

_METRIC_NAME_RE = _re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
# the label body is scanned quote-aware: values may contain ',' and '}'
# (operator names are user-settable and exported verbatim modulo escaping)
_SAMPLE_RE = _re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?P<labels>(?:[^"}]|"(?:[^"\\]|\\.)*")*)\})?'
    r" (?P<value>[^ ]+)(?: (?P<ts>[0-9.+-eE]+))?$"
)
_LABEL_PAIR_RE = _re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _om_parse_labels(raw: str) -> dict:
    """Parse a label body positionally (NOT by splitting on commas — a comma
    inside a quoted label value is legal)."""
    labels: dict = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_PAIR_RE.match(raw, pos)
        assert m, f"malformed label body at …{raw[pos:]!r}"
        labels[m.group(1)] = m.group(2)
        pos = m.end()
        if pos < len(raw):
            assert raw[pos] == ",", f"expected ',' between labels at …{raw[pos:]!r}"
            pos += 1
    return labels


def validate_openmetrics(text: str) -> dict:
    """Assert ``text`` is a valid OpenMetrics exposition; returns
    {family: {"type": ..., "samples": [(name, labels, value)]}}."""
    lines = text.split("\n")
    assert lines[-1] == "", "exposition must end with a newline"
    lines = lines[:-1]
    assert lines, "empty exposition"
    assert lines[-1] == "# EOF", f"missing # EOF terminator (last: {lines[-1]!r})"
    families: dict = {}
    family_order: list = []
    current_family: "str | None" = None
    for lineno, line in enumerate(lines[:-1], 1):
        assert line == line.strip(), f"line {lineno}: stray whitespace {line!r}"
        assert "# EOF" != line, f"line {lineno}: # EOF before the end"
        if line.startswith("# "):
            parts = line.split(" ", 3)
            assert len(parts) >= 3 and parts[1] in ("HELP", "TYPE"), (
                f"line {lineno}: malformed metadata {line!r}"
            )
            kind, name = parts[1], parts[2]
            assert _METRIC_NAME_RE.fullmatch(name), (
                f"line {lineno}: bad metric family name {name!r}"
            )
            fam = families.setdefault(
                name, {"type": None, "help": None, "samples": []}
            )
            assert not fam["samples"], (
                f"line {lineno}: {kind} for {name} AFTER its samples"
            )
            if kind == "TYPE":
                assert fam["type"] is None, f"line {lineno}: duplicate TYPE for {name}"
                assert len(parts) == 4 and parts[3] in (
                    "counter", "gauge", "histogram", "summary", "unknown", "info",
                ), f"line {lineno}: bad TYPE {line!r}"
                fam["type"] = parts[3]
            else:
                assert fam["help"] is None, f"line {lineno}: duplicate HELP for {name}"
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"line {lineno}: malformed sample {line!r}"
        name, raw_labels, raw_value = m.group("name"), m.group("labels"), m.group("value")
        # resolve which declared family this sample belongs to
        fam_name = None
        for suffix in ("_total", "_bucket", "_count", "_sum", ""):
            base = name[: -len(suffix)] if suffix and name.endswith(suffix) else (
                name if not suffix else None
            )
            if base and base in families:
                fam_name = base
                break
        assert fam_name, f"line {lineno}: sample {name!r} has no TYPE/HELP metadata"
        fam = families[fam_name]
        assert fam["type"] is not None, f"line {lineno}: {fam_name} samples precede TYPE"
        if fam["type"] == "counter":
            assert name == fam_name + "_total", (
                f"line {lineno}: counter sample must be {fam_name}_total, got {name!r}"
            )
        if fam["type"] == "histogram":
            assert name in (
                fam_name + "_bucket", fam_name + "_count", fam_name + "_sum",
            ), f"line {lineno}: bad histogram sample name {name!r}"
        labels = _om_parse_labels(raw_labels or "")
        try:
            value = float(raw_value.replace("+Inf", "inf"))
        except ValueError as exc:
            raise AssertionError(f"line {lineno}: bad value {raw_value!r}") from exc
        # one contiguous block per family
        if fam_name != current_family:
            assert fam_name not in family_order, (
                f"line {lineno}: family {fam_name} samples are not contiguous"
            )
            family_order.append(fam_name)
            current_family = fam_name
        fam["samples"].append((name, labels, value))
    for fam_name, fam in families.items():
        if fam["type"] != "histogram" or not fam["samples"]:
            continue
        buckets = [(lb, v) for (n, lb, v) in fam["samples"] if n.endswith("_bucket")]
        counts = {n: v for (n, lb, v) in fam["samples"] if not n.endswith("_bucket")}
        assert buckets, f"{fam_name}: histogram without buckets"
        prev_le = float("-inf")
        prev_count = 0.0
        for lb, v in buckets:
            assert "le" in lb, f"{fam_name}: bucket without le label"
            le = float(lb["le"].replace("+Inf", "inf"))
            assert le > prev_le, f"{fam_name}: le bounds not ascending at {lb['le']}"
            assert v >= prev_count, (
                f"{fam_name}: bucket counts not monotone at le={lb['le']}"
            )
            prev_le, prev_count = le, v
        assert prev_le == float("inf"), f"{fam_name}: missing +Inf bucket"
        assert counts.get(fam_name + "_count") == prev_count, (
            f"{fam_name}: _count != +Inf bucket"
        )
        assert fam_name + "_sum" in counts, f"{fam_name}: missing _sum"
    return families
