"""LLM xpack tests (modeled on reference ``xpacks/llm/tests``): hermetic via mocks."""

import json

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.json import Json

from .mocks import FakeChat, FakeEmbedder, fake_embedding
from .utils import T, capture_rows


def _docs_table():
    rows = [
        (b"the cat sits on the mat", Json({"path": "/data/cats.txt", "modified_at": 10, "seen_at": 11})),
        (b"dogs chase the ball in the park", Json({"path": "/data/dogs.txt", "modified_at": 20, "seen_at": 21})),
        (b"quantum computing uses qubits", Json({"path": "/data/qc.txt", "modified_at": 30, "seen_at": 31})),
    ]
    schema = pw.schema_builder({"data": bytes, "_metadata": pw.Json})
    return pw.debug.table_from_rows(schema, rows)


def _store(docs=None):
    from pathway_tpu.stdlib.indexing.nearest_neighbors import BruteForceKnnFactory, BruteForceKnnMetricKind
    from pathway_tpu.xpacks.llm.document_store import DocumentStore

    embedder = FakeEmbedder(dim=16)
    factory = BruteForceKnnFactory(
        dimensions=16, metric=BruteForceKnnMetricKind.COS, embedder=embedder
    )
    return DocumentStore(docs if docs is not None else _docs_table(), retriever_factory=factory)


def test_document_store_retrieve():
    store = _store()
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"query": str, "k": int, "metadata_filter": str, "filepath_globpattern": str}),
        [("the cat sits on the mat", 1, None, None)],
    )
    result = store.retrieve_query(queries)
    rows = capture_rows(result)
    assert len(rows) == 1
    docs = rows[0]["result"].value
    assert len(docs) == 1
    assert docs[0]["text"] == "the cat sits on the mat"
    assert docs[0]["metadata"]["path"] == "/data/cats.txt"
    assert docs[0]["dist"] == pytest.approx(-1.0, abs=1e-4)  # exact cosine match


def test_document_store_metadata_filter():
    store = _store()
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"query": str, "k": int, "metadata_filter": str, "filepath_globpattern": str}),
        [("anything", 3, "contains(path, 'dogs')", None)],
    )
    rows = capture_rows(store.retrieve_query(queries))
    docs = rows[0]["result"].value
    assert len(docs) == 1
    assert docs[0]["metadata"]["path"] == "/data/dogs.txt"


def test_document_store_globpattern():
    store = _store()
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"query": str, "k": int, "metadata_filter": str, "filepath_globpattern": str}),
        [("anything", 5, None, "**/qc*")],
    )
    rows = capture_rows(store.retrieve_query(queries))
    docs = rows[0]["result"].value
    assert [d["metadata"]["path"] for d in docs] == ["/data/qc.txt"]


def test_document_store_statistics_and_inputs():
    store = _store()
    stats_q = pw.debug.table_from_rows(pw.schema_builder({"dummy": int}), [(1,)])
    rows = capture_rows(store.statistics_query(stats_q))
    stats = rows[0]["result"].value
    assert stats["file_count"] == 3
    assert stats["last_modified"] == 30

    inputs_q = pw.debug.table_from_rows(pw.schema_builder({"dummy": int}), [(1,)])
    rows = capture_rows(store.inputs_query(inputs_q))
    files = rows[0]["result"].value
    assert len(files) == 3


def test_splitter():
    from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter

    splitter = TokenCountSplitter(min_tokens=2, max_tokens=5)
    chunks = splitter.func("one two three four five six seven eight nine ten", {})
    assert len(chunks) >= 2
    text = " ".join(c[0] for c in chunks)
    assert "one" in text and "ten" in text


def test_parser_utf8():
    from pathway_tpu.xpacks.llm.parsers import ParseUtf8

    parser = ParseUtf8()
    assert parser.func(b"hello") == [("hello", {})]


def test_rag_question_answerer():
    from pathway_tpu.xpacks.llm.question_answering import BaseRAGQuestionAnswerer

    store = _store()
    qa = BaseRAGQuestionAnswerer(FakeChat(), store, search_topk=2)
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"prompt": str, "filters": str, "return_context_docs": bool}),
        [("what does the cat do?", None, True)],
    )
    rows = capture_rows(qa.answer_query(queries))
    assert len(rows) == 1
    payload = rows[0]["result"].value
    assert payload["response"].startswith("ANSWER:")
    assert len(payload["context_docs"]) == 2


def test_vector_store_server_rest_e2e():
    """Full REST round-trip: aiohttp server thread + engine thread + HTTP client."""
    import threading
    import time

    import requests

    from pathway_tpu.xpacks.llm.vector_store import VectorStoreClient, VectorStoreServer

    docs = _docs_table()
    server = VectorStoreServer(docs, embedder=FakeEmbedder(dim=16))
    port = 28431
    thread = server.run_server(host="127.0.0.1", port=port, threaded=True)
    client = VectorStoreClient(url=f"http://127.0.0.1:{port}")

    deadline = time.time() + 15
    result = None
    while time.time() < deadline:
        try:
            result = client.query("dogs chase the ball in the park", k=1)
            break
        except Exception:
            time.sleep(0.3)
    assert result is not None, "server did not come up"
    assert result[0]["text"] == "dogs chase the ball in the park"

    stats = client.get_vectorstore_statistics()
    assert stats["file_count"] == 3
    files = client.get_input_files()
    assert len(files) == 3


def test_image_parser_vision_pipeline():
    """ImageParser: decode -> downsize -> base64 -> vision LLM message."""
    import base64
    import io

    from PIL import Image

    from pathway_tpu.xpacks.llm.parsers import ImageParser

    img = Image.new("RGB", (2000, 1000), color=(200, 30, 30))
    buf = io.BytesIO()
    img.save(buf, format="PNG")

    seen = []

    def fake_vision(messages):
        seen.append(messages)
        return "a red rectangle"

    parser = ImageParser(llm=fake_vision, downsize_horizontal_width=640)
    docs = parser.func(buf.getvalue())
    assert docs == [("a red rectangle", {"width": 2000, "height": 1000, "format": "png"})]
    (messages,) = seen
    content = messages[0]["content"]
    assert content[0]["type"] == "text"
    url = content[1]["image_url"]["url"]
    assert url.startswith("data:image/png;base64,")
    # the sent image was downsized to the configured width
    sent = Image.open(io.BytesIO(base64.b64decode(url.split(",", 1)[1])))
    assert sent.size == (640, 320)


def test_slide_parser_per_slide_docs():
    from PIL import Image

    from pathway_tpu.xpacks.llm.parsers import SlideParser

    slides = [Image.new("RGB", (100, 80), color=(0, 0, c)) for c in (10, 20, 30)]
    calls = []

    def fake_vision(messages):
        calls.append(messages)
        return f"slide #{len(calls)}"

    parser = SlideParser(llm=fake_vision, _rasterizer=lambda contents: slides)
    docs = parser.func(b"%PDF-fake")
    assert [d[0] for d in docs] == ["slide #1", "slide #2", "slide #3"]
    assert [d[1]["slide"] for d in docs] == [0, 1, 2]
    assert all(d[1]["slide_count"] == 3 for d in docs)


def test_image_parser_requires_llm():
    import pytest

    from pathway_tpu.xpacks.llm.parsers import ImageParser

    parser = ImageParser()
    with pytest.raises(ValueError, match="vision-capable"):
        parser.func(b"not-an-image")
