"""Elastic mesh membership: grow/shrink the cluster under traffic.

Four layers under test:

- membership plumbing (``parallel/membership.py``): the typed
  ``MembershipMismatchError`` (manifest_n/current_n/epoch + remediation
  hint), the supervisor<->worker directive file, reshard-policy analysis
  refusals;
- state handoff: ``StateTable.reshard_partition`` and the
  ``GroupbyEvaluator`` keyed export/import round-trip (the array
  redistribution at the heart of the reshard);
- chaos (``internals/chaos.py``): the ``scale_join_kill`` /
  ``scale_drain_kill`` / ``handoff_torn`` / ``dropped_scale_handshake``
  plan ops;
- spawn acceptance: a ``spawn -n 2`` cluster scaled 2 -> 4 -> 2 UNDER LIVE
  INGESTION, final output bit-identical to a static n=2 run; joiner catch-up
  from the membership manifest + fragments only (no journal replay,
  asserted on the joiner's own log line); each chaos op recovering via the
  escalation ladder without hanging.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.chaos import Chaos
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.parallel.membership import (
    MembershipDirective,
    MembershipMismatchError,
    clear_directive,
    read_directive,
    write_directive,
)

pytestmark = pytest.mark.elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PORT_SLOT = itertools.count()


def _port_base() -> int:
    return 36000 + os.getpid() % 150 * 40 + next(_PORT_SLOT) * 8


# -- typed mismatch + directive plumbing --------------------------------------


def test_membership_mismatch_error_is_typed_and_actionable(tmp_path):
    """Satellite: a worker-count mismatch carries (manifest_n, current_n,
    epoch) and a --scale-vs-corrupt-store remediation hint, and stays a
    ValueError for pre-elastic refusal handling."""
    from pathway_tpu.persistence.engine import PersistenceManager

    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(tmp_path / "store")
    )
    pm = PersistenceManager(cfg)
    with pytest.raises(MembershipMismatchError) as excinfo:
        pm._check_meta({"key_derivation": 2, "workers": 4, "epoch": 3}, "journal")
    err = excinfo.value
    assert isinstance(err, ValueError)  # pre-elastic triage keeps working
    assert err.manifest_n == 4
    assert err.current_n == 1
    assert err.epoch == 3
    assert "--scale" in str(err) or "spawn --scale" in str(err)
    assert "clear the persistence" in str(err)


def test_directive_file_roundtrip(tmp_path):
    d = MembershipDirective(generation=3, target_n=4, epoch=7, from_n=2)
    write_directive(str(tmp_path), d)
    got = read_directive(str(tmp_path))
    assert got == d
    clear_directive(str(tmp_path))
    assert read_directive(str(tmp_path)) is None
    # malformed files read as "no directive", never crash the commit loop
    (tmp_path / "membership.json").write_text("{not json")
    assert read_directive(str(tmp_path)) is None


def test_store_meta_self_heals_when_manifest_agrees(tmp_path, monkeypatch):
    """Crash window between the membership manifest (the commit point) and
    the store-meta update: a relaunch at the manifest's count rewrites the
    stale meta instead of refusing."""
    from pathway_tpu.persistence.engine import PersistenceManager

    root = tmp_path / "store"
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(root))
    pm = PersistenceManager(cfg)
    pm.open_for_append("sig")  # meta written with workers=2
    pm.dump_cluster_snapshot("sig", 5, {"states": {}, "evaluators": {},
                                        "source_offsets": {}, "source_deltas": {}})
    # the membership manifest commits workers=4 but the meta update is lost
    assert pm.commit_membership_manifest(
        "sig", 5, epoch=1, from_n=2, to_n=4, generation=1
    )
    meta = json.loads((root / "store.meta").read_text())
    assert meta["workers"] == 2  # set_workers never ran (crash window)
    monkeypatch.setenv("PATHWAY_PROCESSES", "4")
    pm4 = PersistenceManager(cfg)
    pm4.open_for_append("sig")  # self-heals: manifest names 4
    assert json.loads((root / "store.meta").read_text())["workers"] == 4
    # a count agreeing with NEITHER still refuses typed
    monkeypatch.setenv("PATHWAY_PROCESSES", "3")
    pm3 = PersistenceManager(cfg)
    with pytest.raises(MembershipMismatchError):
        pm3.open_for_append("sig")


# -- state handoff: the array redistribution ----------------------------------


def test_state_table_reshard_partition_by_key():
    from pathway_tpu.engine.columnar import Delta, StateTable
    from pathway_tpu.internals.keys import sequential_keys, shard_of

    table = StateTable(["v"])
    keys = sequential_keys(100, 16)
    table.apply(Delta(keys, np.ones(16, dtype=np.int64),
                      {"v": np.arange(16, dtype=np.int64)}))
    parts = table.reshard_partition(lambda k: shard_of(k, 4))
    total = 0
    for dest, (pkeys, pdiffs, pcols) in parts.items():
        assert (shard_of(pkeys, 4) == dest).all()
        assert (pdiffs == 1).all()
        total += len(pkeys)
        # rebuild on the "new owner": values survive the move
        t2 = StateTable(["v"])
        t2.apply(Delta(pkeys, pdiffs, pcols))
        assert len(t2) == len(pkeys)
    assert total == 16


def _groupby_runner(rows):
    """A real single-process groupby run, returning (runner, node_id)."""
    from pathway_tpu.engine.runner import GraphRunner

    G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"word": str}), [(w,) for w in rows]
    )
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=4)
    nid = counts._node.id
    return runner, nid


def test_groupby_reshard_export_import_roundtrip():
    """The donor's full export, re-imported into fresh evaluators, carries
    every group's aggregates exactly (counts keep counting correctly)."""
    from pathway_tpu.engine.evaluators import GroupbyEvaluator

    rows = ["cat"] * 3 + ["dog"] * 2 + ["owl"] * 5 + ["elk"]
    runner, nid = _groupby_runner(rows)
    ev = runner.evaluators[nid]
    assert isinstance(ev, GroupbyEvaluator)
    assert ev.reshard_check() is None
    exports = ev.reshard_export(
        lambda keys: (keys["lo"] % np.uint64(2)).astype(np.int64), 2
    )
    assert sum(len(p["gkeys"]) for p in exports.values()) == 4  # 4 groups
    # two fresh importers, one per new rank; re-query their aggregates by
    # re-running an incremental delta through them
    runner2, nid2 = _groupby_runner([])  # empty: fresh evaluator shells
    fresh = runner2.evaluators[nid2]
    for payload in exports.values():
        fresh.reshard_import(payload)
    # all groups present with the exact leaf values
    gkeys, slots = fresh.gindex.items()
    assert len(gkeys) == 4
    counts = {
        int(k["lo"]): int(fresh.leaf_states[0].values(np.array([s]))[0])
        for k, s in zip(gkeys, slots)
    }
    src_gkeys, src_slots = runner.evaluators[nid].gindex.items()
    want = {
        int(k["lo"]): int(
            runner.evaluators[nid].leaf_states[0].values(np.array([s]))[0]
        )
        for k, s in zip(src_gkeys, src_slots)
    }
    assert counts == want
    assert sorted(want.values()) == [1, 2, 3, 5]


def test_groupby_reshard_import_refuses_overlapping_fragments():
    rows = ["cat", "dog"]
    runner, nid = _groupby_runner(rows)
    ev = runner.evaluators[nid]
    full = ev.reshard_export(
        lambda keys: np.zeros(len(keys), dtype=np.int64), 1
    )
    runner2, nid2 = _groupby_runner([])
    fresh = runner2.evaluators[nid2]
    fresh.reshard_import(full[0])
    with pytest.raises(RuntimeError, match="disjoint"):
        fresh.reshard_import(full[0])


# -- observability + plan refusals --------------------------------------------


def test_health_payload_exposes_membership_fields(tmp_path):
    """Satellite: /healthz (via GraphRunner.health) and the status files
    carry target_workers / current_workers / membership_state plus the
    commit/refusal/mismatch markers the supervisor steers by."""
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.parse_graph import ParseGraph
    from pathway_tpu.parallel.supervisor import read_statuses, write_status

    runner = GraphRunner(ParseGraph())

    class _FakeCluster:
        supports_rejoin = True
        epoch = 1
        n = 4

        def heartbeat_ages(self):
            return {}

        def dead_peers(self):
            return {}

    runner._cluster = _FakeCluster()
    runner._membership_state = "resharding"
    runner._member_pending = MembershipDirective(2, 4, 1, 2)
    runner._member_committed_gen = 2
    health = runner.health()
    assert health["membership_state"] == "resharding"
    assert health["current_workers"] == 4
    assert health["target_workers"] == 4
    assert health["membership_committed"] == 2

    write_status(
        str(tmp_path), 0, commit=7, persistence=True,
        extra={
            "membership_state": health["membership_state"],
            "current_workers": health["current_workers"],
            "target_workers": health["target_workers"],
            "membership_committed": health["membership_committed"],
        },
    )
    status = read_statuses(str(tmp_path), 1)[0]
    assert status["membership_state"] == "resharding"
    assert status["target_workers"] == 4
    assert status["membership_committed"] == 2


def test_reshard_plan_accepts_join_graphs():
    """Join arrangements now export by join key and join OUTPUT rows are
    re-exchanged by their output row key, so a join graph plans clean —
    the refusal that used to live here is gone (ROADMAP item closed)."""
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.parallel.membership import compute_reshard_plan

    G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "a": int}), [(1, 10), (2, 20)]
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "b": int}), [(1, 100)]
    )
    joined = left.join(right, left.k == right.k).select(left.a, right.b)
    got: list = []
    pw.io.subscribe(joined, lambda *a, **k: got.append(1))
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=3)
    # stamp the cluster policies the plan reads (single-process runs skip it)
    for node in runner._nodes:
        ev = runner.evaluators[node.id]
        ev._cluster_policies = tuple(
            ev.cluster_input_policy(i) for i in range(len(node.inputs))
        )
    plan = compute_reshard_plan(runner)
    assert plan.ok, plan.refusals
    join_nids = [n.id for n in runner._nodes if n.kind == "join"]
    assert join_nids and all(plan.policies[nid] == "bykey" for nid in join_nids)
    G.clear()


def test_reshard_plan_refusal_is_typed_and_structured():
    """A genuine refusal (join evaluator holding a populated UDF replay memo,
    which is keyed by pre-exchange row keys) surfaces as BOTH a formatted
    string and a structured {node, kind, reason} record for /healthz."""
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.parallel.membership import compute_reshard_plan

    G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "a": int}), [(1, 10)]
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "b": int}), [(1, 100)]
    )
    joined = left.join(right, left.k == right.k).select(left.a, right.b)
    pw.io.subscribe(joined, lambda *a, **k: None)
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=3)
    for node in runner._nodes:
        ev = runner.evaluators[node.id]
        ev._cluster_policies = tuple(
            ev.cluster_input_policy(i) for i in range(len(node.inputs))
        )
    join_nid = next(n.id for n in runner._nodes if n.kind == "join")
    runner.evaluators[join_nid]._udf_memo = {b"stale": 1}
    plan = compute_reshard_plan(runner)
    assert not plan.ok
    assert any("memo" in r for r in plan.refusals)
    assert plan.refused_nodes and plan.refused_nodes[0]["kind"] == "join"
    assert plan.refused_nodes[0]["node"] == join_nid
    G.clear()


def test_reshard_plan_accepts_groupby_pipeline():
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.parallel.membership import compute_reshard_plan

    runner, nid = _groupby_runner(["cat", "dog", "cat"])
    for node in runner._nodes:
        ev = runner.evaluators[node.id]
        ev._cluster_policies = tuple(
            ev.cluster_input_policy(i) for i in range(len(node.inputs))
        )
    plan = compute_reshard_plan(runner)
    assert plan.ok, plan.refusals
    assert plan.policies[nid] == "bykey"
    G.clear()


# -- chaos plan ops -----------------------------------------------------------


def test_chaos_scale_fault_gating(monkeypatch):
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "0")
    plan = {
        "scale": [
            {"op": "handoff_torn", "rank": 1, "at": 0},
            {"op": "dropped_scale_handshake", "rank": 2},
            {"op": "scale_drain_kill", "rank": 3, "run": 1},
        ]
    }
    c = Chaos(0, plan)
    c.begin_scale_attempt()  # attempt 0
    assert c.scale_fault("handoff_torn", 1) is True
    assert c.scale_fault("handoff_torn", 0) is False  # wrong rank
    c.begin_scale_attempt()  # attempt 1: `at: 0` no longer fires
    assert c.scale_fault("handoff_torn", 1) is False
    assert c.scale_fault("dropped_scale_handshake", 2) is True  # every attempt
    assert c.scale_fault("scale_drain_kill", 3) is False  # wrong run
    assert c.stats["scale_faults"] == 2


def test_chaos_scale_kill_fires_sigkill(monkeypatch):
    killed: list = []
    from pathway_tpu.internals import chaos as chaos_mod

    monkeypatch.setattr(
        chaos_mod.os, "kill", lambda pid, sig: killed.append((pid, sig))
    )
    c = Chaos(0, {"scale": [{"op": "scale_join_kill", "rank": 2, "run": 0}]})
    c.begin_scale_attempt()
    c.maybe_scale_kill(1, "scale_join_kill")
    assert killed == []
    c.maybe_scale_kill(2, "scale_join_kill")
    assert killed == [(os.getpid(), signal.SIGKILL)]


# -- spawn acceptance ---------------------------------------------------------

ELASTIC_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema,
        mode="streaming",
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

    out_path = os.path.join(tmp, f"out_{pid}.json")
    rows = {}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(repr(key), None)
        with open(out_path + ".tmp", "w") as f:
            json.dump(list(rows.values()), f)
        os.replace(out_path + ".tmp", out_path)

    pw.io.subscribe(counts, on_change)
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
    )
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    """
)


def _spawn_elastic(
    tmp_path, first_port, *, n, scale_plan, plan=None, max_restarts=0,
    extra_env=None,
):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_SCALE_PLAN"] = json.dumps(scale_plan)
    if plan is not None:
        env["PATHWAY_CHAOS_SEED"] = "7"
        env["PATHWAY_CHAOS_PLAN"] = json.dumps(plan)
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "30"
    env["PATHWAY_FENCE_TIMEOUT_S"] = "30"
    env["PATHWAY_MEMBERSHIP_DEADLINE_S"] = "60"
    env.update(extra_env or {})
    prog = tmp_path / "prog.py"
    prog.write_text(ELASTIC_PROG)
    return subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", str(n), "--first-port", str(first_port),
            "--max-restarts", str(max_restarts),
            sys.executable, str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        start_new_session=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def _read_merged(tmp_path, n: int) -> dict:
    merged: dict = {}
    for p in range(n):
        path = tmp_path / f"out_{p}.json"
        if not path.exists():
            continue
        try:
            for r in json.loads(path.read_text()):
                merged[r["word"]] = r["total"]
        except ValueError:
            pass
    return merged


def _terminate_group(proc) -> str:
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    try:
        _, err = proc.communicate(timeout=20)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        _, err = proc.communicate()
    return err or ""


def _await_counts(proc, tmp_path, n, expected, deadline_s=240) -> dict:
    # generous deadline: convergence itself is asserted EXACTLY by the
    # caller — under full-suite load on the shared 2-core host, a chaos
    # recovery (restart-all + journal replay) can legitimately take minutes,
    # and a tight wait here reads as a spurious row-loss failure
    deadline = time.time() + deadline_s
    merged: dict = {}
    while time.time() < deadline:
        if proc.poll() is not None:
            _, err = proc.communicate()
            raise AssertionError(
                f"spawn exited early (rc={proc.returncode}): {err}"
            )
        merged = _read_merged(tmp_path, n)
        if merged == expected:
            break
        time.sleep(0.3)
    return merged


def _failure_free_counts(tmp_path) -> dict:
    """Reference output: the same pipeline run in-process, statically, at
    n=1 — the bit-identity baseline for the scaled cluster."""
    G.clear()

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        str(tmp_path / "in"), format="csv", schema=WordSchema, mode="static"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    rows: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(key, None)

    pw.io.subscribe(counts, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    G.clear()
    return {r["word"]: r["total"] for r in rows.values()}


def _write_files(tmp_path, prefix: str, spec: dict) -> None:
    for name, words in spec.items():
        (tmp_path / "in" / f"{prefix}{name}.csv").write_text(
            "word\n" + "\n".join(words) + "\n"
        )


@pytest.mark.chaos
def test_elastic_grow_shrink_cycle_exact(tmp_path):
    """THE acceptance scenario: n=2 -> 4 -> 2 under live ingestion. Data
    lands before, between, and after the transitions; the final merged
    output is bit-identical to a static n=2 (and n=1) run; joiners catch up
    from the membership manifest + fragments only (no journal replay —
    asserted on the joiner's own log line); leavers drain as planned
    exits."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 3 + ["dog"] * 2,
        "1": ["cat"] * 2 + ["owl"] * 1,
        "2": ["dog"] * 4,
        "3": ["elk"] * 2 + ["cat"] * 1,
    })
    scale_plan = [
        {"after_commit": 4, "n": 4},
        {"after_commit": 14, "n": 2},
    ]
    proc = _spawn_elastic(tmp_path, first_port, n=2, scale_plan=scale_plan)
    err = ""
    try:
        time.sleep(8)  # let the grow transition land under traffic
        _write_files(tmp_path, "b", {
            "0": ["fox"] * 3 + ["cat"] * 2,
            "1": ["owl"] * 2,
        })
        time.sleep(8)  # shrink window
        _write_files(tmp_path, "c", {"0": ["cat"] * 1 + ["bee"] * 2})
        expected = {"cat": 9, "dog": 6, "owl": 3, "elk": 2, "fox": 3, "bee": 2}
        merged = _await_counts(proc, tmp_path, 4, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "membership change complete: cluster is n=4" in err, (
        f"grow transition never completed:\n{err}"
    )
    assert "membership change complete: cluster is n=2" in err, (
        f"shrink transition never completed:\n{err}"
    )
    assert "joined the cluster" in err and "no journal replay" in err, (
        f"joiner catch-up was not manifest+fragments:\n{err}"
    )
    assert "drained for scale-down" in err, (
        f"leavers were not drained cleanly:\n{err}"
    )
    assert "restarting the cluster" not in err, (
        f"a transition fell back to restart-all:\n{err}"
    )
    # bit-identical to the failure-free static run of the same pipeline
    assert _failure_free_counts(tmp_path) == merged


@pytest.mark.chaos
def test_elastic_scale_join_kill_recovers(tmp_path):
    """Chaos: a joiner is SIGKILLed before it installs. The transition
    cannot complete surgically — the supervisor recovers down the ladder
    (restart-all at the committed topology) without hanging, and the final
    output stays exact."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 3 + ["dog"] * 2,
        "1": ["owl"] * 2,
    })
    plan = {"scale": [{"op": "scale_join_kill", "rank": 2, "run": 0}]}
    proc = _spawn_elastic(
        tmp_path, first_port, n=2,
        scale_plan=[{"after_commit": 4, "n": 4}],
        plan=plan, max_restarts=3,
        extra_env={"PATHWAY_MEMBERSHIP_DEADLINE_S": "20",
                   "PATHWAY_CONNECT_TIMEOUT_S": "8"},
    )
    err = ""
    try:
        time.sleep(12)
        _write_files(tmp_path, "b", {"0": ["fox"] * 3})
        expected = {"cat": 3, "dog": 2, "owl": 2, "fox": 3}
        merged = _await_counts(proc, tmp_path, 4, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "restarting the cluster" in err, (
        f"the joiner kill did not recover via restart-all:\n{err}"
    )


@pytest.mark.chaos
def test_elastic_handoff_torn_retries_and_completes(tmp_path):
    """Chaos: the first transition attempt's handoff fragment write tears.
    Read-back verification fails the ack barrier, the attempt aborts
    cleanly (previous topology stands), and the NEXT attempt completes —
    output exact, no restart."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 3 + ["dog"] * 2,
        "1": ["owl"] * 2,
    })
    plan = {"scale": [{"op": "handoff_torn", "rank": 0, "at": 0, "run": 0}]}
    proc = _spawn_elastic(
        tmp_path, first_port, n=2,
        scale_plan=[{"after_commit": 4, "n": 3}],
        plan=plan, max_restarts=2,
    )
    err = ""
    try:
        time.sleep(8)
        _write_files(tmp_path, "b", {"0": ["fox"] * 3})
        expected = {"cat": 3, "dog": 2, "owl": 2, "fox": 3}
        merged = _await_counts(proc, tmp_path, 3, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "aborted (transient" in err or "will retry" in err, (
        f"the torn handoff never aborted an attempt:\n{err}"
    )
    assert "membership change complete: cluster is n=3" in err, (
        f"the retry never completed the transition:\n{err}"
    )
    assert "restarting the cluster" not in err, (
        f"the torn handoff escalated to restart-all:\n{err}"
    )


@pytest.mark.chaos
def test_elastic_dropped_scale_handshake_recovers(tmp_path):
    """Chaos: the joiner's membership hello is dropped — its wiring fails
    typed, the transition cannot converge, and the supervisor recovers
    (deadline -> restart-all at the committed topology) without hanging."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 2 + ["dog"] * 1,
        "1": ["owl"] * 2,
    })
    plan = {"scale": [{"op": "dropped_scale_handshake", "rank": 2, "run": 0}]}
    proc = _spawn_elastic(
        tmp_path, first_port, n=2,
        scale_plan=[{"after_commit": 4, "n": 3}],
        plan=plan, max_restarts=3,
        extra_env={"PATHWAY_MEMBERSHIP_DEADLINE_S": "15",
                   "PATHWAY_CONNECT_TIMEOUT_S": "6",
                   "PATHWAY_FENCE_TIMEOUT_S": "12"},
    )
    err = ""
    try:
        time.sleep(14)
        _write_files(tmp_path, "b", {"0": ["fox"] * 2})
        expected = {"cat": 2, "dog": 1, "owl": 2, "fox": 2}
        merged = _await_counts(proc, tmp_path, 3, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "restarting the cluster" in err, (
        f"the dropped handshake did not recover via restart-all:\n{err}"
    )


@pytest.mark.chaos
def test_elastic_scale_drain_kill_recovers(tmp_path):
    """Chaos: a donor rank is SIGKILLed mid-handoff (after the quiesce vote,
    before its fragments are durable). The manifest never commits, so the
    ladder recovers at the OLD topology and the re-issued transition is not
    required for exactness — output stays exact either way."""
    (tmp_path / "in").mkdir()
    first_port = _port_base()
    _write_files(tmp_path, "a", {
        "0": ["cat"] * 2 + ["dog"] * 1,
        "1": ["owl"] * 2,
    })
    plan = {"scale": [{"op": "scale_drain_kill", "rank": 1, "run": 0, "at": 0}]}
    proc = _spawn_elastic(
        tmp_path, first_port, n=2,
        scale_plan=[{"after_commit": 4, "n": 4}],
        plan=plan, max_restarts=3,
        extra_env={"PATHWAY_MEMBERSHIP_DEADLINE_S": "20",
                   "PATHWAY_CONNECT_TIMEOUT_S": "8",
                   "PATHWAY_FENCE_TIMEOUT_S": "12"},
    )
    err = ""
    try:
        time.sleep(14)
        _write_files(tmp_path, "b", {"0": ["fox"] * 2})
        expected = {"cat": 2, "dog": 1, "owl": 2, "fox": 2}
        merged = _await_counts(proc, tmp_path, 4, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "restarting the cluster" in err, (
        f"the drain kill did not recover via restart-all:\n{err}"
    )
