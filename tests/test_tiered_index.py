"""Tiered IVF index (ISSUE 15): device-hot / host-cold / frozen-spill page
residency, EWMA-driven promotion with async prefetch, incremental centroid
maintenance, and the fence-riding background rebuild + generation swap
(``ops/knn_tiers.py``). The prefetch/rebuild/swap protocol's model checks live
in ``test_modelcheck.py`` (``tiered_index_model``)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.brownout import get_brownout, reset_brownout
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.ops.knn_tiers import (
    DirSpillStore,
    TieredIvfKnnStore,
    tiering_enabled,
)

pytestmark = pytest.mark.tiered

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clustered(n, dim, n_centers, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(n_centers, dim)).astype(np.float32)
    docs = (
        centers[rng.integers(0, n_centers, n)] + rng.normal(size=(n, dim))
    ).astype(np.float32)
    return centers, docs


def _exact_top(docs, queries, k):
    qn = np.sum(queries * queries, axis=1)[:, None]
    dn = np.sum(docs * docs, axis=1)[None, :]
    dist = qn + dn - 2.0 * queries @ docs.T
    return np.argsort(dist, axis=1)[:, :k]


# -- residency / scoring ------------------------------------------------------


def test_tiered_full_probe_matches_exact():
    _, docs = _clustered(3000, 24, 12, seed=1)
    store = TieredIvfKnnStore(24, n_clusters=12, n_probe=12)
    store.add_many([f"d{i}" for i in range(3000)], docs)
    q = docs[:40]
    _s, idx, valid = store.search_batch(q, 10)
    assert valid[:, 0].all()
    exact = _exact_top(docs, q, 10)
    for r in range(40):
        got = {store.key_of[int(i)] for i in idx[r] if i >= 0}
        want = {f"d{j}" for j in exact[r]}
        assert got == want
    store.close()


def test_residency_never_changes_results_bitwise(tmp_path):
    """The tier-honesty contract: the same corpus + queries return BITWISE
    identical scores/slots whether everything is hot or the store runs a
    tiny HBM budget with a frozen spill tier."""
    centers, docs = _clustered(4000, 16, 8, seed=2)
    keys = [f"d{i}" for i in range(4000)]
    rng = np.random.default_rng(3)
    q = (centers[np.zeros(16, dtype=int)] + rng.normal(size=(16, 16))).astype(
        np.float32
    )
    tiered = TieredIvfKnnStore(
        16, n_clusters=8, n_probe=2, hbm_budget_bytes=30_000,
        spill_store=DirSpillStore(str(tmp_path / "spill")),
    )
    allhot = TieredIvfKnnStore(16, n_clusters=8, n_probe=2)
    tiered.add_many(keys, docs)
    allhot.add_many(keys, docs)
    for _ in range(6):  # settle the EWMA; spill + demotion engage
        rt = tiered.search_batch(q, 10)
        rh = allhot.search_batch(q, 10)
    time.sleep(0.3)  # the prefetch worker drains its staging queue
    rt = tiered.search_batch(q, 10)
    rh = allhot.search_batch(q, 10)
    stats = tiered.tier_stats()
    assert stats["spilled"] > 0 or stats["spills"] > 0, stats
    np.testing.assert_array_equal(rt[0], rh[0])
    np.testing.assert_array_equal(rt[1], rh[1])
    tiered.close()
    allhot.close()


def test_hot_tier_respects_budget_with_demotions():
    _, docs = _clustered(4000, 16, 8, seed=4)
    budget = 50_000
    store = TieredIvfKnnStore(
        16, n_clusters=8, n_probe=8, hbm_budget_bytes=budget
    )
    store.add_many([f"d{i}" for i in range(4000)], docs)
    q = docs[:16]
    for _ in range(8):
        store.search_batch(q, 5)
    time.sleep(0.5)  # promotions are async; let them land and evict
    assert store.tiers.hot_bytes <= budget, store.tier_stats()
    # full-probe traffic over 8 clusters cannot all fit: something demoted
    assert store.tiers.counts()["hot"] < 8, store.tier_stats()
    store.close()


def test_spill_prefetch_and_stall_accounting(tmp_path):
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.profile import histograms

    centers, docs = _clustered(4000, 16, 8, seed=5)
    store = TieredIvfKnnStore(
        16, n_clusters=8, n_probe=2, hbm_budget_bytes=30_000,
        spill_store=DirSpillStore(str(tmp_path / "spill")),
    )
    store.add_many([f"d{i}" for i in range(4000)], docs)
    rng = np.random.default_rng(6)
    q0 = (centers[np.zeros(8, dtype=int)] + rng.normal(size=(8, 16))).astype(
        np.float32
    )
    for _ in range(6):
        store.search_batch(q0, 5)  # narrow working set: the rest freezes
    assert store.tier_stats()["spilled"] > 0, store.tier_stats()
    # now probe EVERY cluster: frozen ones must come back (prefetch/unspill)
    _s, idx, valid = store.search_batch(docs[:32], 5)
    assert valid[:, 0].all()
    stats = store.tier_stats()
    assert stats["probe_spilled"] > 0, stats
    stages = telemetry.stage_snapshot("index.")
    assert stages.get("index.probes", 0) > 0
    assert "pathway_ivf_prefetch_stall_seconds" in histograms()
    assert "pathway_ivf_tier_hit_ratio" in histograms()
    assert "pathway_ivf_tier_occupancy_ratio" in histograms()
    store.close()


# -- incremental maintenance / background rebuild -----------------------------


def test_churn_is_incremental_not_stop_the_world():
    """Mutation batches below the rebuild-drift threshold touch only their
    clusters: the generation never bumps, no rebuild is scheduled, and both
    added and removed rows are immediately visible."""
    _, docs = _clustered(2000, 16, 8, seed=7)
    store = TieredIvfKnnStore(16, n_clusters=8, n_probe=8)
    store.add_many([f"d{i}" for i in range(2000)], docs)
    store.search_batch(docs[:4], 3)  # initial train
    gen0 = store.generation
    rng = np.random.default_rng(8)
    for wave in range(4):
        fresh = (docs[rng.integers(0, 2000, 40)]).astype(np.float32)
        store.add_many([f"w{wave}-{i}" for i in range(40)], fresh)
        for i in range(20):
            store.remove(f"w{wave}-{i}") if wave else store.remove(f"d{i}")
        _s, idx, _v = store.search_batch(fresh[:2], 1)
    assert store.generation == gen0
    assert not store._rebuild_inflight(), store.tier_stats()
    # a just-added row is findable, a just-removed row is not
    probe_vec = docs[150:151]
    store.add("fresh-row", probe_vec[0])
    _s, idx, _v = store.search_batch(probe_vec, 1)
    assert store.key_of.get(int(idx[0, 0])) == "fresh-row"
    store.remove("fresh-row")
    _s, idx, _v = store.search_batch(probe_vec, 1)
    assert store.key_of.get(int(idx[0, 0])) != "fresh-row"
    store.close()


def test_drifted_cluster_splits_without_global_retrain():
    """Concentrated churn into one region splits/recenters THAT cluster
    (bounded per-cluster work) — n_clusters can grow, generation stays."""
    _, docs = _clustered(800, 8, 4, seed=9)
    store = TieredIvfKnnStore(8, n_clusters=4, n_probe=4)
    store.add_many([f"d{i}" for i in range(800)], docs)
    store.search_batch(docs[:4], 3)
    gen0, c0 = store.generation, store.n_clusters
    # pile one tight blob onto a single cluster (far corner of the space)
    blob = (np.full((600, 8), 40.0) + np.random.default_rng(10).normal(
        size=(600, 8)
    )).astype(np.float32)
    for s in range(0, 600, 100):
        store.add_many([f"b{i}" for i in range(s, s + 100)], blob[s : s + 100])
        store.search_batch(blob[:2], 1)
    assert store.generation == gen0
    assert store.n_clusters > c0 or store.stats["splits"] > 0, store.tier_stats()
    store.close()


def test_background_rebuild_swaps_at_commit_boundary():
    _, docs = _clustered(1500, 16, 8, seed=11)
    store = TieredIvfKnnStore(16, n_clusters=8, n_probe=8)
    store.add_many([f"d{i}" for i in range(1500)], docs)
    store.search_batch(docs[:4], 3)
    gen0 = store.generation
    # churn past the rebuild-drift threshold (replace the whole corpus)
    for i in range(1500):
        store.remove(f"d{i}")
    _, fresh = _clustered(1600, 16, 8, seed=12)
    store.add_many([f"n{i}" for i in range(1600)], fresh)
    r_old = store.search_batch(fresh[:8], 5)
    assert store._rebuild_inflight() or store.generation > gen0
    # the OLD generation answered while the rebuild ran — and correctly
    assert np.isfinite(r_old[0][:, 0]).all()
    deadline = time.monotonic() + 30
    while store._rebuild_inflight() and time.monotonic() < deadline:
        time.sleep(0.05)
    store.search_batch(fresh[:1], 1)  # the commit boundary that swaps
    store.search_batch(fresh[:1], 1)
    assert store.generation == gen0 + 1, store.tier_stats()
    exact = _exact_top(fresh, fresh[:20], 10)
    _s, idx, _v = store.search_batch(fresh[:20], 10)
    hits = 0
    for r in range(20):
        got = {store.key_of.get(int(i)) for i in idx[r] if i >= 0}
        hits += len(got & {f"n{j}" for j in exact[r]})
    assert hits / 200 >= 0.95
    # pause accounting: the swap took ONE bounded pause, not a retrain stall
    assert store.stats["swaps"] == 1
    assert store.stats["max_pause_s"] < 5.0
    store.close()


def test_rebuild_dirty_churn_reconciled_at_swap():
    """Rows added/removed WHILE the rebuild runs land in the swapped
    generation exactly once (the dirty-set reconcile)."""
    _, docs = _clustered(1200, 16, 8, seed=13)
    store = TieredIvfKnnStore(16, n_clusters=8, n_probe=8)
    store.add_many([f"d{i}" for i in range(1200)], docs)
    store.search_batch(docs[:4], 3)
    for i in range(1200):
        store.remove(f"d{i}")
    _, fresh = _clustered(1200, 16, 8, seed=14)
    store.add_many([f"n{i}" for i in range(1200)], fresh)
    store.search_batch(fresh[:1], 1)  # schedules the rebuild
    assert store._rebuild_inflight()
    # churn DURING the rebuild: late adds + a late removal
    late = fresh[:5] + 0.25
    store.add_many([f"late{i}" for i in range(5)], late)
    store.remove("n0")
    deadline = time.monotonic() + 30
    while store._rebuild_inflight() and time.monotonic() < deadline:
        time.sleep(0.05)
    store.search_batch(fresh[:1], 1)
    assert store.generation >= 1
    _s, idx, _v = store.search_batch(late, 1)
    got = {store.key_of.get(int(i)) for i in idx[:, 0]}
    assert got == {f"late{i}" for i in range(5)}, got
    _s, idx, _v = store.search_batch(fresh[:1], 3)
    assert "n0" not in {store.key_of.get(int(i)) for i in idx[0] if i >= 0}
    store.close()


# -- chaos: torn swap + rebuild kill ------------------------------------------


@pytest.mark.chaos
def test_torn_tier_swap_old_generation_intact_then_retries(monkeypatch):
    """Injected ``tier_swap_torn`` at rebuild attempt 0: the pending
    generation is DISCARDED at the commit boundary, the old generation keeps
    serving correct results, and the next maintenance pass schedules a fresh
    rebuild (attempt 1, not gated) that swaps cleanly."""
    from pathway_tpu.internals.chaos import reset_chaos

    monkeypatch.setenv(
        "PATHWAY_CHAOS_PLAN",
        json.dumps({"index": [{"op": "tier_swap_torn", "rank": 0, "at": 0}]}),
    )
    monkeypatch.setenv("PATHWAY_CHAOS_SEED", "3")
    reset_chaos()
    try:
        _, docs = _clustered(1000, 16, 8, seed=15)
        store = TieredIvfKnnStore(16, n_clusters=8, n_probe=8)
        store.add_many([f"d{i}" for i in range(1000)], docs)
        store.search_batch(docs[:4], 3)
        for i in range(1000):
            store.remove(f"d{i}")
        _, fresh = _clustered(1000, 16, 8, seed=16)
        store.add_many([f"n{i}" for i in range(1000)], fresh)
        store.search_batch(fresh[:1], 1)  # schedules rebuild attempt 0
        deadline = time.monotonic() + 30
        while store._rebuild_inflight() and time.monotonic() < deadline:
            time.sleep(0.05)
        r_torn = store.search_batch(fresh[:10], 5)  # the torn swap boundary
        assert store.stats["swaps_torn"] == 1, store.tier_stats()
        assert store.generation == 0  # OLD generation intact and serving
        assert np.isfinite(r_torn[0][:, 0]).all()
        exact = _exact_top(fresh, fresh[:10], 5)
        for r in range(10):
            got = {store.key_of.get(int(i)) for i in r_torn[1][r] if i >= 0}
            assert got == {f"n{j}" for j in exact[r]}
        # drift is still over threshold: the retry rebuild (attempt 1) swaps
        store.search_batch(fresh[:1], 1)
        deadline = time.monotonic() + 30
        while store._rebuild_inflight() and time.monotonic() < deadline:
            time.sleep(0.05)
        store.search_batch(fresh[:1], 1)
        assert store.generation == 1, store.tier_stats()
        assert store.stats["swaps"] == 1
        store.close()
    finally:
        reset_chaos()


TIERED_CHAOS_PROG = textwrap.dedent(
    """
    import hashlib, json, os
    import numpy as np
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class DocSchema(pw.Schema):
        text: str

    @pw.udf
    def embed(text: str) -> np.ndarray:
        digest = hashlib.sha256(str(text).encode()).digest()
        rng = np.random.default_rng(int.from_bytes(digest[:8], "little"))
        v = rng.normal(size=8).astype(np.float32)
        return v / np.linalg.norm(v)

    docs = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=DocSchema,
        mode="streaming",
    )
    from pathway_tpu.stdlib.indexing import IvfKnnFactory

    # full probe: results are EXACT whatever generation answers, so the
    # output is bit-identical across any rebuild/kill/replay interleaving
    factory = IvfKnnFactory(
        dimensions=8, n_clusters=4, n_probe=4, embedder=embed
    )
    index = factory.build_index(docs.text, docs)
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"q": str}), [("doc-7",), ("doc-23",), ("doc-41",)]
    )
    res = index.query(queries.q, number_of_matches=1, collapse_rows=True)
    out_path = os.path.join(tmp, f"out_{pid}.json")
    rows = {}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[repr(key)] = {"q": row["q"], "text": list(row["text"])}
        else:
            rows.pop(repr(key), None)
        with open(out_path + ".tmp", "w") as f:
            json.dump(list(rows.values()), f)
        os.replace(out_path + ".tmp", out_path)

    pw.io.subscribe(res.select(pw.this.q, pw.this.text), on_change)
    pw.run(
        monitoring_level=pw.MonitoringLevel.NONE,
        persistence_config=pw.persistence.Config(
            pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
        ),
    )
    """
)


@pytest.mark.chaos
def test_rebuild_kill_spawn_n2_recovers_bit_identical(tmp_path):
    """The n=2 acceptance: a chaos ``rebuild_kill`` SIGKILLs rank 0 while its
    background index rebuild is mid-build; the supervisor ladder recovers
    (persistence on), the torn new generation is simply gone, and the final
    retrieve output is bit-identical to a failure-free run."""
    (tmp_path / "in").mkdir()
    # wave 1 trains; wave 2's churn crosses the rebuild-drift threshold
    (tmp_path / "in" / "a.csv").write_text(
        "text\n" + "\n".join(f"doc-{i}" for i in range(30)) + "\n"
    )
    prog = tmp_path / "prog.py"
    prog.write_text(TIERED_CHAOS_PROG)
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_IVF_TIERED"] = "on"
    env["PATHWAY_IVF_REBUILD_DRIFT"] = "0.5"
    env["PATHWAY_CHAOS_SEED"] = "7"
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(
        {"index": [{"op": "rebuild_kill", "rank": 0, "run": 0}]}
    )
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "30"
    first_port = 26200 + os.getpid() % 500 * 4
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(first_port),
            "--max-restarts", "2",
            sys.executable, str(prog),
        ],
        env=env, cwd=str(tmp_path), start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )

    def _answers():
        merged = {}
        for p in range(2):
            path = tmp_path / f"out_{p}.json"
            if path.exists():
                try:
                    for r in json.loads(path.read_text()):
                        merged[r["q"]] = r["text"]
                except ValueError:
                    pass
        return merged

    try:
        # wave 2 lands mid-run: the add churn schedules the rebuild the
        # chaos op kills
        time.sleep(2.0)
        (tmp_path / "in" / "b.csv").write_text(
            "text\n" + "\n".join(f"doc-{i}" for i in range(30, 60)) + "\n"
        )
        want = {"doc-7": ["doc-7"], "doc-23": ["doc-23"], "doc-41": ["doc-41"]}
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early rc={proc.returncode}: {err[-2000:]}"
                )
            if _answers() == want:
                break
            time.sleep(0.5)
        assert _answers() == want, _answers()
    finally:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            _, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            _, err = proc.communicate()
    # the kill actually fired (rank 0 died mid-rebuild and was relaunched)
    assert "restart" in (err or "").lower() or "rejoin" in (err or "").lower(), (
        err or ""
    )[-2000:]


# -- brownout interplay -------------------------------------------------------


def test_brownout_rung2_probe_never_triggers_promotion_churn():
    """The satellite contract: rung 2 halves ``n_probe`` at query time AND a
    browned-out probe set must not promote/demote — degradation protects the
    tiers, it must not thrash them."""
    reset_brownout()
    try:
        _, docs = _clustered(2000, 16, 8, seed=17)
        store = TieredIvfKnnStore(
            16, n_clusters=8, n_probe=8, hbm_budget_bytes=60_000
        )
        store.add_many([f"d{i}" for i in range(2000)], docs)
        store.search_batch(docs[:2], 1)  # train off the brownout clock
        time.sleep(0.3)
        from pathway_tpu.engine import telemetry

        before = telemetry.stage_snapshot("index.").get(
            "index.prefetch_requests", 0.0
        )
        get_brownout().observe_occupancy(0.95)  # engage rung 2
        assert get_brownout().nprobe_shift() == 1
        assert store._effective_n_probe() == 4
        for _ in range(4):
            store.search_batch(docs[:8], 3)
        after = telemetry.stage_snapshot("index.").get(
            "index.prefetch_requests", 0.0
        )
        assert after == before, (before, after)
        store.close()
    finally:
        reset_brownout()


# -- selection / descriptor / membership --------------------------------------


def test_tiering_enabled_knob(monkeypatch):
    monkeypatch.delenv("PATHWAY_IVF_TIERED", raising=False)
    monkeypatch.delenv("PATHWAY_IVF_HBM_BUDGET_MB", raising=False)
    assert not tiering_enabled()
    monkeypatch.setenv("PATHWAY_IVF_HBM_BUDGET_MB", "64")
    assert tiering_enabled()  # auto: budget implies tiered
    monkeypatch.setenv("PATHWAY_IVF_TIERED", "off")
    assert not tiering_enabled()
    monkeypatch.setenv("PATHWAY_IVF_TIERED", "on")
    monkeypatch.delenv("PATHWAY_IVF_HBM_BUDGET_MB", raising=False)
    assert tiering_enabled()
    from pathway_tpu.ops.knn import IvfKnnIndex

    index = IvfKnnIndex(8, n_clusters=4, n_probe=2)
    assert isinstance(index.store, TieredIvfKnnStore)
    index.store.close()


def test_rebuild_descriptor_roundtrip():
    from pathway_tpu.ops.knn import IvfKnnIndex

    _, docs = _clustered(500, 8, 4, seed=18)
    src = IvfKnnIndex(8, n_clusters=4, n_probe=4, tiered=True)
    src.add_many(
        [f"d{i}" for i in range(500)], list(docs),
        filter_data=[{"n": i} if i % 2 == 0 else None for i in range(500)],
    )
    src.search_many([docs[0]], [1])  # train
    desc = src.rebuild_descriptor()
    assert desc is not None and len(desc["keys"]) == 500
    dst = IvfKnnIndex(8, n_clusters=4, n_probe=4, tiered=True)
    dst.install_rebuild_descriptor(desc)
    a = src.search_many(list(docs[:20]), [3] * 20)
    b = dst.search_many(list(docs[:20]), [3] * 20)
    for ra, rb in zip(a, b):
        assert {k for k, _ in ra} == {k for k, _ in rb}
    assert dst.filter_data.get("d0") == {"n": 0}
    src.store.close()
    dst.store.close()


def test_reshard_plan_accepts_descriptor_capable_external_index():
    """The membership-preflight half of the new contract: an external index
    whose store exports a rebuildable descriptor plans as ``replicate``
    instead of the blanket device-resident refusal."""
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.parallel.membership import compute_reshard_plan
    from pathway_tpu.stdlib.indexing import IvfKnnFactory

    from .mocks import fake_embedding

    @pw.udf
    def embed(text: str) -> np.ndarray:
        return fake_embedding(text, 8)

    G.clear()
    docs = pw.debug.table_from_rows(
        pw.schema_builder({"text": str}), [("alpha",), ("beta",), ("gamma",)]
    )
    factory = IvfKnnFactory(dimensions=8, n_clusters=2, n_probe=2, embedder=embed)
    index = factory.build_index(docs.text, docs)
    queries = pw.debug.table_from_rows(pw.schema_builder({"q": str}), [("alpha",)])
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    got: list = []
    pw.io.subscribe(res, lambda *a, **k: got.append(1))
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=4)
    for node in runner._nodes:
        ev = runner.evaluators[node.id]
        ev._cluster_policies = tuple(
            ev.cluster_input_policy(i) for i in range(len(node.inputs))
        )
    plan = compute_reshard_plan(runner)
    # the external-index node itself plans as "replicate" — the blanket
    # device-resident refusal is GONE for descriptor-capable indexes (the
    # collapse_rows flatten downstream keeps its own, unrelated refusal)
    ext = [
        nid for nid, pol in plan.policies.items() if pol == "replicate"
    ]
    assert ext, (plan.policies, plan.refusals)
    assert not any(
        "external index" in r or "snapshot protocol" in r for r in plan.refusals
    ), plan.refusals
    # descriptor round-trips through the evaluator surface the fragments use
    ev = runner.evaluators[ext[0]]
    desc = ev.rebuild_descriptor()
    assert desc is not None and len(desc["keys"]) == 3
    G.clear()


def test_reshard_plan_keeps_typed_refusal_without_descriptor():
    """An index type with no export contract still refuses — loudly."""
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.parallel.membership import compute_reshard_plan
    from pathway_tpu.stdlib.indexing.nearest_neighbors import LshKnn
    from pathway_tpu.stdlib.indexing.data_index import DataIndex

    from .mocks import fake_embedding

    @pw.udf
    def embed(text: str) -> np.ndarray:
        return fake_embedding(text, 8)

    G.clear()
    docs = pw.debug.table_from_rows(
        pw.schema_builder({"text": str}), [("alpha",), ("beta",)]
    )
    index = DataIndex(
        docs, LshKnn(docs.text, None, dimensions=8, embedder=embed)
    )
    queries = pw.debug.table_from_rows(pw.schema_builder({"q": str}), [("alpha",)])
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    got: list = []
    pw.io.subscribe(res, lambda *a, **k: got.append(1))
    runner = GraphRunner(G._current)
    runner.lint_exempt = True
    runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=4)
    for node in runner._nodes:
        ev = runner.evaluators[node.id]
        ev._cluster_policies = tuple(
            ev.cluster_input_policy(i) for i in range(len(node.inputs))
        )
    plan = compute_reshard_plan(runner)
    assert not plan.ok
    assert any("rebuildable descriptor" in r for r in plan.refusals), plan.refusals
    G.clear()


def test_index_counters_on_openmetrics():
    from pathway_tpu.engine.http_server import ProberStats

    from .utils import validate_openmetrics

    _, docs = _clustered(500, 8, 4, seed=19)
    store = TieredIvfKnnStore(8, n_clusters=4, n_probe=2)
    store.add_many([f"d{i}" for i in range(500)], docs)
    store.search_batch(docs[:4], 3)
    text = ProberStats().to_openmetrics()
    validate_openmetrics(text)
    assert 'pathway_stage_total{stage="index.probes"}' in text
    assert "pathway_ivf_tier_occupancy_ratio" in text
    store.close()
