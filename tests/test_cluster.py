"""Multi-process correctness: ``spawn -n N`` with the cluster exchange
(reference rig: ``integration_tests/wordcount/base.py`` — subprocess pipelines with
``PATHWAY_PROCESSES`` combos asserting exactly-correct global output)."""

from __future__ import annotations

import collections
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(n: int, program: str, tmp_path, extra_env: dict | None = None) -> None:
    prog = tmp_path / "prog.py"
    prog.write_text(program)
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env.update(extra_env or {})
    out = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", str(n), "--first-port", str(19000 + os.getpid() % 500 * 4),
            sys.executable, str(prog),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, f"spawn failed:\nstdout={out.stdout}\nstderr={out.stderr}"


WORDCOUNT_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    words = json.load(open(os.path.join(tmp, f"input_{pid}.json")))
    rows = [(w,) for w in words]
    tbl = pw.debug.table_from_rows(pw.schema_builder({"word": str}), rows)
    counts = tbl.groupby(pw.this.word).reduce(pw.this.word, cnt=pw.reducers.count())
    got = {}
    pw.io.subscribe(
        counts,
        lambda key, row, time, is_addition: got.__setitem__(row["word"], row["cnt"])
        if is_addition
        else got.pop(row["word"], None),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)


@pytest.mark.parametrize("n_processes", [2, 3])
def test_spawn_wordcount_exact_global_counts(tmp_path, n_processes):
    """Each process ingests a disjoint shard; grouped counts must be EXACT global
    totals, with every word owned by exactly one process."""
    import numpy as np

    rng = np.random.default_rng(7)
    pool = [f"word{i}" for i in range(40)]
    shards = []
    for p in range(n_processes):
        shard = [pool[i] for i in rng.integers(0, len(pool), 300)]
        shards.append(shard)
        (tmp_path / f"input_{p}.json").write_text(json.dumps(shard))

    _spawn(n_processes, WORDCOUNT_PROG, tmp_path)

    expected = collections.Counter()
    for shard in shards:
        expected.update(shard)
    merged: dict = {}
    owners: dict = {}
    for p in range(n_processes):
        out = json.loads((tmp_path / f"out_{p}.json").read_text())
        for word, cnt in out.items():
            assert word not in owners, (
                f"word {word!r} owned by both process {owners[word]} and {p}"
            )
            owners[word] = p
            merged[word] = cnt
    assert merged == dict(expected)
    if n_processes > 1:
        assert len(set(owners.values())) > 1, "all keys landed on one process"


JOIN_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    data = json.load(open(os.path.join(tmp, f"jinput_{pid}.json")))
    left = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "v": int}), [tuple(r) for r in data["left"]]
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"k2": str, "w": int}), [tuple(r) for r in data["right"]]
    )
    j = left.join(right, left.k == right.k2).select(left.k, s=left.v + right.w)
    rows = []
    pw.io.subscribe(
        j,
        on_batch=lambda keys, diffs, columns, time: rows.extend(
            (str(k), int(s), int(d))
            for k, s, d in zip(columns["k"], columns["s"], diffs)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(rows, open(os.path.join(tmp, f"jout_{pid}.json"), "w"))
    """
)


def test_spawn_join_exact_global_result(tmp_path):
    """Join sides ingested on DIFFERENT processes still meet on the key owner."""
    n = 2
    # left rows only on process 0, right rows only on process 1: any correct pair
    # proves the cross-process exchange (no co-located data at all)
    left = [(f"k{i}", i) for i in range(50)]
    right = [(f"k{i}", 100 + i) for i in range(0, 50, 2)]
    (tmp_path / "jinput_0.json").write_text(json.dumps({"left": left, "right": []}))
    (tmp_path / "jinput_1.json").write_text(json.dumps({"left": [], "right": right}))

    _spawn(n, JOIN_PROG, tmp_path)

    got = collections.Counter()
    for p in range(n):
        for k, s, d in json.loads((tmp_path / f"jout_{p}.json").read_text()):
            got[(k, s)] += d
    expected = collections.Counter({(f"k{i}", 100 + 2 * i): 1 for i in range(0, 50, 2)})
    assert {kv: c for kv, c in got.items() if c != 0} == dict(expected)


PAGERANK_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw
    from pathway_tpu.stdlib.graphs import pagerank

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    data = json.load(open(os.path.join(tmp, f"pr_input_{pid}.json")))
    eraw = pw.debug.table_from_rows(
        pw.schema_builder({"u_raw": int, "v_raw": int}), [tuple(r) for r in data]
    )
    edges = eraw.select(
        u=eraw.pointer_from(eraw.u_raw), v=eraw.pointer_from(eraw.v_raw)
    )
    ranks = pagerank(edges, steps=3)
    # rank rows come keyed by vertex pointer; recover the vertex label by join
    verts = eraw.select(vid=eraw.v_raw).groupby(pw.this.vid).reduce(pw.this.vid)
    labeled = verts.with_id(verts.pointer_from(pw.this.vid)).join(
        ranks, pw.left.id == pw.right.id
    ).select(pw.left.vid, pw.right.rank)
    got = {}
    pw.io.subscribe(
        labeled,
        lambda key, row, time, is_addition: got.__setitem__(str(row["vid"]), row["rank"])
        if is_addition
        else got.pop(str(row["vid"]), None),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"pr_out_{pid}.json"), "w"))
    """
)


def test_spawn_pagerank_exact(tmp_path):
    """pagerank (unrolled join/groupby rounds with same-universe cross refs,
    which the placement analysis must admit) under spawn -n 2: edges split
    across processes; ranks must equal the single-process run's."""
    edges = [(i, 0) for i in range(1, 5)] + [(0, 1), (2, 1)]
    shard0 = edges[::2]
    shard1 = edges[1::2]

    # single-process expected output
    (tmp_path / "pr_input_0.json").write_text(json.dumps(edges))
    _spawn(1, PAGERANK_PROG, tmp_path)
    expected = json.loads((tmp_path / "pr_out_0.json").read_text())
    assert expected, "single-process pagerank produced nothing"

    (tmp_path / "pr_input_0.json").write_text(json.dumps(shard0))
    (tmp_path / "pr_input_1.json").write_text(json.dumps(shard1))
    _spawn(2, PAGERANK_PROG, tmp_path)
    merged: dict = {}
    for p in range(2):
        out = json.loads((tmp_path / f"pr_out_{p}.json").read_text())
        for vid, rank in out.items():
            assert vid not in merged, f"vertex {vid} owned twice"
            merged[vid] = rank
    assert merged == expected


ITERATE_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    rows = json.load(open(os.path.join(tmp, f"it_input_{pid}.json")))
    t = pw.debug.table_from_rows(pw.schema_builder({"a": int}), [tuple(r) for r in rows])
    halve = lambda t: dict(t=t.select(a=pw.if_else(t.a > 1, t.a // 2, t.a)))
    s = pw.iterate(halve, t=t).t
    total = s.reduce(n=pw.reducers.count(), s=pw.reducers.sum(pw.this.a))
    got = []
    pw.io.subscribe(
        total,
        lambda key, row, time, is_addition: got.append((row["n"], row["s"]))
        if is_addition
        else got.remove((row["n"], row["s"])),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"it_out_{pid}.json"), "w"))
    """
)


def test_spawn_iterate_fixpoint_exact(tmp_path):
    """pw.iterate (nested fixpoint, formerly blocklisted) under spawn -n 2:
    inputs split across processes; the fixpoint centralizes on process 0 and
    the global aggregate must equal the single-process answer."""
    (tmp_path / "it_input_0.json").write_text(json.dumps([(1,), (16,), (7,)]))
    (tmp_path / "it_input_1.json").write_text(json.dumps([(64,), (3,)]))
    _spawn(2, ITERATE_PROG, tmp_path)
    merged = []
    for p in range(2):
        merged.extend(
            tuple(x) for x in json.loads((tmp_path / f"it_out_{p}.json").read_text())
        )
    # every value halves to 1: 5 rows, sum 5 (exactly one process owns the total)
    assert merged == [(5, 5)], merged


TRANSFORMER_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    rows = json.load(open(os.path.join(tmp, f"tr_input_{pid}.json")))

    class OutputSchema(pw.Schema):
        ret: int

    @pw.transformer
    class add_one:
        class table(pw.ClassArg, output=OutputSchema):
            arg = pw.input_attribute()

            @pw.output_attribute
            def ret(self) -> int:
                return self.arg + 1

    t = pw.debug.table_from_rows(pw.schema_builder({"arg": int}), [tuple(r) for r in rows])
    ret = add_one(t).table
    got = []
    pw.io.subscribe(
        ret,
        lambda key, row, time, is_addition: got.append(row["ret"])
        if is_addition
        else got.remove(row["ret"]),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(sorted(got), open(os.path.join(tmp, f"tr_out_{pid}.json"), "w"))
    """
)


def test_spawn_row_transformer_exact(tmp_path):
    """@pw.transformer (pointer-chasing, formerly blocklisted) under spawn -n 2:
    rows split across processes; outputs must equal the single-process run's."""
    (tmp_path / "tr_input_0.json").write_text(json.dumps([(i,) for i in range(1, 7)]))
    (tmp_path / "tr_input_1.json").write_text(json.dumps([(i,) for i in range(7, 13)]))
    _spawn(2, TRANSFORMER_PROG, tmp_path)
    merged: list = []
    for p in range(2):
        merged.extend(json.loads((tmp_path / f"tr_out_{p}.json").read_text()))
    assert sorted(merged) == list(range(2, 14))


STREAMING_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    rows = json.load(open(os.path.join(tmp, f"input_{pid}.json")))
    tbl = pw.debug.table_from_rows(
        pw.schema_builder({"word": str}), [tuple(r) for r in rows], is_stream=True
    )
    counts = tbl.groupby(pw.this.word).reduce(pw.this.word, cnt=pw.reducers.count())
    got = {}
    pw.io.subscribe(
        counts,
        lambda key, row, time, is_addition: got.__setitem__(row["word"], row["cnt"])
        if is_addition
        else got.pop(row["word"], None),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)


def test_spawn_streaming_commits_with_retractions(tmp_path):
    """The lockstep exchange must stay correct across MULTIPLE commits, including
    a retraction that crosses process boundaries (a row retracted on process 0
    while its group is owned by the peer)."""
    n = 2
    # process 0: inserts a@t0, b@t2, retracts a@t4; process 1: inserts a@t0, b@t4
    inputs = {
        0: [("a", 0, 1), ("b", 2, 1), ("a", 4, -1)],
        1: [("a", 0, 1), ("b", 4, 1)],
    }
    for pid, rows in inputs.items():
        (tmp_path / f"input_{pid}.json").write_text(json.dumps(rows))
    _spawn(n, STREAMING_PROG, tmp_path)
    merged = collections.Counter()
    owners = collections.Counter()
    for pid in range(n):
        out = json.loads((tmp_path / f"out_{pid}.json").read_text())
        for w, c in out.items():
            merged[w] += c
            owners[w] += 1
    # global truth: a -> 1 (2 inserts - 1 retract), b -> 2
    assert dict(merged) == {"a": 1, "b": 2}
    assert all(v == 1 for v in owners.values())  # one owner per group


def test_python_connector_reads_on_process_zero_only(tmp_path, monkeypatch):
    """A non-parallelized python ConnectorSubject must read on process 0 only
    (reference parallel-reader placement, dataflow.rs:3317); peers see its rows
    via the exchange, not by re-running the subject."""
    import pathway_tpu as pw
    from pathway_tpu.io.python import ConnectorSubject, read

    class Subj(ConnectorSubject):
        def run(self):
            self.next(v=1)
            self.close()

    class Sch(pw.Schema):
        v: int

    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    import pathway_tpu.internals.parse_graph as pg_mod

    pg_mod.G.clear()
    t = read(Subj(), schema=Sch)
    node = next(n for n in pg_mod.G._current.nodes if n.kind == "input")
    from pathway_tpu.io.python import _NoopRunner

    assert isinstance(node.config["source"].subject, _NoopRunner)

    # process 0 DOES read
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    pg_mod.G.clear()
    t0 = read(Subj(), schema=Sch)
    node0 = next(n for n in pg_mod.G._current.nodes if n.kind == "input")
    assert not isinstance(node0.config["source"].subject, _NoopRunner)

    # a parallelized subject reads everywhere
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")

    class ShardedSubj(Subj):
        parallelized = True

    pg_mod.G.clear()
    t1 = read(ShardedSubj(), schema=Sch)
    node1 = next(n for n in pg_mod.G._current.nodes if n.kind == "input")
    assert not isinstance(node1.config["source"].subject, _NoopRunner)


def test_multiprocess_kafka_requires_consumer_group(monkeypatch):
    import pytest

    import pathway_tpu as pw

    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    import pathway_tpu.internals.parse_graph as pg_mod

    pg_mod.G.clear()
    with pytest.raises(ValueError, match="group.id"):
        pw.io.kafka.read(
            {"bootstrap.servers": "x"},
            topic="t",
            _consumer_factory=lambda s: None,
        )
