"""Graph-lint tests (pathway_tpu/analysis): one deliberately-broken graph per
pass (golden diagnostics asserted by code), the PATHWAY_LINT run-time gate, the
``cli analyze`` exit-code contract, telemetry mirroring, a clean sweep over the
``examples/`` programs, and the REWIND_SAFE source audit."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import GraphLintError, Severity, analyze_graph
from pathway_tpu.internals import parse_graph as pg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(report):
    return {d.code for d in report.diagnostics}


def _ints_table():
    return pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,), (2,), (3,)])


# ---------------------------------------------------------------------------
# PWA001 — determinism
# ---------------------------------------------------------------------------


def test_pwa001_time_udf_flagged_with_location():
    t = _ints_table()

    @pw.udf
    def stamp(a: int) -> float:
        return time.time() + a

    t.select(x=stamp(t.v))
    report = analyze_graph(pg.G._current)
    found = report.by_code("PWA001")
    assert found, report.to_json()
    d = found[0]
    assert d.severity == Severity.ERROR
    assert "time.time()" in d.message
    assert d.file is not None and d.file.endswith("test_analysis.py")
    assert d.node_kind == "rowwise"
    assert report.exit_code() == 2


def test_pwa001_random_uuid_direct_import_and_lambda():
    import random

    t = _ints_table()
    t.select(x=pw.apply(lambda a: random.random() * a, t.v))
    report = analyze_graph(pg.G._current)
    assert any(
        "random.random()" in d.message for d in report.by_code("PWA001")
    ), report.to_json()


def test_pwa001_datetime_module_chain_flagged():
    # the common spelling: ``import datetime; datetime.datetime.now()`` —
    # two attribute loads deep from the module global
    import datetime

    t = _ints_table()
    t.select(x=pw.apply(lambda a: datetime.datetime.now().timestamp() + a, t.v))
    report = analyze_graph(pg.G._current)
    assert any(
        "datetime.datetime.now()" in d.message for d in report.by_code("PWA001")
    ), report.to_json()


def test_pwa001_global_and_closure_mutation():
    t = _ints_table()

    def bump_global(a):
        global _PWA001_COUNTER  # noqa: PLW0603 - deliberate violation
        _PWA001_COUNTER = a
        return a

    seen = []

    def bump_closure(a):
        seen.append(a)
        return a

    t.select(x=pw.apply(bump_global, t.v), y=pw.apply(bump_closure, t.v))
    report = analyze_graph(pg.G._current)
    reasons = {d.details.get("reason") for d in report.by_code("PWA001")}
    assert "global_mutation" in reasons, report.to_json()
    assert "closure_mutation" in reasons, report.to_json()


def test_pwa001_local_container_with_closed_over_key_quiet():
    # a deterministic UDF that item-assigns into a LOCAL dict using a
    # closed-over KEY must not be flagged; item-assigning into a closed-over
    # CONTAINER must
    t = _ints_table()
    key = "k"
    state = {}

    def local_dict(a):
        out = {}
        out[key] = a
        return out[key]

    def shared_dict(a):
        state[a] = a
        return a

    t.select(x=pw.apply(local_dict, t.v), y=pw.apply(shared_dict, t.v))
    report = analyze_graph(pg.G._current)
    flagged = {d.details.get("udf") for d in report.by_code("PWA001")}
    assert "local_dict" not in flagged, report.to_json()
    assert "shared_dict" in flagged, report.to_json()


def test_pwa001_clean_udf_and_sink_callbacks_quiet():
    t = _ints_table()

    @pw.udf
    def pure(a: int) -> int:
        return a * 2 + 1

    r = t.select(x=pure(t.v))
    got = []
    # sink callbacks mutate closures by design; they are not dataflow UDFs
    pw.io.subscribe(r, lambda key, row, time, is_addition: got.append(row["x"]))
    report = analyze_graph(pg.G._current)
    assert not report.by_code("PWA001"), report.to_json()


# ---------------------------------------------------------------------------
# PWA002 — rewind safety
# ---------------------------------------------------------------------------


def _buffered_graph():
    t = pw.debug.table_from_markdown(
        """
        t | v | __time__ | __diff__
        1 | 1 | 0        | 1
        4 | 2 | 2        | 1
        """
    )
    return t._buffer(pw.this.t + 2, pw.this.t)


def test_pwa002_buffer_warns_under_persistence():
    _buffered_graph()
    report = analyze_graph(pg.G._current, persistence=True)
    found = report.by_code("PWA002")
    assert found and found[0].severity == Severity.WARNING, report.to_json()
    assert found[0].node_kind == "buffer"
    assert report.exit_code() == 1
    assert report.exit_code(strict=True) == 2


def test_pwa002_info_only_without_persistence():
    _buffered_graph()
    report = analyze_graph(pg.G._current, persistence=False)
    found = report.by_code("PWA002")
    assert found and found[0].severity == Severity.INFO
    assert report.exit_code() == 0


def test_pwa002_audit_draining_flushers_are_marked_rewind_unsafe():
    """Source audit: every evaluator whose process() consults runner.draining
    (a live-only flush signal replay cannot reproduce) must opt out of the
    rewind rung — the PR 6 review found the time-threshold family by hand;
    this proves the list stays complete."""
    import types

    from pathway_tpu.engine import evaluators as ev_mod
    from pathway_tpu.engine.evaluators import Evaluator

    def code_mentions_draining(cls) -> bool:
        # compiled code only — comments/docstrings about draining don't count
        for value in vars(cls).values():
            fn = getattr(value, "__func__", value)
            code = getattr(fn, "__code__", None)
            if code is None:
                continue
            stack = [code]
            while stack:
                co = stack.pop()
                if "draining" in co.co_names or "draining" in co.co_consts:
                    return True
                stack.extend(
                    c for c in co.co_consts if isinstance(c, types.CodeType)
                )
        return False

    offenders = []
    for name in dir(ev_mod):
        cls = getattr(ev_mod, name)
        if not (isinstance(cls, type) and issubclass(cls, Evaluator)):
            continue
        if code_mentions_draining(cls) and getattr(cls, "REWIND_SAFE", True):
            offenders.append(cls.__name__)
    assert not offenders, (
        f"evaluators flush on runner.draining but claim REWIND_SAFE: {offenders}"
    )


# ---------------------------------------------------------------------------
# PWA003 — unbounded state
# ---------------------------------------------------------------------------


class _EndlessSubject(pw.io.python.ConnectorSubject):
    def run(self):  # pragma: no cover - never started by the analyzer
        pass


class _StreamSchema(pw.Schema):
    v: int


def test_pwa003_streaming_groupby_flagged():
    t = pw.io.python.read(_EndlessSubject(), schema=_StreamSchema)
    t.groupby(t.v).reduce(t.v, n=pw.reducers.count())
    report = analyze_graph(pg.G._current)
    found = report.by_code("PWA003")
    assert found and found[0].severity == Severity.WARNING, report.to_json()
    assert found[0].node_kind == "groupby"


def test_pwa003_forget_upstream_suppresses():
    t = pw.io.python.read(_EndlessSubject(), schema=_StreamSchema)
    bounded = t._forget(pw.this.v + 10, pw.this.v)
    bounded.groupby(bounded.v).reduce(bounded.v, n=pw.reducers.count())
    report = analyze_graph(pg.G._current)
    assert not report.by_code("PWA003"), report.to_json()


def test_pwa003_forget_on_sibling_branch_does_not_mask():
    # a forget on the join's RIGHT branch must not mask the forget-free LEFT
    # branch from the same unbounded source
    t = pw.io.python.read(_EndlessSubject(), schema=_StreamSchema)
    raw = t.select(v=t.v)
    bounded = t._forget(pw.this.v + 10, pw.this.v)
    raw.join(bounded, raw.v == bounded.v).select(v=pw.left.v)
    report = analyze_graph(pg.G._current)
    found = [d for d in report.by_code("PWA003") if d.node_kind == "join"]
    assert found, report.to_json()


def test_pwa003_static_source_quiet():
    t = _ints_table()
    t.groupby(t.v).reduce(t.v, n=pw.reducers.count())
    report = analyze_graph(pg.G._current)
    assert not report.by_code("PWA003"), report.to_json()


# ---------------------------------------------------------------------------
# PWA004 — device placement
# ---------------------------------------------------------------------------


def test_pwa004_udf_inside_numeric_chain():
    t = _ints_table()

    @pw.udf
    def double(a: int) -> int:
        return a * 2

    t.select(y=double(t.v) + t.v * 3)
    report = analyze_graph(pg.G._current)
    found = report.by_code("PWA004")
    assert found and found[0].severity == Severity.WARNING, report.to_json()
    assert found[0].details.get("udf") == "double"


def test_pwa004_udf_alone_or_host_dtypes_quiet():
    t = pw.debug.table_from_rows(
        pw.schema_builder({"v": int, "s": str}), [(1, "a"), (2, "b")]
    )

    @pw.udf
    def double(a: int) -> int:
        return a * 2

    @pw.udf
    def tag(s: str) -> str:
        return s + "!"

    # standalone UDF column (no numeric chain) and a str chain: both fine
    t.select(y=double(t.v), z=tag(t.s) + "x")
    report = analyze_graph(pg.G._current)
    assert not report.by_code("PWA004"), report.to_json()


def test_pwa004_inconsistent_device_kwargs():
    class FakeStore:
        def __init__(self, device):
            self.device = device

    t = _ints_table()
    pg.G.add_node(pg.Node(inputs=[t], store=FakeStore("tpu:0"), name="store_a"))
    pg.G.add_node(pg.Node(inputs=[t], store=FakeStore("cpu:0"), name="store_b"))
    report = analyze_graph(pg.G._current)
    found = report.by_code("PWA004")
    assert len(found) == 2, report.to_json()
    assert {d.details.get("device") for d in found} == {"tpu:0", "cpu:0"}


def test_pwa004_consistent_devices_quiet():
    class FakeStore:
        def __init__(self, device):
            self.device = device

    t = _ints_table()
    pg.G.add_node(pg.Node(inputs=[t], store=FakeStore("tpu:0")))
    pg.G.add_node(pg.Node(inputs=[t], store=FakeStore("tpu:0")))
    report = analyze_graph(pg.G._current)
    assert not report.by_code("PWA004"), report.to_json()


# ---------------------------------------------------------------------------
# PWA005 — checkpoint compatibility
# ---------------------------------------------------------------------------


def _knn_graph():
    import numpy as np

    from pathway_tpu.stdlib.ml.index import KNNIndex

    docs = pw.debug.table_from_rows(
        pw.schema_builder({"vec": np.ndarray}),
        [(np.asarray([1.0, 0.0], dtype=np.float32),)],
    )
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"qvec": np.ndarray}),
        [(np.asarray([0.9, 0.1], dtype=np.float32),)],
    )
    KNNIndex(docs.vec, docs, n_dimensions=2).get_nearest_items(queries.qvec, k=1)


def test_pwa005_external_index_under_persistence_errors():
    _knn_graph()
    report = analyze_graph(pg.G._current, persistence=True)
    found = report.by_code("PWA005")
    assert any(
        d.severity == Severity.ERROR and d.node_kind == "external_index"
        for d in found
    ), report.to_json()


def test_pwa005_quiet_without_persistence():
    _knn_graph()
    report = analyze_graph(pg.G._current, persistence=False)
    assert not report.by_code("PWA005"), report.to_json()


def test_pwa005_source_without_offset_state_warns():
    from pathway_tpu.engine.datasource import DataSource
    from pathway_tpu.internals.table import Table

    class RawSource(DataSource):
        def next_batch(self, column_names):
            raise NotImplementedError

        def is_finished(self):
            return True

    node = pg.G.add_node(pg.InputNode(source=RawSource()))
    Table(node, pw.schema_builder({"v": int}), name="raw")
    report = analyze_graph(pg.G._current, persistence=True)
    found = report.by_code("PWA005")
    assert any(d.details.get("source") == "RawSource" for d in found), report.to_json()


# ---------------------------------------------------------------------------
# run-time gate: PATHWAY_LINT=off|warn|error
# ---------------------------------------------------------------------------


def _nondet_graph_with_sink():
    t = _ints_table()

    @pw.udf
    def stamp(a: int) -> float:
        return time.time() + a

    r = t.select(x=stamp(t.v))
    got = []
    pw.io.subscribe(r, lambda key, row, time, is_addition: got.append(row["x"]))
    return got


def test_lint_error_mode_refuses_nondeterministic_graph(monkeypatch):
    monkeypatch.setenv("PATHWAY_LINT", "error")
    _nondet_graph_with_sink()
    with pytest.raises(GraphLintError) as exc_info:
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert "PWA001" in str(exc_info.value)


def test_lint_off_preserves_behavior(monkeypatch):
    monkeypatch.setenv("PATHWAY_LINT", "off")
    got = _nondet_graph_with_sink()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(got) == 3


def test_lint_warn_default_runs_and_logs(monkeypatch, caplog):
    import logging

    monkeypatch.delenv("PATHWAY_LINT", raising=False)
    got = _nondet_graph_with_sink()
    with caplog.at_level(logging.WARNING, logger="pathway_tpu.analysis"):
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(got) == 3  # default mode never blocks a run
    assert any("PWA001" in r.message for r in caplog.records)


def test_lint_unknown_mode_warns_and_does_not_block(monkeypatch, caplog):
    import logging

    # a typo'd mode must be loud, not a silent disarm of the error gate
    monkeypatch.setenv("PATHWAY_LINT", "errors")
    got = _nondet_graph_with_sink()
    with caplog.at_level(logging.WARNING, logger="pathway_tpu.analysis"):
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(got) == 3  # fell back to warn: the run proceeds
    assert any("unrecognized PATHWAY_LINT" in r.getMessage() for r in caplog.records)


def test_lint_capture_sees_replay_storage_persistence(monkeypatch, tmp_path):
    """PATHWAY_REPLAY_STORAGE implies persistence even when run() gets no
    persistence_config — the persistence-gated passes must see it."""
    from pathway_tpu.analysis import GraphCaptureInterrupt

    monkeypatch.setenv("PATHWAY_REPLAY_STORAGE", str(tmp_path / "replay"))
    monkeypatch.setenv("PATHWAY_LINT_CAPTURE", "1")
    _ints_table()
    with pytest.raises(GraphCaptureInterrupt) as exc_info:
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert exc_info.value.persistence is True


def test_lint_error_mode_does_not_refuse_debug_helpers(monkeypatch, capsys):
    # pw.debug is local inspection, not a production run: a debug print of a
    # nondeterministic graph must keep working under PATHWAY_LINT=error
    monkeypatch.setenv("PATHWAY_LINT", "error")
    t = _ints_table()

    @pw.udf
    def stamp(a: int) -> float:
        return time.time() + a

    r = t.select(x=stamp(t.v))
    pw.debug.compute_and_print(r)  # must not raise GraphLintError
    assert "x" in capsys.readouterr().out


def test_lint_error_mode_refuses_run_threads_lane(monkeypatch):
    # run_threads workers build their own graphs with no parent run: rank 0
    # must still lint, so PATHWAY_LINT=error refuses the lane too
    from pathway_tpu.parallel.threads import run_threads

    monkeypatch.setenv("PATHWAY_LINT", "error")

    def program():
        t = _ints_table()

        @pw.udf
        def stamp(a: int) -> float:
            return time.time() + a

        r = t.select(x=stamp(t.v))
        got = []
        pw.io.subscribe(r, lambda key, row, time, is_addition: got.append(row["x"]))
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)

    with pytest.raises(RuntimeError, match="GraphLintError"):
        run_threads(program, 2)


def test_lint_telemetry_mirrored(monkeypatch):
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.profile import get_flight_recorder

    monkeypatch.setenv("PATHWAY_LINT", "warn")
    telemetry.stage_reset("lint.")
    recorder = get_flight_recorder()
    monkeypatch.setattr(recorder, "enabled", True)
    _nondet_graph_with_sink()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    counters = telemetry.stage_snapshot("lint.")
    assert counters.get("lint.errors", 0) >= 1, counters
    assert counters.get("lint.diag.PWA001", 0) >= 1, counters
    assert any(
        ev.get("kind") == "lint" and ev.get("errors", 0) >= 1
        for ev in list(recorder._events)
    )


# ---------------------------------------------------------------------------
# cli analyze: exit-code contract + clean sweep over examples/
# ---------------------------------------------------------------------------


def _cli_env():
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PATHWAY_LINT", None)
    env.pop("PATHWAY_LINT_CAPTURE", None)
    return env


def _analyze_cli(program: str, *flags: str):
    proc = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "analyze", *flags, program],
        capture_output=True,
        text=True,
        env=_cli_env(),
        timeout=120,
        cwd=REPO,
    )
    return proc


def _parse_json_stdout(stdout: str) -> dict:
    return json.loads(stdout[stdout.index("{") :])


_CLEAN_PROG = """
import pathway_tpu as pw
t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,), (2,)])
r = t.select(x=t.v * 2)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""

_WARNING_PROG = """
import pathway_tpu as pw

class Subj(pw.io.python.ConnectorSubject):
    def run(self):
        pass

class Sch(pw.Schema):
    v: int

t = pw.io.python.read(Subj(), schema=Sch)
t.groupby(t.v).reduce(t.v, n=pw.reducers.count())
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""

_ERROR_PROG = """
import time
import pathway_tpu as pw

t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,)])

@pw.udf
def stamp(a: int) -> float:
    return time.time() + a

t.select(x=stamp(t.v))
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""


def test_cli_analyze_exit_code_contract(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text(_CLEAN_PROG)
    warn = tmp_path / "warn.py"
    warn.write_text(_WARNING_PROG)
    err = tmp_path / "err.py"
    err.write_text(_ERROR_PROG)

    p = _analyze_cli(str(clean), "--format", "json")
    assert p.returncode == 0, p.stdout + p.stderr
    payload = _parse_json_stdout(p.stdout)
    assert payload["summary"]["errors"] == 0

    p = _analyze_cli(str(warn), "--format", "json")
    assert p.returncode == 1, p.stdout + p.stderr
    payload = _parse_json_stdout(p.stdout)
    assert payload["summary"]["errors"] == 0
    assert payload["summary"]["warnings"] >= 1
    assert any(d["code"] == "PWA003" for d in payload["diagnostics"])

    p = _analyze_cli(str(warn), "--format", "json", "--strict")
    assert p.returncode == 2, p.stdout + p.stderr

    p = _analyze_cli(str(err), "--format", "json")
    assert p.returncode == 2, p.stdout + p.stderr
    payload = _parse_json_stdout(p.stdout)
    assert any(
        d["code"] == "PWA001" and d["severity"] == "error"
        for d in payload["diagnostics"]
    )
    # text format carries the same verdict
    p = _analyze_cli(str(err))
    assert p.returncode == 2
    assert "PWA001" in p.stdout


_CRASH_PROG = """
import nonexistent_module_xyz  # crashes before any graph exists
"""

_DEBUG_THEN_ERROR_PROG = """
import time
import pathway_tpu as pw

t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,)])
df = pw.debug.table_to_pandas(t)  # debug capture mid-build must not end analysis

@pw.udf
def stamp(a: int) -> float:
    return time.time() + a

t.select(x=stamp(t.v))
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""


def test_cli_analyze_program_crash_is_exit_3(tmp_path):
    # a crashing program must not collide with the 0/1/2 diagnostic contract
    prog = tmp_path / "crash.py"
    prog.write_text(_CRASH_PROG)
    p = _analyze_cli(str(prog), "--format", "json")
    assert p.returncode == 3, p.stdout + p.stderr
    assert "crashed" in p.stderr


def test_cli_analyze_debug_helper_does_not_truncate(tmp_path):
    # pw.debug mid-program executes normally under capture; the analyzer still
    # sees the FULL graph built afterwards and reports its errors
    prog = tmp_path / "dbg.py"
    prog.write_text(_DEBUG_THEN_ERROR_PROG)
    p = _analyze_cli(str(prog), "--format", "json")
    assert p.returncode == 2, p.stdout + p.stderr
    payload = _parse_json_stdout(p.stdout)
    assert any(d["code"] == "PWA001" for d in payload["diagnostics"])


def test_cli_analyze_clean_sweep_over_examples():
    """The analyzer reports zero errors over the shipped example programs
    (06 drives a spawn cluster from a driver script and is exercised by
    test_cli instead)."""
    examples = [
        "01_streaming_wordcount.py",
        "02_etl_joins.py",
        "03_windows_and_behaviors.py",
        "04_vector_index_rag.py",
        "05_persistence_resume.py",
    ]
    for name in examples:
        p = _analyze_cli(os.path.join(REPO, "examples", name), "--format", "json")
        payload = _parse_json_stdout(p.stdout)
        assert payload["summary"]["errors"] == 0, (name, p.stdout, p.stderr)
        assert p.returncode in (0, 1), (name, p.stdout, p.stderr)


def test_bench_like_graph_clean():
    """A representative bench-engine pipeline (join + groupby + filter chain)
    carries no lint errors."""
    left = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "a": int}), [(i, i * 2) for i in range(20)]
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "b": int}), [(i, i * 3) for i in range(20)]
    )
    joined = left.join(right, left.k == right.k).select(
        k=pw.left.k, s=pw.left.a + pw.right.b
    )
    filtered = joined.filter(joined.s > 4)
    filtered.groupby(filtered.k).reduce(filtered.k, total=pw.reducers.sum(filtered.s))
    report = analyze_graph(pg.G._current, persistence=True)
    assert not report.errors, report.to_json()


def test_analyzer_overhead_negligible():
    """The build-time lint of a mid-sized graph stays well under a commit's
    budget (acceptance: no measurable tier-1 slowdown)."""
    t = _ints_table()
    cur = t
    for _ in range(30):
        cur = cur.select(v=cur.v + 1)
    cur.groupby(cur.v).reduce(cur.v, n=pw.reducers.count())
    t0 = time.perf_counter()
    analyze_graph(pg.G._current)
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, f"analysis took {elapsed:.3f}s on a 30-node chain"
