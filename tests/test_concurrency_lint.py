"""Concurrency-lint tests (pathway_tpu/analysis/concurrency.py): one planted
violation per pass (PWA101 lock-order cycle + call-chain self-deadlock, PWA102
unbounded waits, PWA103 unlocked shared writes with the constructor exemption,
PWA104 thread lifecycle), noqa suppression, the ``cli analyze --runtime``
exit-code contract, the clean-tree gate the acceptance criteria demand, and
telemetry mirroring."""

from __future__ import annotations

import json
import os
import subprocess
import sys

from pathway_tpu.analysis import Severity, analyze_runtime, analyze_source
from pathway_tpu.analysis.concurrency import (
    RUNTIME_MODULES,
    LockOrderPass,
    build_runtime_context,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(report):
    return {d.code for d in report.diagnostics}


# ---------------------------------------------------------------------------
# PWA101 — lock-order cycles
# ---------------------------------------------------------------------------

_INVERSION = '''
import threading

class Inverted:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def forward(self):
        with self.a:
            with self.b:
                pass

    def backward(self):
        with self.b:
            with self.a:
                pass
'''


def test_pwa101_inversion_cycle_flagged():
    report = analyze_source(_INVERSION)
    found = report.by_code("PWA101")
    assert found, report.to_json()
    d = found[0]
    assert d.severity == Severity.ERROR
    assert "Inverted.a" in d.message and "Inverted.b" in d.message
    assert d.line is not None


def test_pwa101_consistent_order_quiet():
    consistent = _INVERSION.replace(
        "with self.b:\n            with self.a:",
        "with self.a:\n            with self.b:",
    )
    assert not analyze_source(consistent).by_code("PWA101")


def test_pwa101_call_chain_self_deadlock():
    src = '''
import threading

class SelfDead:
    def __init__(self):
        self.lk = threading.Lock()
    def outer(self):
        with self.lk:
            self.inner()
    def inner(self):
        with self.lk:
            pass
'''
    report = analyze_source(src)
    assert report.by_code("PWA101"), report.to_json()
    # an RLock is reentrant: same shape is legal
    assert not analyze_source(
        src.replace("threading.Lock()", "threading.RLock()")
    ).by_code("PWA101")


def test_pwa101_cross_method_cycle_via_calls():
    src = '''
import threading

class TwoLayers:
    def __init__(self):
        self.outer_lk = threading.Lock()
        self.inner_lk = threading.Lock()
    def path_one(self):
        with self.outer_lk:
            self.helper()
    def helper(self):
        with self.inner_lk:
            pass
    def path_two(self):
        with self.inner_lk:
            with self.outer_lk:
                pass
'''
    report = analyze_source(src)
    found = report.by_code("PWA101")
    assert found, report.to_json()
    assert "TwoLayers.inner_lk" in found[0].message


def test_pwa101_condition_alias_is_not_a_cycle():
    # Condition(self._lock) shares the mutex: with cond inside with lock must
    # not read as a two-lock cycle (it is a self-alias, caught separately)
    src = '''
import threading

class Aliased:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
    def a(self):
        with self._lock:
            pass
    def b(self):
        with self._cond:
            pass
'''
    assert not analyze_source(src).by_code("PWA101")


# ---------------------------------------------------------------------------
# PWA102 — unbounded waits
# ---------------------------------------------------------------------------

_WAITS = '''
import threading
import queue

class W:
    def __init__(self):
        self.cv = threading.Condition()
        self.done = threading.Event()
        self.q = queue.Queue()
    def bad_cv(self):
        with self.cv:
            self.cv.wait()
    def good_cv(self):
        with self.cv:
            self.cv.wait(timeout=0.5)
    def bad_queue(self):
        return self.q.get()
    def good_event(self):
        return self.done.wait(5.0)
    def bad_local(self):
        flag = threading.Event()
        flag.wait()
'''


def test_pwa102_untimed_waits_flagged():
    report = analyze_source(_WAITS)
    lines = sorted(d.line for d in report.by_code("PWA102"))
    assert len(lines) == 3, report.to_json()
    for d in report.by_code("PWA102"):
        assert d.severity == Severity.ERROR


def test_pwa102_queue_get_block_flag_is_not_a_timeout():
    # `q.get(True)` is the BLOCK flag — still an unbounded wait; only the
    # second positional (or timeout=) bounds it
    src = '''
import queue

class Q:
    def __init__(self):
        self.q = queue.Queue()
    def bad(self):
        return self.q.get(True)
    def good(self):
        return self.q.get(True, 5.0)
    def also_good(self):
        return self.q.get(block=True, timeout=5.0)
'''
    report = analyze_source(src)
    lines = sorted(d.line for d in report.by_code("PWA102"))
    assert len(lines) == 1, report.to_json()


def test_pwa102_cross_class_event_receiver():
    src = '''
import threading

class _Req:
    def __init__(self):
        self.event = threading.Event()

class Submitter:
    def submit(self, req):
        req.event.wait()
'''
    found = analyze_source(src).by_code("PWA102")
    assert found and found[0].details["primitive"] == "event"


def test_pwa102_ambiguous_attr_name_quiet():
    # `cv` is also assigned a non-primitive somewhere: the terminal-attribute
    # heuristic must not assume the receiver is the threading one
    src = '''
import threading

class RealCv:
    def __init__(self):
        self.cv = threading.Condition()

class ModelCv:
    def __init__(self, sched):
        self.cv = sched.condition()

class User:
    def go(self, thing):
        thing.cv.wait()
'''
    assert not analyze_source(src).by_code("PWA102")


# ---------------------------------------------------------------------------
# PWA103 — unlocked shared writes
# ---------------------------------------------------------------------------

_UNLOCKED = '''
import threading

class Counter:
    def __init__(self):
        self.lk = threading.Lock()
        self.count = 0
        self._wire()
    def _wire(self):
        self.count = 0
    def inc(self):
        with self.lk:
            self.count += 1
    def reset(self):
        self.count = 0
'''


def test_pwa103_inconsistent_lock_flagged_ctor_exempt():
    report = analyze_source(_UNLOCKED)
    found = report.by_code("PWA103")
    # reset() is flagged; __init__ and _wire (reachable only from __init__)
    # are exempt — no peer thread exists during construction
    assert len(found) == 1, report.to_json()
    assert found[0].details["attr"] == "count"
    assert "reset" in (found[0].function or "")


def test_pwa103_escaped_method_not_exempt():
    src = _UNLOCKED.replace(
        "self._wire()",
        "self._wire()\n        self.t = threading.Thread(target=self._wire, daemon=True)",
    )
    report = analyze_source(src)
    # _wire escapes as a thread target: its unlocked write is now flagged too
    assert len(report.by_code("PWA103")) == 2, report.to_json()


def test_pwa103_single_owner_attr_quiet():
    src = '''
import threading

class SingleOwner:
    def __init__(self):
        self.lk = threading.Lock()
        self.stats = 0
    def a(self):
        self.stats += 1
    def b(self):
        self.stats -= 1
'''
    # never written under a lock anywhere: a single-owner convention, not an
    # inconsistency — quiet
    assert not analyze_source(src).by_code("PWA103")


def test_pwa103_noqa_suppresses_with_reason():
    suppressed = _UNLOCKED.replace(
        "self.count = 0\n",
        "self.count = 0  # noqa: PWA103 (stats are advisory)\n",
    )
    assert not analyze_source(suppressed).by_code("PWA103")


# ---------------------------------------------------------------------------
# PWA104 — thread lifecycle
# ---------------------------------------------------------------------------


def test_pwa104_leaky_thread_flagged():
    src = '''
import threading

def leaky():
    t = threading.Thread(target=print)
    t.start()
'''
    found = analyze_source(src).by_code("PWA104")
    assert found and found[0].severity == Severity.ERROR


def test_pwa104_unrelated_join_does_not_mask_sibling_leak():
    # join/daemon attribution is per-variable for named threads: joining the
    # reader must not excuse the never-joined non-daemon flusher
    src = '''
import threading

def teardown():
    reader = threading.Thread(target=print)
    flusher = threading.Thread(target=print)
    reader.start()
    flusher.start()
    reader.join(timeout=5)
'''
    found = analyze_source(src).by_code("PWA104")
    assert len(found) == 1, [d.to_dict() for d in found]


def test_crashed_pass_reports_warning_not_clean():
    from pathway_tpu.analysis.concurrency import ConcurrencyPass, analyze_runtime

    class Exploder(ConcurrencyPass):
        code = "PWA101"

        def run(self, ctx):
            raise RuntimeError("parser changed under me")

    report = analyze_runtime(passes=[Exploder()])
    # a pass that silently checks nothing must not report the tree CLEAN:
    # exit 1 (2 under --strict) so CI sees the lost coverage
    assert report.exit_code() == 1
    assert report.exit_code(strict=True) == 2
    assert "NOT being checked" in report.warnings[0].message


def test_pwa104_daemon_join_and_late_daemon_quiet():
    src = '''
import threading

def daemonized():
    t = threading.Thread(target=print, daemon=True)
    t.start()

def joined():
    ts = [threading.Thread(target=print) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=5)

def late_daemon():
    t = threading.Thread(target=print)
    t.daemon = True
    t.start()
'''
    assert not analyze_source(src).by_code("PWA104")


# ---------------------------------------------------------------------------
# the tree gate (acceptance: zero PWA101-104 errors on the runtime)
# ---------------------------------------------------------------------------


def test_runtime_tree_is_clean():
    report = analyze_runtime()
    assert report.exit_code() == 0, report.to_json()
    assert not report.errors, report.to_json()


def test_runtime_lock_graph_sees_cross_module_edges():
    # the analysis is only trustworthy if it actually SEES the runtime's lock
    # nesting: the telemetry stage-counter lock taken under exchange/cache
    # locks must appear as edges (and form no cycle)
    ctx = build_runtime_context()
    edges = LockOrderPass().build_graph(ctx)
    idents = {(a, b) for (a, b) in edges}
    assert ("ClusterExchange._cv", "telemetry._stage_lock") in idents, sorted(idents)
    assert ("EmbedCache._lock", "telemetry._stage_lock") in idents, sorted(idents)


def test_runtime_modules_all_present():
    missing = [
        rel for rel in RUNTIME_MODULES if not os.path.exists(os.path.join(REPO, rel))
    ]
    assert not missing, f"RUNTIME_MODULES entries vanished: {missing}"


# ---------------------------------------------------------------------------
# cli analyze --runtime: exit-code contract + telemetry
# ---------------------------------------------------------------------------


def _cli_env():
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def test_cli_analyze_runtime_gate():
    proc = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "analyze", "--runtime",
         "--format", "json"],
        capture_output=True,
        text=True,
        env=_cli_env(),
        timeout=120,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout[proc.stdout.index("{"):])
    assert payload["summary"]["errors"] == 0, proc.stdout
    assert "PWA101" in payload["summary"]["pass_seconds"]
    assert "PWA104" in payload["summary"]["pass_seconds"]


def test_cli_analyze_runtime_rejects_program_argument():
    # `analyze --runtime my_graph.py` exiting 0 with the program never linted
    # would be a silent CI hole
    proc = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "analyze", "--runtime",
         "prog.py"],
        capture_output=True,
        text=True,
        env=_cli_env(),
        timeout=60,
        cwd=REPO,
    )
    assert proc.returncode != 0
    assert "takes no PROGRAM" in proc.stderr


def test_cli_analyze_requires_program_without_runtime():
    proc = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "analyze"],
        capture_output=True,
        text=True,
        env=_cli_env(),
        timeout=60,
        cwd=REPO,
    )
    assert proc.returncode != 0
    assert "PROGRAM is required" in proc.stderr


def test_runtime_lint_gate_modes(monkeypatch):
    from pathway_tpu.analysis import concurrency
    from pathway_tpu.analysis.framework import AnalysisReport, GraphLintError
    from pathway_tpu.analysis.concurrency import runtime_gate

    planted = analyze_source(_INVERSION)  # before patching: it delegates
    assert planted.errors
    # off (default): no analysis happens at all
    monkeypatch.delenv("PATHWAY_RUNTIME_LINT", raising=False)
    monkeypatch.setattr(
        concurrency, "analyze_runtime", lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("analyzed despite off")
        )
    )
    runtime_gate()
    # error mode with a planted error report: refuses
    monkeypatch.setattr(concurrency, "_cached_report", planted)
    monkeypatch.setenv("PATHWAY_RUNTIME_LINT", "error")
    try:
        runtime_gate()
        raise AssertionError("runtime_gate did not refuse")
    except GraphLintError as exc:
        assert isinstance(exc.report, AnalysisReport)
    # warn mode logs but does not refuse
    monkeypatch.setenv("PATHWAY_RUNTIME_LINT", "warn")
    runtime_gate()


def test_runtime_gate_rides_pw_run_and_clean_tree_passes_error_mode(monkeypatch):
    import pathway_tpu as pw
    from pathway_tpu.engine import telemetry

    # error mode on a CLEAN tree must not refuse the run (and must run even
    # with the graph lint disabled — independent knobs)
    monkeypatch.setenv("PATHWAY_RUNTIME_LINT", "error")
    monkeypatch.setenv("PATHWAY_LINT", "off")
    telemetry.stage_reset("lint.")
    t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,)])
    got = []
    pw.io.subscribe(t, lambda key, row, time, is_addition: got.append(row["v"]))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert got == [1]
    counters = telemetry.stage_snapshot("lint.")
    assert counters.get("lint.runs", 0) >= 1, counters


def test_runtime_report_telemetry_counters():
    from pathway_tpu.engine import telemetry

    telemetry.stage_reset("lint.")
    report = analyze_source(_INVERSION)
    report.emit_telemetry()
    counters = telemetry.stage_snapshot("lint.")
    assert counters.get("lint.diag.PWA101", 0) >= 1, counters
    assert counters.get("lint.errors", 0) >= 1, counters
