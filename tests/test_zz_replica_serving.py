"""Replica serving plane: strict OpenMetrics + the Retry-After audit.

``zz``-parked like ``test_zz_brownout_serving.py``: these tests start live
HTTP servers (the replica serving endpoint) whose handler threads are
daemons — running them LAST keeps any lingering accept loop from shadowing
earlier modules' socket assertions. Nothing here is slow; it is ordering
hygiene, not cost.

Two satellites live here:

- **strict OpenMetrics over ``replica.*``** — the live replica ``/metrics``
  exposition passes the same strict grammar validator the worker plane
  does, including the ``replica.*`` stage-counter family and the
  ``pathway_replica_staleness_seconds`` / ``pathway_replica_failover_seconds``
  histograms (observations forced first, so the families are PRESENT, not
  vacuously absent);
- **the Retry-After audit** — every shed path in the tree (REST overload,
  quiesce, replica staleness) formats its ``Retry-After`` through
  ``engine/brownout.py:retry_after_int`` and the result parses as an
  RFC-9110 base-10 non-negative integer under adversarial inputs.
"""

import json
import os
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

from pathway_tpu.engine.brownout import BrownoutState, retry_after_int
from pathway_tpu.ops.knn import BruteForceKnnIndex
from pathway_tpu.parallel.replica import (
    ReplicaFollower,
    ReplicaRouter,
    ReplicaServer,
    default_index_factory,
)
from pathway_tpu.persistence.replica_feed import ReplicaFeed

from .utils import validate_openmetrics

pytestmark = [pytest.mark.replicas, pytest.mark.telemetry]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8

_INTEGER = re.compile(r"[0-9]+")


# -- satellite: the Retry-After audit ------------------------------------------


def test_retry_after_int_is_rfc9110_integer():
    """Adversarial sweep: whatever a shed-path estimator produces, the
    header value is a base-10 non-negative integer (no float, no sign, no
    units), at least 1 (a 0 invites an instant re-hammer), at most 3600 (a
    shed is a backoff hint, not a ban)."""
    adversarial = [
        0, 0.0, -0.0, 0.0001, 0.3, 0.999, 1, 1.0, 1.2, 2, 7.5, 59.01,
        3599.2, 3600, 3600.5, 1e9, float("inf"), float("nan"), -5, -0.3,
        None, "garbage", "12.5",
    ]
    for value in adversarial:
        out = retry_after_int(value)
        assert isinstance(out, str)
        assert _INTEGER.fullmatch(out), f"{value!r} -> {out!r}"
        assert 1 <= int(out) <= 3600, f"{value!r} -> {out!r}"
    # rounds UP, never down: a client told 0.3s that retries at 0s hammers
    # the very queue the shed protects
    assert retry_after_int(0.3) == "1"
    assert retry_after_int(1.0) == "1"
    assert retry_after_int(1.2) == "2"
    assert retry_after_int(59.01) == "60"
    assert retry_after_int("12.5") == "13"
    # degenerate estimators shed "momentarily", capped estimators stay sane
    for bad in (float("nan"), -5, None, "garbage"):
        assert retry_after_int(bad) == "1"
    for huge in (1e9, float("inf"), 3601):
        assert retry_after_int(huge) == "3600"


def test_every_retry_after_header_routes_through_the_one_formatter():
    """Source audit: every ``"Retry-After":`` header CONSTRUCTION in
    ``pathway_tpu/`` calls ``retry_after_int`` on the same line — there is
    exactly one formatter, so a new shed path cannot silently ship a float
    or negative header. (Reads of the header — the router parsing a shed
    response — are exempt.)"""
    sites = []
    for dirpath, _, filenames in os.walk(os.path.join(REPO, "pathway_tpu")):
        for filename in filenames:
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            with open(path, "r", encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if '"Retry-After":' in line:
                        sites.append((os.path.relpath(path, REPO), lineno, line))
    assert sites, "the shed paths vanished? expected Retry-After emitters"
    offenders = [
        (path, lineno)
        for path, lineno, line in sites
        if "retry_after_int(" not in line
    ]
    assert not offenders, (
        f"Retry-After headers built without retry_after_int: {offenders} — "
        "route them through engine/brownout.py:retry_after_int"
    )
    # all three shed paths are represented: REST (overload + quiesce), replica
    files = {path for path, _, _ in sites}
    assert any("io/http/_server.py" in p for p in files)
    assert any("parallel/replica.py" in p for p in files)


def test_each_shed_path_estimate_parses_as_integer(tmp_path):
    """Per-path leg of the audit: drive each shed path's LIVE estimator
    (quiesce remaining-pause, REST overload retry callable, replica
    staleness backlog) through the formatter and parse the result."""
    # 1. quiesce: a membership transition's expected remaining pause
    brownout = BrownoutState(enabled=True)
    brownout.enter_quiesce(expected_s=2.5)
    quiesce_s = brownout.quiesce_retry_after()
    assert quiesce_s is not None and quiesce_s > 0
    assert _INTEGER.fullmatch(retry_after_int(quiesce_s))
    brownout.exit_quiesce()
    assert brownout.quiesce_retry_after() is None

    # 2. REST overload: whatever the pipeline's retry callable estimates
    # (including the degenerate "estimator raised -> 1.0s" fallback)
    for estimate in (0.05, 3.7, 120.0):
        assert _INTEGER.fullmatch(retry_after_int(estimate))

    # 3. replica staleness: poll cadence x pending backlog
    primary = BruteForceKnnIndex(DIM)
    primary.add_many(["a", "b"], np.eye(2, DIM, dtype=np.float32))
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    follower = ReplicaFollower(feed, default_index_factory, poll_s=0.07)
    follower.bootstrap()
    for commit in (2, 3, 4, 5):
        feed.record_commit(
            commit, [f"c{commit}"], np.ones((1, DIM), dtype=np.float32)
        )
    estimate = follower.retry_estimate_s()
    assert estimate == pytest.approx(0.07 * 5)
    assert _INTEGER.fullmatch(retry_after_int(estimate))


# -- satellite: strict OpenMetrics over the replica plane ----------------------


def test_live_replica_metrics_pass_strict_openmetrics(tmp_path):
    """Serve, shed, fail over — then scrape the LIVE replica ``/metrics``
    through the strict validator and assert the replica families and the
    ``replica.*`` stage counters are present with the traffic just driven."""

    class Clock:
        t = 100.0

        def __call__(self):
            return self.t

    clock = Clock()
    rng = np.random.default_rng(0)
    primary = BruteForceKnnIndex(DIM)
    primary.add_many(
        [f"k{i}" for i in range(8)],
        rng.normal(size=(8, DIM)).astype(np.float32),
    )
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(1, primary)
    follower = ReplicaFollower(feed, default_index_factory, clock=clock)
    follower.bootstrap()
    # a poll observes the staleness histogram; a frame bumps frames_applied
    feed.record_commit(2, ["z"], rng.normal(size=(1, DIM)).astype(np.float32))
    assert follower.poll_frames() == 1
    server = ReplicaServer(follower)
    try:
        url = f"http://127.0.0.1:{server.port}"
        query = {"vectors": [[0.0] * DIM], "k": 2}

        def post(payload):
            req = urllib.request.Request(
                f"{url}/v1/retrieve",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())

        assert post(query)["commit"] == 2  # replica.serve
        clock.t += 9.0
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            post({**query, "max_staleness_s": 0.5})  # replica.shed_stale
        assert exc_info.value.code == 429
        assert _INTEGER.fullmatch(exc_info.value.headers["Retry-After"])

        # a router walk over one dead endpoint observes the failover
        # histogram and the replica.router.* counters
        router = ReplicaRouter(
            ["http://127.0.0.1:9", url], timeout_s=10.0
        )
        router._rr = 0  # start on the dead endpoint: forced failover
        commit, _ = router.retrieve(query["vectors"], 2)
        assert commit == 2
        assert router.stats["failovers"] == 1

        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )
            text = resp.read().decode()
    finally:
        server.close()

    families = validate_openmetrics(text)
    # replica-level gauges/counters with the traffic just driven
    assert families["pathway_replica_applied_commit"]["type"] == "gauge"
    assert families["pathway_replica_applied_commit"]["samples"][0][2] == 2.0
    assert families["pathway_replica_staleness_current_seconds"]["type"] == "gauge"
    assert families["pathway_replica_served"]["samples"][0][0].endswith("_total")
    assert families["pathway_replica_served"]["samples"][0][2] >= 1.0
    assert families["pathway_replica_shed"]["samples"][0][2] >= 1.0
    # the shared metrics plane carries the replica.* stage family
    stages = {
        labels["stage"]: value
        for name, labels, value in families["pathway_stage"]["samples"]
    }
    for stage in (
        "replica.bootstraps",
        "replica.serve",
        "replica.shed_stale",
        "replica.frames_applied",
        "replica.polls",
        "replica.router.served",
        "replica.router.failover",
        "replica.router.unhealthy",
    ):
        assert stages.get(stage, 0.0) >= 1.0, f"stage {stage} missing: {sorted(stages)}"
    # both replica histograms are live OpenMetrics histogram families
    for hist in (
        "pathway_replica_staleness_seconds",
        "pathway_replica_failover_seconds",
    ):
        family = families[hist]
        assert family["type"] == "histogram", hist
        names = {name for name, _, _ in family["samples"]}
        assert f"{hist}_bucket" in names
        assert f"{hist}_count" in names and f"{hist}_sum" in names
        count = [
            value
            for name, _, value in family["samples"]
            if name == f"{hist}_count"
        ][0]
        assert count >= 1.0, f"{hist} never observed"


def test_healthz_staleness_tracks_the_metrics_gauge(tmp_path):
    """The ``/healthz`` JSON and the ``/metrics`` gauge are two views of ONE
    snapshot: same applied commit, consistent staleness."""
    rng = np.random.default_rng(1)
    primary = BruteForceKnnIndex(DIM)
    primary.add_many(["a", "b", "c"], rng.normal(size=(3, DIM)).astype(np.float32))
    feed = ReplicaFeed(str(tmp_path / "feed"))
    feed.export_bootstrap(4, primary)
    follower = ReplicaFollower(feed, default_index_factory)
    follower.bootstrap()
    server = ReplicaServer(follower)
    try:
        url = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{url}/healthz", timeout=10) as resp:
            health = json.loads(resp.read())
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
            families = validate_openmetrics(resp.read().decode())
        assert health["applied_commit"] == 4
        assert (
            families["pathway_replica_applied_commit"]["samples"][0][2] == 4.0
        )
        gauge = families["pathway_replica_staleness_current_seconds"]["samples"][0][2]
        assert gauge >= 0.0 and gauge < 60.0  # fresh, finite
        assert health["staleness_s"] is not None
    finally:
        server.close()
