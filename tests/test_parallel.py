"""Multi-device tests on the virtual 8-device CPU mesh (conftest sets
``xla_force_host_platform_device_count=8``): mesh construction, ring attention vs the
single-device oracle, sharded-KNN parity with the dense store, the TP+DP train step, and
the key-hash exchange."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pathway_tpu.ops.knn import DenseKNNStore
from pathway_tpu.parallel import (
    ShardedKNNStore,
    exchange_by_key,
    make_mesh,
    mesh_shape_for,
)


def test_mesh_shape_factorization():
    assert mesh_shape_for(8) == (2, 4)
    assert mesh_shape_for(4) == (1, 4)
    assert mesh_shape_for(8, model_parallel=2) == (4, 2)
    assert mesh_shape_for(1) == (1, 1)


def test_make_mesh_axes():
    mesh = make_mesh(8)
    assert mesh.shape == {"data": 2, "model": 4}


def test_sharded_knn_matches_dense():
    mesh = make_mesh(8)
    rng = np.random.default_rng(1)
    dim, n, q, k = 32, 100, 7, 5
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    queries = rng.normal(size=(q, dim)).astype(np.float32)
    dense = DenseKNNStore(dim, metric="l2sq", initial_capacity=128)
    sharded = ShardedKNNStore(mesh, dim, metric="l2sq", initial_capacity=128)
    for i in range(n):
        dense.add(i, vecs[i])
        sharded.add(i, vecs[i])
    ds, di, _ = dense.search_batch(queries, k)
    ss, si, sv = sharded.search_batch(queries, k)
    assert sv.all()
    np.testing.assert_allclose(ss, ds, atol=1e-4)
    # same neighbor KEYS (slot numbering differs between the two stores)
    for row in range(q):
        dense_keys = {dense.key_of[int(j)] for j in di[row]}
        sharded_keys = {sharded.key_of[int(j)] for j in si[row]}
        assert sharded_keys == dense_keys


def test_sharded_knn_remove_and_grow():
    mesh = make_mesh(8)
    rng = np.random.default_rng(2)
    dim = 16
    store = ShardedKNNStore(mesh, dim, metric="ip", initial_capacity=8)
    vecs = rng.normal(size=(40, dim)).astype(np.float32)
    for i in range(40):  # forces growth past 8
        store.add(i, vecs[i])
    for i in range(0, 40, 2):
        store.remove(i)
    scores, idx, valid = store.search_batch(vecs[:3], k=4)
    for row in range(3):
        for j, ok in zip(idx[row], valid[row]):
            if ok:
                assert store.key_of[int(j)] % 2 == 1  # evens were removed


def test_exchange_by_key_routes_to_owner():
    mesh = make_mesh(8, model_parallel=1)  # data=8
    n = 64
    rng = np.random.default_rng(4)
    key_lo = jnp.asarray(rng.integers(0, 2**62, size=(n,)), dtype=jnp.uint64)
    values = jnp.asarray(np.arange(n, dtype=np.float32))
    out_vals, out_valid = exchange_by_key(mesh, key_lo, values, capacity=n)
    out_vals = np.asarray(out_vals)
    out_valid = np.asarray(out_valid)
    # every input row arrives exactly once, on the shard owning its key
    received = sorted(out_vals[out_valid].tolist())
    assert received == sorted(np.asarray(values).tolist())
    owners = np.asarray(key_lo & np.uint64(7), dtype=np.int64)
    rows_per_shard = len(out_valid) // 8
    for i in np.nonzero(out_valid)[0]:
        shard = i // rows_per_shard
        val = int(out_vals[i])
        assert owners[val] == shard


def test_sharded_segment_sum_matches_host():
    from pathway_tpu.parallel.groupby_sharded import sharded_segment_sum

    mesh = make_mesh(8, model_parallel=1)
    rng = np.random.default_rng(7)
    n, m = 203, 13  # deliberately not divisible by the shard count
    key_lo = rng.integers(0, 1 << 30, n).astype(np.uint64)
    seg = rng.integers(0, m, n)
    vals = rng.normal(size=n).astype(np.float32)
    got = sharded_segment_sum(mesh, key_lo, seg, vals, m)
    want = np.zeros(m, dtype=np.float64)
    np.add.at(want, seg, vals.astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_engine_groupby_rides_mesh_exchange():
    """A grouped sum through pw.run routes its segment reduction over the mesh."""
    import pathway_tpu as pw
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.ops import segment as segment_mod
    from pathway_tpu.parallel.groupby_sharded import sharded_segment_sum as real_impl
    from pathway_tpu.parallel import groupby_sharded
    from pathway_tpu.parallel.mesh import set_default_mesh

    calls = []

    def spy(*args, **kwargs):
        calls.append(1)
        return real_impl(*args, **kwargs)

    mesh = make_mesh(8, model_parallel=1)
    set_default_mesh(mesh)
    old_threshold = segment_mod.MESH_THRESHOLD
    segment_mod.MESH_THRESHOLD = 0
    groupby_sharded.sharded_segment_sum = spy
    try:
        pg.G.clear()
        rng = np.random.default_rng(3)
        gids = rng.integers(0, 5, 200)
        vals = rng.normal(size=200).astype(np.float32)
        tbl = pw.debug.table_from_rows(
            pw.schema_builder({"g": int, "v": float}),
            [(int(g), float(v)) for g, v in zip(gids, vals)],
        )
        out = tbl.groupby(pw.this.g).reduce(pw.this.g, total=pw.reducers.sum(pw.this.v))
        got = {}
        pw.io.subscribe(
            out,
            lambda key, row, time, is_addition: got.__setitem__(row["g"], row["total"])
            if is_addition
            else None,
        )
        GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
        assert calls, "mesh exchange path was not taken"
        for g in range(5):
            want = float(vals[gids == g].sum())
            assert abs(got[g] - want) < 1e-3 * max(1.0, abs(want))
    finally:
        groupby_sharded.sharded_segment_sum = real_impl
        segment_mod.MESH_THRESHOLD = old_threshold
        set_default_mesh(None)
        pg.G.clear()


def test_engine_external_index_uses_sharded_store():
    """Table -> KNN index -> query through pw.run picks ShardedKNNStore on a mesh."""
    import pathway_tpu as pw
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.parallel.mesh import set_default_mesh
    from pathway_tpu.stdlib.ml.index import KNNIndex

    mesh = make_mesh(8, model_parallel=1)
    set_default_mesh(mesh)
    try:
        pg.G.clear()
        rng = np.random.default_rng(0)
        dim, n_docs = 8, 64
        vecs = rng.normal(size=(n_docs, dim)).astype(np.float32)
        data = pw.debug.table_from_rows(
            pw.schema_builder({"doc": str, "vec": np.ndarray}),
            [(f"doc{i}", vecs[i]) for i in range(n_docs)],
        )
        q = pw.debug.table_from_rows(
            pw.schema_builder({"qvec": np.ndarray}), [(vecs[9],)]
        )
        res = KNNIndex(data.vec, data, n_dimensions=dim).get_nearest_items(q.qvec, k=3)
        rows = []
        pw.io.subscribe(
            res,
            lambda key, row, time, is_addition: rows.append(row)
            if is_addition
            else None,
        )
        runner = GraphRunner(pg.G._current)
        runner.run(monitoring_level=pw.MonitoringLevel.NONE)
        assert rows and rows[0]["doc"][0] == "doc9"
        # the engine's external-index evaluator must actually hold the sharded store
        from pathway_tpu.engine.evaluators import ExternalIndexEvaluator
        from pathway_tpu.parallel.knn_sharded import ShardedKNNStore as SKS

        stores = [
            ev.index.store
            for ev in runner.evaluators.values()
            if isinstance(ev, ExternalIndexEvaluator)
        ]
        assert stores and all(isinstance(s, SKS) for s in stores)
    finally:
        set_default_mesh(None)
        pg.G.clear()
