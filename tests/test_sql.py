"""pw.sql parser/planner matrix (reference ``internals/sql.py`` over sqlglot:
joins, subqueries, HAVING, UNION — VERDICT r2 item 10)."""

from __future__ import annotations

import pathway_tpu as pw

from .utils import T, capture_rows


def _rows(table, names):
    from .utils import _norm

    return sorted(
        (tuple(_norm(r[c]) for c in names) for r in capture_rows(table)), key=repr
    )


def _users():
    return T(
        """
        uid | name  | age
        1   | alice | 30
        2   | bob   | 25
        3   | carol | 35
        """
    )


def _orders():
    return T(
        """
        oid | user_id | total
        10  | 1       | 100
        11  | 1       | 50
        12  | 2       | 75
        13  | 9       | 20
        """
    )


def test_sql_inner_join_with_aliases():
    res = pw.sql(
        "SELECT u.name, o.total FROM users u JOIN orders o ON u.uid = o.user_id",
        users=_users(),
        orders=_orders(),
    )
    assert _rows(res, ["name", "total"]) == sorted(
        [("alice", 100), ("alice", 50), ("bob", 75)], key=repr
    )


def test_sql_left_join_pads_nulls():
    res = pw.sql(
        "SELECT u.name, o.total FROM users u LEFT JOIN orders o ON u.uid = o.user_id",
        users=_users(),
        orders=_orders(),
    )
    assert _rows(res, ["name", "total"]) == sorted(
        [("alice", 100), ("alice", 50), ("bob", 75), ("carol", None)], key=repr
    )


def test_sql_join_group_by_having():
    res = pw.sql(
        "SELECT u.name, SUM(o.total) AS spent FROM users u "
        "JOIN orders o ON u.uid = o.user_id GROUP BY u.name HAVING SUM(o.total) > 60",
        users=_users(),
        orders=_orders(),
    )
    assert _rows(res, ["name", "spent"]) == sorted(
        [("alice", 150), ("bob", 75)], key=repr
    )


def test_sql_join_residual_on_condition():
    res = pw.sql(
        "SELECT u.name, o.total FROM users u JOIN orders o "
        "ON u.uid = o.user_id AND o.total > 60",
        users=_users(),
        orders=_orders(),
    )
    assert _rows(res, ["name", "total"]) == sorted(
        [("alice", 100), ("bob", 75)], key=repr
    )


def test_sql_subquery_in_from():
    res = pw.sql(
        "SELECT name FROM (SELECT name, age FROM users WHERE age > 26) grown "
        "WHERE grown.age < 34",
        users=_users(),
    )
    assert _rows(res, ["name"]) == [("alice",)]


def test_sql_subquery_with_aggregation_joined():
    res = pw.sql(
        "SELECT u.name, s.spent FROM users u "
        "JOIN (SELECT user_id, SUM(total) AS spent FROM orders GROUP BY user_id) s "
        "ON u.uid = s.user_id",
        users=_users(),
        orders=_orders(),
    )
    assert _rows(res, ["name", "spent"]) == sorted(
        [("alice", 150), ("bob", 75)], key=repr
    )


def test_sql_union_all_and_union_distinct():
    a = T(
        """
        v
        1
        2
        """
    )
    b = T(
        """
        v
        2
        3
        """
    )
    res_all = pw.sql("SELECT v FROM a UNION ALL SELECT v FROM b", a=a, b=b)
    assert _rows(res_all, ["v"]) == [(1,), (2,), (2,), (3,)]

    import pathway_tpu.internals.parse_graph as pg

    pg.G.clear()
    a2 = T("""
        v
        1
        2
        """)
    b2 = T("""
        v
        2
        3
        """)
    res_distinct = pw.sql("SELECT v FROM a UNION SELECT v FROM b", a=a2, b=b2)
    assert _rows(res_distinct, ["v"]) == [(1,), (2,), (3,)]


def test_sql_distinct():
    t = T(
        """
        color
        red
        red
        blue
        """
    )
    res = pw.sql("SELECT DISTINCT color FROM t", t=t)
    assert _rows(res, ["color"]) == [("blue",), ("red",)]


def test_sql_predicates_in_between_like_null():
    t = T(
        """
        name  | age
        alice | 30
        bob   | 25
        carol |
        dave  | 40
        """
    )
    res = pw.sql("SELECT name FROM t WHERE age IN (25, 40)", t=t)
    assert _rows(res, ["name"]) == [("bob",), ("dave",)]
    import pathway_tpu.internals.parse_graph as pg

    pg.G.clear()
    t = T("""
        name  | age
        alice | 30
        bob   | 25
        carol |
        dave  | 40
        """)
    res = pw.sql("SELECT name FROM t WHERE age BETWEEN 26 AND 40", t=t)
    assert _rows(res, ["name"]) == [("alice",), ("dave",)]

    pg.G.clear()
    t = T("""
        name  | age
        alice | 30
        bob   | 25
        carol |
        dave  | 40
        """)
    res = pw.sql("SELECT name FROM t WHERE age IS NULL", t=t)
    assert _rows(res, ["name"]) == [("carol",)]

    pg.G.clear()
    t = T("""
        name  | age
        alice | 30
        bob   | 25
        carol |
        dave  | 40
        """)
    res = pw.sql("SELECT name FROM t WHERE name LIKE 'a%' OR name LIKE '%ve'", t=t)
    assert _rows(res, ["name"]) == [("alice",), ("dave",)]

    pg.G.clear()
    t = T("""
        name  | age
        alice | 30
        bob   | 25
        dave  | 40
        """)
    res = pw.sql("SELECT name FROM t WHERE NOT (age > 26) OR age NOT BETWEEN 0 AND 35", t=t)
    assert _rows(res, ["name"]) == [("bob",), ("dave",)]


def test_sql_count_star_and_expressions():
    t = T(
        """
        grp | v
        a   | 1
        a   | 2
        b   | 5
        """
    )
    res = pw.sql(
        "SELECT grp, COUNT(*) AS n, SUM(v) + 1 AS s1 FROM t GROUP BY grp", t=t
    )
    assert _rows(res, ["grp", "n", "s1"]) == sorted(
        [("a", 2, 4), ("b", 1, 6)], key=repr
    )


def test_sql_ambiguous_column_errors():
    import pytest

    a = T("""
        v
        1
        """)
    b = T("""
        v
        2
        """)
    with pytest.raises(ValueError, match="ambiguous"):
        pw.sql("SELECT v FROM a JOIN b ON a.v = b.v", a=a, b=b)


def test_sql_star_select_through_join():
    res = pw.sql(
        "SELECT * FROM users u JOIN orders o ON u.uid = o.user_id WHERE o.total > 90",
        users=_users(),
        orders=_orders(),
    )
    rows = capture_rows(res)
    assert len(rows) == 1
    row = rows[0]
    assert row["name"] == "alice" and row["total"] == 100 and row["oid"] == 10
