"""Resource-lifecycle & exception-contract lint (analysis/resources.py):
planted golden violations per pass (PWA201 acquire/release incl. the
interprocedural release-via-helper corner, PWA202 typed-error swallowing,
PWA203 write-only state with the ctor exemption, PWA204 finally masking,
PWA205 telemetry drift), noqa suppression, the clean-tree gate, the
``cli analyze --runtime`` fold-in with per-pass ``checked`` flags, telemetry
mirroring through the OpenMetrics grammar, the knob-drift audit, and one-line
regressions for the findings this PR fixed on the tree."""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import pytest

from pathway_tpu.analysis import (
    RESOURCE_MODULES,
    Severity,
    analyze_resource_source,
    analyze_resources,
    analyze_runtime_full,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# PWA201 — acquire/release pairing
# ---------------------------------------------------------------------------

_LEAK = '''
import socket

class Wiring:
    def leak(self):
        s = socket.socket()
        s.connect(("127.0.0.1", 1))
        s.close()

    def ok_finally(self):
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", 1))
        finally:
            s.close()

    def ok_with(self):
        with open("f") as f:
            return f.read()

    def ok_escape(self):
        s = socket.socket()
        return s

    def ok_tail(self):
        f = open("x")
        f.close()
'''


def test_pwa201_unprotected_release_flagged():
    report = analyze_resource_source(_LEAK)
    found = report.by_code("PWA201")
    assert len(found) == 1, report.to_json()
    d = found[0]
    assert d.severity == Severity.ERROR
    assert "leak" in (d.function or "")
    assert d.details["resource"] == "socket"


def test_pwa201_release_via_helper_interprocedural():
    # the class-attr corner: the socket is released only inside a teardown
    # helper (called from a finally elsewhere) — the pass must find the
    # release THROUGH the helper, not demand a literal close at the acquire
    src = '''
import socket

class Held:
    def start(self):
        self.sock = socket.socket()
        try:
            self.sock.connect(("127.0.0.1", 1))
        finally:
            self._teardown()

    def _teardown(self):
        self.sock.close()
'''
    assert not analyze_resource_source(src).by_code("PWA201")


def test_pwa201_class_attr_without_releaser_flagged():
    src = '''
import socket

class NeverClosed:
    def start(self):
        self.sock = socket.socket()
'''
    found = analyze_resource_source(src).by_code("PWA201")
    assert found and found[0].details["attr"] == "sock"


def test_pwa201_alias_swap_release_found():
    # the idempotent-close idiom: `h, self.h = self.h, None` then h.close()
    src = '''
import socket

class Swapped:
    def start(self):
        self.sock = socket.socket()

    def close(self):
        sock, self.sock = self.sock, None
        sock.close()
'''
    assert not analyze_resource_source(src).by_code("PWA201")


def test_pwa201_slot_store_without_finally_pop_flagged():
    src = '''
class Handler:
    def __init__(self):
        self.futures = {}

    def serve(self, key, fut):
        self.futures[key] = fut
        result = self.await_it(fut)
        self.futures.pop(key, None)
        return result

    def await_it(self, fut):
        return fut
'''
    found = analyze_resource_source(src).by_code("PWA201")
    assert found, "success-only slot pop must be flagged"
    assert found[0].details["container"] == "futures"
    fixed = src.replace(
        "        result = self.await_it(fut)\n"
        "        self.futures.pop(key, None)\n"
        "        return result",
        "        try:\n"
        "            return self.await_it(fut)\n"
        "        finally:\n"
        "            self.futures.pop(key, None)",
    )
    assert not analyze_resource_source(fixed).by_code("PWA201")


def test_pwa201_noqa_suppresses_with_reason():
    suppressed = _LEAK.replace(
        "        s = socket.socket()\n        s.connect",
        "        s = socket.socket()  # noqa: PWA201 (probe socket, process-lifetime)\n"
        "        s.connect",
    )
    assert not analyze_resource_source(suppressed).by_code("PWA201")


# ---------------------------------------------------------------------------
# PWA202 — typed-error swallowing
# ---------------------------------------------------------------------------

_SWALLOW = '''
class PeerGoneError(ConnectionError):
    pass

class Loop:
    def commit(self):
        try:
            self.exchange()
        except Exception:
            pass

    def exchange(self):
        raise PeerGoneError("peer died")
'''


def test_pwa202_typed_swallow_flagged_interprocedurally():
    report = analyze_resource_source(_SWALLOW)
    found = report.by_code("PWA202")
    assert len(found) == 1, report.to_json()
    assert found[0].severity == Severity.ERROR
    assert "PeerGoneError" in found[0].message


def test_pwa202_isinstance_triage_and_reraise_quiet():
    triaged = _SWALLOW.replace(
        "        except Exception:\n            pass",
        "        except Exception as exc:\n"
        "            if isinstance(exc, PeerGoneError):\n"
        "                raise\n"
        "            pass",
    )
    assert not analyze_resource_source(triaged).by_code("PWA202")


def test_pwa202_specific_handler_before_broad_quiet():
    narrowed = _SWALLOW.replace(
        "        except Exception:\n            pass",
        "        except PeerGoneError:\n"
        "            raise\n"
        "        except Exception:\n"
        "            pass",
    )
    assert not analyze_resource_source(narrowed).by_code("PWA202")


def test_pwa202_capture_for_transfer_quiet():
    # a worker-thread handler that SHIPS the exception to its waiters is not
    # swallowing it (the coalescer/encoder-service propagate pattern)
    shipped = _SWALLOW.replace(
        "        except Exception:\n            pass",
        "        except Exception as exc:\n            self.error = exc",
    )
    assert not analyze_resource_source(shipped).by_code("PWA202")


def test_pwa202_log_and_continue_is_still_a_swallow():
    # capture-for-transfer means STORING the exception for another consumer;
    # logging it (or `msg = str(exc)` into a local) is log-and-continue —
    # exactly the fence-wedging swallow the pass exists to catch
    logged = _SWALLOW.replace(
        "        except Exception:\n            pass",
        "        except Exception as exc:\n"
        "            import logging\n"
        '            logging.warning("failed: %s", exc)',
    )
    assert analyze_resource_source(logged).by_code("PWA202")
    localed = _SWALLOW.replace(
        "        except Exception:\n            pass",
        "        except Exception as exc:\n            msg = str(exc)",
    )
    assert analyze_resource_source(localed).by_code("PWA202")


def test_pwa202_base_exception_flagged_even_without_typed_raise():
    src = '''
class Quiet:
    def go(self):
        try:
            print("x")
        except BaseException:
            pass
'''
    found = analyze_resource_source(src).by_code("PWA202")
    assert found and "GraphCaptureInterrupt" in found[0].message


def test_pwa202_noqa_suppresses():
    suppressed = _SWALLOW.replace(
        "        except Exception:",
        "        except Exception:  # noqa: PWA202 (commit loop absorbs, fence retries)",
    )
    assert not analyze_resource_source(suppressed).by_code("PWA202")


# ---------------------------------------------------------------------------
# PWA203 — write-only / dead attribute state
# ---------------------------------------------------------------------------

_DEAD = '''
class Tracker:
    def __init__(self):
        self.parked = {}
        self.config = 7

    def park(self, rank, cont):
        self.parked[rank] = cont
'''


def test_pwa203_write_only_attr_flagged_ctor_exempt():
    report = analyze_resource_source(_DEAD)
    found = report.by_code("PWA203")
    # `parked` is written in park() and read nowhere; `config` is only
    # written in the constructor (exempt — external readers are likely)
    assert len(found) == 1, report.to_json()
    assert found[0].details["attr"] == "parked"
    assert found[0].severity == Severity.WARNING


def test_pwa203_read_anywhere_quiet():
    read = _DEAD + '''
class Restorer:
    def restore(self, tracker, rank):
        return tracker.parked.get(rank)
'''
    assert not analyze_resource_source(read).by_code("PWA203")


def test_pwa203_noqa_suppresses_with_reason():
    suppressed = _DEAD.replace(
        "        self.parked[rank] = cont",
        "        self.parked[rank] = cont  # noqa: PWA203 (read by the joiner via snapshot)",
    )
    assert not analyze_resource_source(suppressed).by_code("PWA203")


# ---------------------------------------------------------------------------
# PWA204 — exception-masking finally
# ---------------------------------------------------------------------------


def test_pwa204_raise_and_return_in_finally_flagged():
    src = '''
class Cleanup:
    def masks_with_raise(self):
        try:
            self.work()
        finally:
            raise RuntimeError("cleanup failed")

    def masks_with_return(self):
        try:
            self.work()
        finally:
            return None

    def work(self):
        pass
'''
    report = analyze_resource_source(src)
    found = report.by_code("PWA204")
    assert len(found) == 2, report.to_json()
    assert all(d.severity == Severity.ERROR for d in found)


def test_pwa204_typed_raising_call_in_finally_flagged_guard_quiet():
    src = '''
class FenceError(ConnectionError):
    pass

class Teardown:
    def bad(self):
        try:
            pass
        finally:
            self.release()

    def good(self):
        try:
            pass
        finally:
            try:
                self.release()
            except Exception as exc:
                self.last_error = exc

    def release(self):
        raise FenceError("peer gone")
'''
    report = analyze_resource_source(src)
    found = report.by_code("PWA204")
    assert len(found) == 1, report.to_json()
    assert "FenceError" in found[0].message
    assert "bad" in (found[0].function or "")


# ---------------------------------------------------------------------------
# PWA205 — telemetry-contract drift
# ---------------------------------------------------------------------------


def test_pwa205_unregistered_namespace_flagged():
    src = '''
from pathway_tpu.engine import telemetry

class Stage:
    def go(self):
        telemetry.stage_add("bogus.counter")
        telemetry.stage_add("cluster.fine")
        with telemetry.stage_timer("embed.also_fine"):
            pass
'''
    report = analyze_resource_source(src)
    found = report.by_code("PWA205")
    assert len(found) == 1, report.to_json()
    assert found[0].details["stage"] == "bogus.counter"


def test_pwa205_add_many_dict_keys_and_fstring_heads_checked():
    src = '''
from pathway_tpu.engine import telemetry

class Stage:
    def go(self, peer, kind):
        telemetry.stage_add_many({
            "exchange.barriers": 1.0,
            f"forked.peer{peer}.bytes": 2.0,
        })
        telemetry.stage_add(f"cluster.{kind}")
'''
    report = analyze_resource_source(src)
    found = report.by_code("PWA205")
    assert len(found) == 1, report.to_json()
    assert found[0].details["stage"].startswith("forked.")


def test_pwa205_truncated_complete_literal_flagged():
    # a COMPLETE literal must carry a full registered prefix — "clu" would
    # fork from /metrics even though "cluster." starts with it; only an
    # f-string HEAD may be shorter than its namespace (the tail is dynamic)
    src = '''
from pathway_tpu.engine import telemetry

class S:
    def go(self, x):
        telemetry.stage_add("clu")
        telemetry.stage_add(f"embed{x}")
'''
    found = analyze_resource_source(src).by_code("PWA205")
    assert [d.details["stage"] for d in found] == ["clu"]


def test_pwa205_add_many_via_local_dict_checked():
    src = '''
from pathway_tpu.engine import telemetry

class Stage:
    def go(self, n):
        updates = {"exchange.barriers": 1.0}
        updates[f"offbrand.peer{n}"] = 1.0
        telemetry.stage_add_many(updates)
'''
    found = analyze_resource_source(src).by_code("PWA205")
    assert len(found) == 1 and found[0].details["stage"].startswith("offbrand.")


def test_pwa205_unknown_flight_event_kind_flagged():
    src = '''
from pathway_tpu.engine.profile import get_flight_recorder

class Ev:
    def go(self):
        get_flight_recorder().record_event("fence")
        get_flight_recorder().record_event("surprise_event", detail=1)
'''
    found = analyze_resource_source(src).by_code("PWA205")
    assert len(found) == 1 and found[0].details["event"] == "surprise_event"


def test_pwa205_unknown_trace_span_kind_flagged():
    # span kinds are a closed set (telemetry.TRACE_SPAN_KINDS): the trace
    # merger and critical-path analysis key on them, so an off-registry
    # literal in trace_span()/start()/record_span() is flagged; variable
    # kinds and registered literals stay quiet
    src = '''
from pathway_tpu.engine.tracing import get_tracer, trace_span

class Sp:
    def go(self, kind):
        with trace_span("rest", "GET /v1/retrieve"):
            pass
        with get_tracer().trace_span("made_up_kind", "oops"):
            pass
        span = get_tracer().start("barrier", "b")
        with trace_span(kind):
            pass
'''
    found = analyze_resource_source(src).by_code("PWA205")
    assert len(found) == 1, [d.message for d in found]
    assert found[0].details["span_kind"] == "made_up_kind"


def test_pwa205_registry_has_no_ghost_namespaces():
    # the registry itself can drift: every registered namespace must still
    # have at least one live mention in the analyzed tree, or the registry
    # documents ghosts
    from pathway_tpu.engine.telemetry import STAGE_NAMESPACES

    joined = ""
    for rel in RESOURCE_MODULES + ("pathway_tpu/analysis/framework.py",):
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                joined += f.read()
    dead = [ns for ns in STAGE_NAMESPACES if ns not in joined]
    assert not dead, f"registered but unused namespaces: {dead}"


# ---------------------------------------------------------------------------
# the tree gate (acceptance: zero PWA201-205 errors on the runtime)
# ---------------------------------------------------------------------------


def test_resource_tree_is_clean():
    report = analyze_resources()
    assert report.exit_code() == 0, report.to_json()
    assert not report.errors, report.to_json()


def test_runtime_full_tree_is_clean_and_all_passes_checked():
    report = analyze_runtime_full()
    assert report.exit_code() == 0, report.to_json()
    for code in ("PWA101", "PWA102", "PWA103", "PWA104",
                 "PWA201", "PWA202", "PWA203", "PWA204", "PWA205"):
        assert report.pass_checked.get(code) is True, report.pass_checked


def test_resource_modules_all_present():
    missing = [
        rel for rel in RESOURCE_MODULES if not os.path.exists(os.path.join(REPO, rel))
    ]
    assert not missing, f"RESOURCE_MODULES entries vanished: {missing}"


def test_crashed_resource_pass_reports_warning_and_unchecked():
    from pathway_tpu.analysis.resources import ResourcePass

    class Exploder(ResourcePass):
        code = "PWA203"

        def run(self, ctx):
            raise RuntimeError("parser changed under me")

    report = analyze_resources(passes=[Exploder()])
    assert report.exit_code() == 1
    assert report.exit_code(strict=True) == 2
    assert "NOT being checked" in report.warnings[0].message
    assert report.pass_checked == {"PWA203": False}
    assert json.loads(report.to_json())["summary"]["checked"] == {"PWA203": False}


# ---------------------------------------------------------------------------
# regressions for the findings this PR fixed on today's tree
# ---------------------------------------------------------------------------


def _src(rel: str) -> str:
    with open(os.path.join(REPO, rel), "r", encoding="utf-8") as f:
        return f.read()


def test_fixed_dead_state_stays_dead():
    # each was a PWA203 finding: write-only state deleted (or wired) in this PR
    assert "_membership_target" not in _src("pathway_tpu/parallel/cluster.py")
    assert "_fusion_plan" not in _src("pathway_tpu/engine/runner.py")
    assert "_ckpt_attempts" not in _src("pathway_tpu/engine/runner.py")
    assert "self._source = source" not in _src("pathway_tpu/io/http/_server.py")


def test_model_counters_are_wired_into_invariants():
    # `installed`/`stale_dropped` were write-only model state; now invariants
    src = _src("pathway_tpu/internals/protocol_models.py")
    assert "assert surv.installed" in src
    assert "surv.stale_dropped ==" in src or "+ surv.stale_dropped" in src


def test_healthz_probe_triages_typed_peer_errors():
    """A probe aborted by the epoch fence reports state=fencing (recoverable
    protocol state), not a generic degradation."""
    import urllib.request

    from pathway_tpu.engine.http_server import MonitoringServer, ProberStats
    from pathway_tpu.parallel.cluster import ClusterFenceError

    server = MonitoringServer(ProberStats(), 0)

    def fencing_source():
        raise ClusterFenceError("peer 1 died; fencing at epoch 3")

    server.health_source = fencing_source
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=5
        ) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read())
    finally:
        server.close()
    assert payload["state"] == "fencing"
    assert "epoch 3" in payload["error"]


def test_retrying_store_does_not_retry_not_found():
    """A not-found raised by an inner client is definitive: the retry wrapper
    must surface it immediately instead of burning the whole backoff budget."""
    from pathway_tpu.persistence.backends import ObjectStore, RetryingObjectStore

    calls = {"n": 0}

    class NotFoundStore(ObjectStore):
        def get(self, key):
            calls["n"] += 1
            raise FileNotFoundError(key)

    store = RetryingObjectStore(NotFoundStore())
    with pytest.raises(FileNotFoundError):
        store.get("absent")
    assert calls["n"] == 1, f"not-found was retried {calls['n']} times"


def test_retrying_store_still_retries_transient():
    from pathway_tpu.persistence.backends import ObjectStore, RetryingObjectStore

    calls = {"n": 0}

    class Transient(Exception):
        pass

    class FlakyStore(ObjectStore):
        def get(self, key):
            calls["n"] += 1
            if calls["n"] < 3:
                raise Transient("throttled")
            return b"ok"

    store = RetryingObjectStore(FlakyStore())
    assert store.get("k") == b"ok"
    assert calls["n"] == 3


# ---------------------------------------------------------------------------
# cli analyze --runtime: the fold-in + checked field
# ---------------------------------------------------------------------------


def _cli_env():
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def test_cli_analyze_runtime_includes_resource_passes():
    proc = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "analyze", "--runtime",
         "--format", "json"],
        capture_output=True,
        text=True,
        env=_cli_env(),
        timeout=180,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout[proc.stdout.index("{"):])
    assert payload["summary"]["errors"] == 0, proc.stdout
    for code in ("PWA101", "PWA201", "PWA202", "PWA203", "PWA204", "PWA205"):
        assert code in payload["summary"]["pass_seconds"], payload["summary"]
        assert payload["summary"]["checked"][code] is True, payload["summary"]


def test_resource_gate_modes(monkeypatch):
    from pathway_tpu.analysis import resources
    from pathway_tpu.analysis.framework import AnalysisReport, GraphLintError
    from pathway_tpu.analysis.resources import resource_gate

    planted = analyze_resource_source(_SWALLOW)
    assert planted.errors
    # off (default): no analysis happens at all
    monkeypatch.delenv("PATHWAY_RESOURCE_LINT", raising=False)
    monkeypatch.setattr(
        resources, "analyze_resources", lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("analyzed despite off")
        )
    )
    resource_gate()
    # error mode with a planted error report: refuses
    monkeypatch.setattr(resources, "_cached_report", planted)
    monkeypatch.setenv("PATHWAY_RESOURCE_LINT", "error")
    with pytest.raises(GraphLintError) as exc_info:
        resource_gate()
    assert isinstance(exc_info.value.report, AnalysisReport)
    # warn mode logs but does not refuse
    monkeypatch.setenv("PATHWAY_RESOURCE_LINT", "warn")
    resource_gate()


def test_resource_report_telemetry_counters_and_grammar():
    """lint.diag.PWA20x counters ride the stage counters and survive the
    strict OpenMetrics line grammar on /metrics."""
    from pathway_tpu.engine import telemetry
    from pathway_tpu.engine.http_server import ProberStats

    from .utils import validate_openmetrics

    telemetry.stage_reset("lint.")
    report = analyze_resource_source(_SWALLOW)
    report.emit_telemetry()
    counters = telemetry.stage_snapshot("lint.")
    assert counters.get("lint.diag.PWA202", 0) >= 1, counters
    assert counters.get("lint.errors", 0) >= 1, counters
    text = ProberStats().to_openmetrics()
    validate_openmetrics(text)
    assert 'pathway_stage_total{stage="lint.diag.PWA202"}' in text


# ---------------------------------------------------------------------------
# knob-drift audit: code PATHWAY_* reads <-> README env-knob tables
# ---------------------------------------------------------------------------

_KNOB_RE = re.compile(r"PATHWAY_[A-Z0-9_]*[A-Z0-9]")


def _code_knobs() -> set:
    out = set()
    for base, dirs, files in os.walk(os.path.join(REPO, "pathway_tpu")):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(base, name), "r", encoding="utf-8") as f:
                out.update(_KNOB_RE.findall(f.read()))
    with open(os.path.join(REPO, "bench.py"), "r", encoding="utf-8") as f:
        out.update(_KNOB_RE.findall(f.read()))
    return out


def test_env_knobs_match_readme_tables():
    """The env-knob tables grew by hand across 13 PRs: every PATHWAY_* the
    code reads must appear in README.md, and every documented knob must still
    exist in code — else the docs describe a ghost."""
    with open(os.path.join(REPO, "README.md"), "r", encoding="utf-8") as f:
        documented = set(_KNOB_RE.findall(f.read()))
    in_code = _code_knobs()
    undocumented = sorted(in_code - documented)
    assert not undocumented, (
        f"PATHWAY_* knobs read in code but absent from every README table: "
        f"{undocumented} — add them to the README env-knob (or internal "
        "wiring) table"
    )
    dead = sorted(documented - in_code)
    assert not dead, (
        f"README documents knobs no code reads: {dead} — delete the rows or "
        "restore the knobs"
    )


def test_b904_raise_from_discipline_holds_without_ruff():
    """ruff.toml carries B904, but this container may not ship a ruff binary:
    the AST fallback keeps the raise-from discipline enforced either way."""
    import ast

    hits = []
    for base, dirs, files in os.walk(os.path.join(REPO, "pathway_tpu")):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(base, name)
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), path)
            for node in ast.walk(tree):
                if isinstance(node, ast.ExceptHandler):
                    for sub in ast.walk(node):
                        if (
                            isinstance(sub, ast.Raise)
                            and sub.exc is not None
                            and sub.cause is None
                        ):
                            hits.append(f"{os.path.relpath(path, REPO)}:{sub.lineno}")
    assert not hits, (
        f"raise without `from` inside except (B904): {hits} — chain the cause "
        "(`from exc`) or sever it explicitly (`from None`)"
    )


# ---------------------------------------------------------------------------
# dynamic leak oracle: the PWA201 model proven against the live runtime
# ---------------------------------------------------------------------------

_ORACLE_PROG = """
import json, os
import pathway_tpu as pw

tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

class WordSchema(pw.Schema):
    word: str

t = pw.io.fs.read(
    os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="static"
)
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[row["word"]] = int(row["total"])
    else:
        rows.pop(row["word"], None)

pw.io.subscribe(counts, on_change)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
with open(os.path.join(tmp, f"out_{pid}.json"), "w") as f:
    json.dump(rows, f)
"""


def test_leak_oracle_around_n2_spawn_acceptance(tmp_path, leak_oracle):
    """The acceptance: an n=2 spawn run completes bit-exactly AND leaves this
    process with zero fd/socket/thread growth (the oracle fixture asserts the
    growth half after the test body)."""
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "a.csv").write_text("word\nalpha\nbeta\nalpha\n")
    (tmp_path / "in" / "b.csv").write_text("word\nbeta\ngamma\nbeta\n")
    prog = tmp_path / "prog.py"
    prog.write_text(_ORACLE_PROG)
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(26000 + os.getpid() % 500 * 4),
            sys.executable, str(prog),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, f"spawn failed:\nstdout={out.stdout}\nstderr={out.stderr}"
    merged: dict = {}
    for p in range(2):
        merged.update(json.loads((tmp_path / f"out_{p}.json").read_text()))
    assert merged == {"alpha": 2, "beta": 3, "gamma": 1}


def test_leak_oracle_around_in_process_run_with_monitoring(leak_oracle):
    """An in-process run with the monitoring HTTP server live ALONGSIDE it
    must tear down the listener socket and serving threads completely once
    closed — the leaked-listener class PWA201 models for
    MonitoringServer.close (the server serves a real request mid-run, so a
    half-closed accept thread would show up as a leaked thread/socket)."""
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.engine.http_server import MonitoringServer, ProberStats

    server = MonitoringServer(ProberStats(), 0)
    try:
        t = pw.debug.table_from_rows(pw.schema_builder({"v": int}), [(1,), (2,)])
        got = []
        pw.io.subscribe(t, lambda key, row, time, is_addition: got.append(row["v"]))
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ) as resp:
            assert resp.status == 200
    finally:
        server.close()
    assert sorted(got) == [1, 2]
