"""Connector tests: debezium CDC parsing, REST-based sinks against a local fake server,
postgres statement generation, namespace surface parity."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals.parse_graph import G


def test_io_namespace_surface():
    # the reference exposes 27 connector namespaces (io/__init__.py:3-30)
    for name in [
        "airbyte", "bigquery", "csv", "debezium", "deltalake", "elasticsearch",
        "fs", "gdrive", "http", "jsonlines", "kafka", "logstash", "minio",
        "mongodb", "nats", "null", "plaintext", "postgres", "pubsub",
        "pyfilesystem", "python", "redpanda", "s3", "s3_csv", "slack", "sqlite",
    ]:
        assert hasattr(pw.io, name), name
    assert callable(pw.io.subscribe)


def test_debezium_parse_envelope():
    from pathway_tpu.io.debezium import parse_debezium_message

    cols = ["id", "name"]
    create = {"payload": {"op": "c", "before": None, "after": {"id": 1, "name": "a"}}}
    update = {"payload": {"op": "u", "before": {"id": 1, "name": "a"}, "after": {"id": 1, "name": "b"}}}
    delete = {"payload": {"op": "d", "before": {"id": 1, "name": "b"}, "after": None}}
    assert parse_debezium_message(create, cols) == [({"id": 1, "name": "a"}, 1)]
    assert parse_debezium_message(json.dumps(update), cols) == [
        ({"id": 1, "name": "a"}, -1),
        ({"id": 1, "name": "b"}, 1),
    ]
    assert parse_debezium_message(delete, cols) == [({"id": 1, "name": "b"}, -1)]
    # mongo variant: before/after as embedded JSON strings
    mongo = {"payload": {"op": "c", "after": json.dumps({"id": 2, "name": "m"})}}
    assert parse_debezium_message(mongo, cols) == [({"id": 2, "name": "m"}, 1)]


def test_debezium_stream_through_engine():
    schema = pw.schema_builder(
        {"id": pw.column_definition(dtype=int, primary_key=True), "name": str}
    )
    messages = [
        {"payload": {"op": "c", "after": {"id": 1, "name": "a"}}},
        {"payload": {"op": "c", "after": {"id": 2, "name": "x"}}},
        {"payload": {"op": "u", "before": {"id": 1, "name": "a"}, "after": {"id": 1, "name": "b"}}},
        {"payload": {"op": "d", "before": {"id": 2, "name": "x"}}},
    ]
    t = pw.io.debezium.read_from_iterable(messages, schema=schema)
    rows = dbg.table_to_pandas(t, include_id=False).to_dict("records")
    assert sorted((r["id"], r["name"]) for r in rows) == [(1, "b")]


class _FakeHTTP:
    """Captures POSTed bodies; returns 200 with {"ok": true}."""

    def __init__(self):
        captured = self.captured = []

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                captured.append((self.path, self.rfile.read(length)))
                body = b'{"ok": true}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _run_table():
    return pw.debug.table_from_markdown(
        """
        word  | n
        cat   | 1
        dog   | 2
        """
    )


def test_elasticsearch_bulk_sink():
    server = _FakeHTTP()
    try:
        t = _run_table()
        pw.io.elasticsearch.write(
            t,
            f"http://127.0.0.1:{server.port}",
            auth=pw.io.elasticsearch.ElasticSearchAuth.basic("u", "p"),
            index_name="idx",
        )
        GraphRunner(G._current).run()
    finally:
        server.close()
    assert server.captured, "no bulk request sent"
    path, body = server.captured[0]
    assert path == "/_bulk"
    lines = [json.loads(line) for line in body.decode().strip().split("\n")]
    actions = [entry["index"]["_index"] for entry in lines[::2]]
    docs = lines[1::2]
    assert actions == ["idx", "idx"]
    assert sorted(d["word"] for d in docs) == ["cat", "dog"]


def test_logstash_sink():
    server = _FakeHTTP()
    try:
        t = _run_table()
        pw.io.logstash.write(t, f"http://127.0.0.1:{server.port}/")
        GraphRunner(G._current).run()
    finally:
        server.close()
    docs = [json.loads(body) for _path, body in server.captured]
    assert sorted(d["word"] for d in docs) == ["cat", "dog"]
    assert all(d["diff"] == 1 for d in docs)


def test_slack_sink():
    server = _FakeHTTP()
    try:
        t = _run_table()
        pw.io.slack.send_alerts(
            t.word, "C123", "xoxb-token", api_url=f"http://127.0.0.1:{server.port}/api"
        )
        GraphRunner(G._current).run()
    finally:
        server.close()
    docs = [json.loads(body) for _path, body in server.captured]
    assert sorted(d["text"] for d in docs) == ["cat", "dog"]
    assert all(d["channel"] == "C123" for d in docs)


def test_postgres_statement_generation():
    from pathway_tpu.io.postgres import snapshot_statement, updates_statement

    sql, params = updates_statement("t", {"word": "cat", "n": 1}, 4, 1)
    assert sql == "INSERT INTO t (word, n, time, diff) VALUES (%s, %s, %s, %s)"
    assert params == ["cat", 1, 4, 1]

    sql, params = snapshot_statement("t", ["word"], {"word": "cat", "n": 2}, 6, 1)
    # snapshot inserts carry (time, diff) like the reference PsqlSnapshot format
    assert "ON CONFLICT (word) DO UPDATE SET n=EXCLUDED.n" in sql
    assert "time=EXCLUDED.time" in sql and "diff=EXCLUDED.diff" in sql
    assert params == ["cat", 2, 6, 1]

    sql, params = snapshot_statement("t", ["word"], {"word": "cat", "n": 2}, 6, -1)
    assert sql == "DELETE FROM t WHERE word=%s"
    assert params == ["cat"]


def test_gated_connectors_raise_clearly():
    t = _run_table()
    with pytest.raises(ImportError):
        pw.io.mongodb.write(t, "mongodb://x", "db", "coll")
    with pytest.raises(ImportError):
        pw.io.deltalake.write(t, "/tmp/dl")
    with pytest.raises(FileNotFoundError):
        # airbyte is a real protocol runner now; a missing config fails upfront
        pw.io.airbyte.read("conn.yaml", ["users"])
    with pytest.raises(ImportError):
        pw.io.postgres.write(t, {"host": "x"}, "t")
