"""Temporal-join semantics matrix (reference ``tests/temporal/test_interval_joins.py``,
``test_window_joins.py``, ``test_asof_joins.py``): randomized brute-force oracles across
join modes x bounds x sharding x dtype, plus hand-pinned reference cases (asof full with
two-sided defaults, session window joins over concatenated sides)."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.joins import JoinKind

from .utils import T, assert_table_equality_wo_index, capture_rows


def _rows_multiset(rows: list[dict], names: list[str]) -> list[tuple]:
    from .utils import _norm

    return sorted((tuple(_norm(r[c]) for c in names) for r in rows), key=repr)


MODES = [JoinKind.INNER, JoinKind.LEFT, JoinKind.RIGHT, JoinKind.OUTER]


def _expected_pairs(
    lts: list, rts: list, lo, hi, lkeys=None, rkeys=None
) -> list[tuple]:
    """Brute-force interval-join oracle over (time, key) rows."""
    out = []
    matched_l: set = set()
    matched_r: set = set()
    for i, lt in enumerate(lts):
        for j, rt in enumerate(rts):
            if lkeys is not None and lkeys[i] != rkeys[j]:
                continue
            if lo <= rt - lt <= hi:
                out.append((lt, rt))
                matched_l.add(i)
                matched_r.add(j)
    return out, matched_l, matched_r


def _run_interval_case(seed: int, mode: JoinKind, lo, hi, sharded: bool, floats: bool):
    rng = np.random.default_rng(seed)
    nl, nr = 17, 13
    if floats:
        lts = np.round(rng.uniform(0, 10, nl), 2).tolist()
        rts = np.round(rng.uniform(0, 10, nr), 2).tolist()
    else:
        lts = rng.integers(0, 12, nl).tolist()
        rts = rng.integers(0, 12, nr).tolist()
    lkeys = rng.integers(0, 3, nl).tolist() if sharded else None
    rkeys = rng.integers(0, 3, nr).tolist() if sharded else None

    pg.G.clear()
    if sharded:
        left = pw.debug.table_from_rows(
            pw.schema_builder({"t": float if floats else int, "k": int}),
            list(zip(lts, lkeys)),
        )
        right = pw.debug.table_from_rows(
            pw.schema_builder({"t2": float if floats else int, "k2": int}),
            list(zip(rts, rkeys)),
        )
        res = left.interval_join(
            right, left.t, right.t2, pw.temporal.interval(lo, hi), left.k == right.k2,
            how=mode,
        ).select(lt=left.t, rt=right.t2)
    else:
        left = pw.debug.table_from_rows(
            pw.schema_builder({"t": float if floats else int}), [(t,) for t in lts]
        )
        right = pw.debug.table_from_rows(
            pw.schema_builder({"t2": float if floats else int}), [(t,) for t in rts]
        )
        res = left.interval_join(
            right, left.t, right.t2, pw.temporal.interval(lo, hi), how=mode
        ).select(lt=left.t, rt=right.t2)
    got = _rows_multiset(capture_rows(res), ["lt", "rt"])

    pairs, matched_l, matched_r = _expected_pairs(lts, rts, lo, hi, lkeys, rkeys)
    want = list(pairs)
    if mode in (JoinKind.LEFT, JoinKind.OUTER):
        want += [(lts[i], None) for i in range(nl) if i not in matched_l]
    if mode in (JoinKind.RIGHT, JoinKind.OUTER):
        want += [(None, rts[j]) for j in range(nr) if j not in matched_r]
    assert got == sorted(want, key=repr), (
        f"seed={seed} mode={mode} lo={lo} hi={hi} sharded={sharded} floats={floats}\n"
        f"got  {got}\nwant {sorted(want, key=repr)}"
    )


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("bounds", [(-2, 2), (0, 3), (-3, -1), (1, 4), (0, 0)])
def test_interval_join_modes_bounds(mode, bounds):
    _run_interval_case(1, mode, bounds[0], bounds[1], sharded=False, floats=False)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
@pytest.mark.parametrize("mode", MODES)
def test_interval_join_sharded_oracle(seed, mode):
    _run_interval_case(seed, mode, -2, 1, sharded=True, floats=False)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("mode", [JoinKind.INNER, JoinKind.OUTER])
def test_interval_join_float_oracle(seed, mode):
    _run_interval_case(seed, mode, -0.5, 0.75, sharded=False, floats=True)


def test_interval_join_non_overlapping_outer():
    pg.G.clear()
    left = pw.debug.table_from_rows(pw.schema_builder({"t": int}), [(0,), (1,)])
    right = pw.debug.table_from_rows(pw.schema_builder({"t2": int}), [(100,), (200,)])
    res = left.interval_join_outer(
        right, left.t, right.t2, pw.temporal.interval(-1, 1)
    ).select(lt=left.t, rt=right.t2)
    got = _rows_multiset(capture_rows(res), ["lt", "rt"])
    assert got == sorted(
        [(0, None), (1, None), (None, 100), (None, 200)], key=repr
    )


def test_interval_join_expressions_and_select():
    """Output expressions combining both sides (reference
    test_interval_inner_join_expressions)."""
    pg.G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"t": int, "a": int}), [(1, 10), (4, 40), (7, 70)]
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"t2": int, "b": int}), [(2, 1), (5, 2), (11, 3)]
    )
    res = left.interval_join_inner(
        right, left.t, right.t2, pw.temporal.interval(0, 2)
    ).select(s=left.a + right.b, d=right.t2 - left.t)
    got = _rows_multiset(capture_rows(res), ["s", "d"])
    assert got == sorted([(11, 1), (42, 1)], key=repr)


# -- window joins ----------------------------------------------------------------


def _window_of(t, duration, hop):
    """All (start, end) windows containing t for a sliding(hop, duration) window."""
    import math

    out = []
    b = math.floor(t / hop)
    # scan a safe range of window starts
    for k in range(b - int(duration / hop) - 2, b + 2):
        start = k * hop
        if start <= t < start + duration:
            out.append((start, start + duration))
    return out


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("win", [("tumbling", 3, 3), ("sliding", 4, 2)])
def test_window_join_oracle(mode, win):
    _kind, duration, hop = win
    rng = np.random.default_rng(5)
    lts = rng.integers(0, 15, 14).tolist()
    rts = rng.integers(0, 15, 11).tolist()
    pg.G.clear()
    left = pw.debug.table_from_rows(pw.schema_builder({"t": int}), [(t,) for t in lts])
    right = pw.debug.table_from_rows(pw.schema_builder({"t2": int}), [(t,) for t in rts])
    w = (
        pw.temporal.tumbling(duration=duration)
        if _kind == "tumbling"
        else pw.temporal.sliding(hop=hop, duration=duration)
    )
    res = left.window_join(right, left.t, right.t2, w, how=mode).select(
        lt=left.t, rt=right.t2
    )
    got = _rows_multiset(capture_rows(res), ["lt", "rt"])

    # oracle: each (row, window) pair is an entity; join within (window)
    lwin = [(t, wnd) for t in lts for wnd in _window_of(t, duration, hop)]
    rwin = [(t, wnd) for t in rts for wnd in _window_of(t, duration, hop)]
    pairs = []
    matched_l, matched_r = set(), set()
    for i, (lt, wl) in enumerate(lwin):
        for j, (rt, wr) in enumerate(rwin):
            if wl == wr:
                pairs.append((lt, rt))
                matched_l.add(i)
                matched_r.add(j)
    want = list(pairs)
    if mode in (JoinKind.LEFT, JoinKind.OUTER):
        want += [(lwin[i][0], None) for i in range(len(lwin)) if i not in matched_l]
    if mode in (JoinKind.RIGHT, JoinKind.OUTER):
        want += [(None, rwin[j][0]) for j in range(len(rwin)) if j not in matched_r]
    assert got == sorted(want, key=repr), f"mode={mode} win={win}"


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("use_predicate", [False, True])
def test_session_window_join_concatenated_sides(mode, use_predicate):
    """Sessions form over BOTH sides' times: left 1,2 and right 3 chain into one
    session with max_gap=2 even though neither side alone spans it (reference
    ``_window_join.py:174-179``)."""
    pg.G.clear()
    left = pw.debug.table_from_rows(pw.schema_builder({"t": int}), [(1,), (2,), (10,)])
    right = pw.debug.table_from_rows(pw.schema_builder({"t2": int}), [(3,), (20,)])
    w = (
        pw.temporal.session(predicate=lambda a, b: abs(a - b) <= 2)
        if use_predicate
        else pw.temporal.session(max_gap=2)
    )
    res = left.window_join(right, left.t, right.t2, w, how=mode).select(
        lt=left.t, rt=right.t2
    )
    got = _rows_multiset(capture_rows(res), ["lt", "rt"])
    # session 1: {1,2,3}; session 2: {10}; session 3: {20}
    want = [(1, 3), (2, 3)]
    if mode in (JoinKind.LEFT, JoinKind.OUTER):
        want += [(10, None)]
    if mode in (JoinKind.RIGHT, JoinKind.OUTER):
        want += [(None, 20)]
    assert got == sorted(want, key=repr), f"mode={mode}"


def test_session_window_join_sharded():
    pg.G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"t": int, "k": int}), [(1, 0), (2, 1), (3, 0)]
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"t2": int, "k2": int}), [(2, 0), (3, 1), (9, 0)]
    )
    res = left.window_join_inner(
        right, left.t, right.t2, pw.temporal.session(max_gap=1), left.k == right.k2
    ).select(lt=left.t, rt=right.t2, k=left.k)
    got = _rows_multiset(capture_rows(res), ["lt", "rt", "k"])
    # k=0: union times {1,3}+{2} chain into session {1,2,3} -> left{1,3} x right{2};
    # k=1: {2}+{3} -> left{2} x right{3}
    assert got == sorted([(1, 2, 0), (3, 2, 0), (2, 3, 1)], key=repr)


def test_window_join_window_columns():
    pg.G.clear()
    left = pw.debug.table_from_rows(pw.schema_builder({"t": int}), [(1,), (5,)])
    right = pw.debug.table_from_rows(pw.schema_builder({"t2": int}), [(2,)])
    res = left.window_join_left(
        right, left.t, right.t2, pw.temporal.tumbling(duration=4)
    ).select(lt=left.t, ws=pw.this._pw_window_start)
    got = _rows_multiset(capture_rows(res), ["lt", "ws"])
    assert got == sorted([(1, 0), (5, 4)], key=repr)


# -- asof joins ------------------------------------------------------------------


def test_asof_full_two_sided_defaults():
    """The reference's canonical OUTER asof case (test_asof_full): every record of
    both sides emits once, matched backward against the other side, with per-side
    defaults and pw.this.instance/side/t exposed."""
    pg.G.clear()
    t1 = T(
        """
            | K | val |  t
        1   | 0 | 1   |  1
        2   | 0 | 2   |  4
        3   | 0 | 3   |  5
        4   | 0 | 4   |  6
        5   | 0 | 5   |  7
        6   | 0 | 6   |  11
        7   | 0 | 7   |  12
        8   | 1 | 8   |  5
        9   | 1 | 9   |  7
    """
    )
    t2 = T(
        """
             | K | val | t
        21   | 1 | 7  | 2
        22   | 1 | 3  | 8
        23   | 0 | 0  | 2
        24   | 0 | 6  | 3
        25   | 0 | 2  | 7
        26   | 0 | 3  | 8
        27   | 0 | 9  | 9
        28   | 0 | 7  | 13
        29   | 0 | 4  | 14
        """
    )
    res = t1.asof_join(
        t2,
        t1.t,
        t2.t,
        t1.K == t2.K,
        how=JoinKind.OUTER,
        defaults={t1.val: 0, t2.val: 0},
    ).select(
        pw.this.instance,
        pw.this.side,
        pw.this.t,
        val_v1=t1.val,
        val_v2=t2.val,
        sum=t1.val + t2.val,
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
instance | side  | t  | val_v1 | val_v2 | sum
0        | False | 1  | 1      | 0      | 1
0        | False | 4  | 2      | 6      | 8
0        | False | 5  | 3      | 6      | 9
0        | False | 6  | 4      | 6      | 10
0        | False | 7  | 5      | 6      | 11
0        | False | 11 | 6      | 9      | 15
0        | False | 12 | 7      | 9      | 16
0        | True  | 2  | 1      | 0      | 1
0        | True  | 3  | 1      | 6      | 7
0        | True  | 7  | 5      | 2      | 7
0        | True  | 8  | 5      | 3      | 8
0        | True  | 9  | 5      | 9      | 14
0        | True  | 13 | 7      | 7      | 14
0        | True  | 14 | 7      | 4      | 11
1        | False | 5  | 8      | 7      | 15
1        | False | 7  | 9      | 7      | 16
1        | True  | 2  | 0      | 7      | 7
1        | True  | 8  | 9      | 3      | 12
"""
        ),
    )


def test_asof_left_with_defaults():
    pg.G.clear()
    t1 = T(
        """
        | t | v
      1 | 1 | a
      2 | 5 | b
      3 | 9 | c
    """
    )
    t2 = T(
        """
        | t | val
      1 | 3 | 30
      2 | 7 | 70
    """
    )
    res = t1.asof_join_left(t2, t1.t, t2.t, defaults={t2.val: -1}).select(
        v=t1.v, rv=t2.val
    )
    got = _rows_multiset(capture_rows(res), ["v", "rv"])
    assert got == sorted([("a", -1), ("b", 30), ("c", 70)], key=repr)


def test_asof_right_mode():
    pg.G.clear()
    t1 = T(
        """
        | t | v
      1 | 2 | x
      2 | 6 | y
    """
    )
    t2 = T(
        """
        | t | w
      1 | 1 | p
      2 | 4 | q
      3 | 9 | r
    """
    )
    res = t1.asof_join(t2, t1.t, t2.t, how=JoinKind.RIGHT).select(
        w=t2.w, lv=t1.v, t=pw.this.t
    )
    got = _rows_multiset(capture_rows(res), ["w", "lv", "t"])
    # each right row picks latest left at-or-before: 1->None, 4->x, 9->y
    assert got == sorted([("p", None, 1), ("q", "x", 4), ("r", "y", 9)], key=repr)


@pytest.mark.parametrize(
    "direction,expect",
    [
        (None, [("a", None), ("b", 30), ("c", 70)]),  # BACKWARD: strictly-before
        ("forward", [("a", 30), ("b", 70), ("c", None)]),  # FORWARD: at-or-after
        ("nearest", [("a", 30), ("b", 30), ("c", 70)]),
    ],
)
def test_asof_directions(direction, expect):
    pg.G.clear()
    t1 = T(
        """
        | t | v
      1 | 1 | a
      2 | 5 | b
      3 | 9 | c
    """
    )
    t2 = T(
        """
        | t | val
      1 | 3 | 30
      2 | 7 | 70
    """
    )
    kwargs = {}
    if direction == "forward":
        kwargs["direction"] = pw.temporal.Direction.FORWARD
    elif direction == "nearest":
        kwargs["direction"] = pw.temporal.Direction.NEAREST
    res = t1.asof_join_left(t2, t1.t, t2.t, **kwargs).select(v=t1.v, rv=t2.val)
    got = _rows_multiset(capture_rows(res), ["v", "rv"])
    assert got == sorted(expect, key=repr)


def test_asof_nearest_tie_and_exact():
    pg.G.clear()
    t1 = T(
        """
        | t
      1 | 5
    """
    )
    t2 = T(
        """
        | t | val
      1 | 3 | 1
      2 | 5 | 2
      3 | 8 | 3
    """
    )
    res = t1.asof_join_left(
        t2, t1.t, t2.t, direction=pw.temporal.Direction.NEAREST
    ).select(rv=t2.val)
    assert _rows_multiset(capture_rows(res), ["rv"]) == [(2,)]


def test_asof_multiple_keys():
    pg.G.clear()
    t1 = T(
        """
        | a | b | t | v
      1 | 0 | 0 | 5 | l1
      2 | 0 | 1 | 5 | l2
      3 | 1 | 0 | 5 | l3
    """
    )
    t2 = T(
        """
        | a | b | t | w
      1 | 0 | 0 | 3 | r1
      2 | 0 | 1 | 4 | r2
      3 | 1 | 1 | 2 | r3
    """
    )
    res = t1.asof_join_left(t2, t1.t, t2.t, t1.a == t2.a, t1.b == t2.b).select(
        v=t1.v, w=t2.w
    )
    got = _rows_multiset(capture_rows(res), ["v", "w"])
    assert got == sorted([("l1", "r1"), ("l2", "r2"), ("l3", None)], key=repr)


# -- behavior x interval-join interaction ----------------------------------------


def test_interval_join_with_behavior_cutoff_streaming():
    """Late rows beyond the cutoff are ignored by the join (common_behavior on
    interval_join, reference ``_interval_join.py`` behavior plumbing)."""
    pg.G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"t": int}),
        [(1, 0, 1), (2, 0, 1), (20, 2, 1), (3, 4, 1)],  # t=3 arrives after time 20 seen
        is_stream=True,
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"t2": int}), [(1,), (2,), (3,), (20,)]
    )
    res = left.interval_join_inner(
        right,
        left.t,
        right.t2,
        pw.temporal.interval(0, 0),
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).select(lt=left.t, rt=right.t2)
    got = _rows_multiset(capture_rows(res), ["lt", "rt"])
    # the late t=3 row is past the cutoff (max seen 20, cutoff 2) and is dropped
    assert (3, 3) not in got
    assert (1, 1) in got and (2, 2) in got and (20, 20) in got


def test_interval_join_outer_streaming_null_flip():
    """A late-arriving right row must RETRACT the left row's null output and
    emit the matched pair (the incremental flip obligation of outer temporal
    joins — reference interval_join outer under streaming)."""
    pg.G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"t": int}), [(10, 0, 1)], is_stream=True
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"t2": int, "v": int}),
        [(100, 0, 0, 1), (11, 7, 2, 1)],  # match for t=10 arrives LATER (time 2)
        is_stream=True,
    )
    res = left.interval_join_outer(
        right, left.t, right.t2, pw.temporal.interval(-2, 2)
    ).select(lt=left.t, rv=right.v)
    events = []
    pw.io.subscribe(
        res,
        on_batch=lambda keys, diffs, columns, time: events.extend(
            (time, lt, rv, d)
            for lt, rv, d in zip(
                columns["lt"].tolist(), columns["rv"].tolist(), diffs.tolist()
            )
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # final state: (10, 7) matched and (None, 0) for the unmatched right row
    state = {}
    for _t, lt, rv, d in events:
        state[(lt, rv)] = state.get((lt, rv), 0) + d
    live = sorted((k for k, v in state.items() if v > 0), key=repr)
    assert live == sorted([(10, 7), (None, 0)], key=repr)
    # and the null row (10, None) was emitted then retracted
    assert (10, None) in [(lt, rv) for _t, lt, rv, d in events if d > 0]
    assert (10, None) in [(lt, rv) for _t, lt, rv, d in events if d < 0]


def test_asof_now_join_keeps_first_answers():
    """asof_now joins answer at arrival and never retract, even when the right
    side later changes (reference _asof_now_join.py semantics)."""
    pg.G.clear()
    queries = pw.debug.table_from_rows(
        pw.schema_builder({"q": int}),
        [(1, 2, 1), (2, 6, 1)],
        is_stream=True,
    )
    state = pw.debug.table_from_rows(
        pw.schema_builder({"k": int, "ver": str}),
        # version changes between the two queries
        [(0, "v1", 0, 1), (0, "v1", 4, -1), (0, "v2", 4, 1)],
        is_stream=True,
    )
    res = queries.asof_now_join(state).select(q=queries.q, ver=state.ver)
    events = []
    pw.io.subscribe(
        res,
        on_batch=lambda keys, diffs, columns, time: events.extend(
            zip(columns["q"].tolist(), columns["ver"].tolist(), diffs.tolist())
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert all(d > 0 for _q, _v, d in events)  # never a retraction
    answers = {q: v for q, v, _d in events}
    assert answers == {1: "v1", 2: "v2"}  # each query saw the state AT ARRIVAL
