"""Worker-thread parallelism (``PATHWAY_THREADS``): the transparent shared-graph
lane and the explicit ``run_threads`` lane.

Parity: reference ``src/engine/dataflow/config.rs:63-70`` (N timely worker
threads per process over a shared-memory allocator) and
``external/timely-dataflow/communication/src/initialize.rs:25-31``. Here the
spawn cluster's key-partitioning policies run unchanged over an in-memory
exchange; outputs centralize on rank 0 so results are exactly the
single-thread run's.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.runner import GraphRunner
from pathway_tpu.internals import config as config_mod
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clear_graph():
    G.clear()
    yield
    G.clear()


def _threads_config(n: int, processes: int = 1):
    return config_mod.PathwayConfig(threads=n, processes=processes)


def _collect(table):
    rows = {}
    calls = []

    def cb(key, row, time, is_addition):
        calls.append(threading.get_ident())
        if is_addition:
            rows[key] = row
        else:
            rows.pop(key, None)

    pw.io.subscribe(table, cb)
    return rows, calls


def _run_with_threads(n: int) -> None:
    config_mod.set_thread_config(_threads_config(n))
    try:
        GraphRunner(G._current).run()
    finally:
        config_mod.set_thread_config(None)


def test_shared_graph_wordcount_matches_single_thread():
    t = pw.debug.table_from_markdown(
        """
        word | n
        cat  | 1
        dog  | 2
        cat  | 3
        owl  | 5
        dog  | 1
        """
    )
    out = t.groupby(t.word).reduce(t.word, total=pw.reducers.sum(t.n))
    rows, calls = _collect(out)
    _run_with_threads(3)
    got = sorted((r["word"], r["total"]) for r in rows.values())
    assert got == [("cat", 4), ("dog", 3), ("owl", 5)]
    # outputs centralize on one rank: the callback thread is unique
    assert len(set(calls)) == 1


def test_shared_graph_join_and_filter():
    left = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "v": int}), [(f"k{i}", i) for i in range(60)]
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "w": int}),
        [(f"k{i}", 100 + i) for i in range(0, 60, 2)],
    )
    joined = left.join(right, left.k == right.k).select(
        left.k, s=left.v + right.w
    ).filter(pw.this.s % 2 == 0)
    rows, _ = _collect(joined)
    _run_with_threads(4)
    expected = sorted(
        (f"k{i}", 100 + 2 * i) for i in range(0, 60, 2) if (100 + 2 * i) % 2 == 0
    )
    assert sorted((r["k"], r["s"]) for r in rows.values()) == expected


def test_shared_graph_streaming_updates():
    """Update-stream semantics survive the fan-out: retractions route like adds."""
    t = pw.debug.table_from_markdown(
        """
        grp | v | __time__ | __diff__
        a   | 1 | 2        | 1
        a   | 2 | 2        | 1
        b   | 5 | 2        | 1
        a   | 1 | 4        | -1
        """
    )
    out = t.groupby(pw.this.grp).reduce(pw.this.grp, total=pw.reducers.sum(pw.this.v))
    rows, _ = _collect(out)
    _run_with_threads(2)
    assert sorted((r["grp"], r["total"]) for r in rows.values()) == [("a", 2), ("b", 5)]


def test_threads_with_processes_refuses_loudly():
    t = pw.debug.table_from_markdown("a\n1")
    _collect(t)
    config_mod.set_thread_config(_threads_config(2, processes=2))
    try:
        with pytest.raises(NotImplementedError, match="hierarchical exchange"):
            GraphRunner(G._current).run()
    finally:
        config_mod.set_thread_config(None)


def test_run_threads_explicit_per_worker_shards():
    """The spawn-like lane: each worker builds its own graph over its own input
    shard; grouped totals are exact global counts, keys owned once."""
    from pathway_tpu.internals.config import get_pathway_config
    from pathway_tpu.parallel.threads import run_threads

    rng = np.random.default_rng(3)
    pool = [f"w{i}" for i in range(30)]
    shards = [[pool[i] for i in rng.integers(0, 30, 200)] for _ in range(3)]

    def program():
        rank = get_pathway_config().process_id
        tbl = pw.debug.table_from_rows(
            pw.schema_builder({"word": str}), [(w,) for w in shards[rank]]
        )
        counts = tbl.groupby(pw.this.word).reduce(
            pw.this.word, cnt=pw.reducers.count()
        )
        got = {}
        pw.io.subscribe(
            counts,
            lambda key, row, time, is_addition: got.__setitem__(row["word"], row["cnt"])
            if is_addition
            else got.pop(row["word"], None),
        )
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
        return got

    outs = run_threads(program, 3)
    import collections

    expected = collections.Counter()
    for shard in shards:
        expected.update(shard)
    merged: dict = {}
    for rank, out in enumerate(outs):
        for word, cnt in out.items():
            assert word not in merged, f"{word} owned twice"
            merged[word] = cnt
    assert merged == dict(expected)
    assert sum(bool(o) for o in outs) > 1, "all keys landed on one worker"


def test_shared_graph_worker_failure_propagates():
    @pw.udf
    def boom(x: int) -> int:
        if x == 13:
            raise ValueError("poof")
        return x

    t = pw.debug.table_from_rows(
        pw.schema_builder({"x": int}), [(i,) for i in range(20)]
    )
    out = t.select(y=boom(pw.this.x)).groupby(pw.this.y).reduce(
        pw.this.y, c=pw.reducers.count()
    )
    _collect(out)
    config_mod.set_thread_config(_threads_config(2))
    try:
        with pytest.raises(RuntimeError, match="worker thread"):
            GraphRunner(G._current).run(terminate_on_error=True)
    finally:
        config_mod.set_thread_config(None)


def test_cli_spawn_threads_end_to_end(tmp_path):
    """`spawn -t 2`: PATHWAY_THREADS env -> transparent fan-out inside pw.run."""
    import json
    import os
    import subprocess
    import sys
    import textwrap

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = tmp_path / "prog.py"
    prog.write_text(
        textwrap.dedent(
            """
            import json, os, sys
            import pathway_tpu as pw
            t = pw.debug.table_from_markdown(\"\"\"
            word | n
            cat  | 1
            dog  | 2
            cat  | 3
            \"\"\")
            out = t.groupby(t.word).reduce(t.word, total=pw.reducers.sum(t.n))
            rows = {}
            pw.io.subscribe(out, lambda key, row, time, is_addition:
                rows.__setitem__(row["word"], row["total"]) if is_addition
                else rows.pop(row["word"], None))
            pw.run(monitoring_level=pw.MonitoringLevel.NONE)
            json.dump(rows, open(sys.argv[1], "w"))
            """
        )
    )
    out_path = tmp_path / "out.json"
    env = os.environ.copy()
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn", "-t", "2",
            sys.executable, str(prog), str(out_path),
        ],
        env=env, capture_output=True, text=True, timeout=180, cwd=str(tmp_path),
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert json.loads(out_path.read_text()) == {"cat": 4, "dog": 2}


def test_run_threads_fs_reader_shards_not_duplicated(tmp_path):
    """Connector reader threads must inherit the worker's config override:
    partition-sharded fs readers on 2 workers each read THEIR shard of the
    files; without the override handoff both read everything and every count
    doubles."""
    from pathway_tpu.parallel.threads import run_threads

    for i in range(6):
        (tmp_path / f"f{i}.csv").write_text("word\n" + "\n".join(["cat"] * 3) + "\n")

    def program():
        t = pw.io.csv.read(
            str(tmp_path), schema=pw.schema_builder({"word": str}), mode="static"
        )
        counts = t.groupby(pw.this.word).reduce(
            pw.this.word, cnt=pw.reducers.count()
        )
        got = {}
        pw.io.subscribe(
            counts,
            lambda key, row, time, is_addition: got.__setitem__(row["word"], row["cnt"])
            if is_addition
            else got.pop(row["word"], None),
        )
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
        return got

    outs = run_threads(program, 2)
    merged: dict = {}
    for out in outs:
        for word, cnt in out.items():
            assert word not in merged
            merged[word] = cnt
    assert merged == {"cat": 18}, merged


def test_groupby_reducer_cross_ref_refused_under_cluster():
    """Reducer arguments evaluate AFTER the group-key exchange, where a foreign
    table's shard is not resident — must refuse loudly, not ERROR-poison."""
    t = pw.debug.table_from_rows(
        pw.schema_builder({"k": str, "v": int}), [(f"k{i}", i) for i in range(10)]
    )
    other = t.select(w=pw.this.v * 2)
    agg = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(other.w))
    _collect(agg)
    config_mod.set_thread_config(_threads_config(2))
    try:
        with pytest.raises(RuntimeError, match="reducer arguments reference"):
            GraphRunner(G._current).run()
    finally:
        config_mod.set_thread_config(None)


# -- typed peer-failure triage + exchange immutability (PR 1 satellites) -------


def test_primary_error_with_timeout_phrasing_not_misclassified():
    """A genuine worker failure whose MESSAGE contains 'timed out waiting' must
    still be picked as the root cause (triage is by exception type now, not by
    repr substring): the peer that dies waiting raises a typed
    PeerShutdownError and is the one classified secondary."""
    import pytest

    from pathway_tpu.engine.columnar import Delta
    from pathway_tpu.parallel.cluster import get_cluster
    from pathway_tpu.parallel.threads import run_threads

    def program():
        from pathway_tpu.internals.config import get_pathway_config

        rank = get_pathway_config().process_id
        if rank == 0:
            raise RuntimeError("backend timed out waiting for quota")
        get_cluster().exchange_to_root(b"t0", Delta.empty(["x"]))

    with pytest.raises(RuntimeError, match="worker thread 0 failed") as ei:
        run_threads(program, 2)
    assert "timed out waiting for quota" in str(ei.value)


def test_exchanged_delta_arrays_are_read_only():
    """The zero-serialization thread exchange hands LIVE arrays to peers; they
    must be frozen on handoff so an in-place mutation fails fast in the
    violating worker instead of corrupting its peers."""
    import numpy as np
    import pytest

    from pathway_tpu.engine.columnar import Delta
    from pathway_tpu.internals.keys import KEY_DTYPE
    from pathway_tpu.parallel.cluster import get_cluster
    from pathway_tpu.parallel.threads import run_threads

    def program():
        keys = np.zeros(2, dtype=KEY_DTYPE)
        diffs = np.ones(2, dtype=np.int64)
        cols = {"x": np.arange(2, dtype=np.float64)}
        d = Delta(keys, diffs, cols)
        merged = get_cluster().broadcast_merge(b"bm", d)
        return d, merged

    outs = run_threads(program, 2)
    for own, merged in outs:
        assert not own.keys.flags.writeable
        assert not own.columns["x"].flags.writeable
        with pytest.raises(ValueError):
            own.columns["x"][0] = 99.0
        assert len(merged) == 4
