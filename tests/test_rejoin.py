"""Surgical single-rank restart: exchange epochs, rank rejoin, and per-rank
journal handoff.

Three layers under test:

- mesh (``parallel/cluster.py``): epoch-stamped frames, stale-epoch drops,
  FENCE broadcast, the rejoin acceptor/dialer, ``await_rejoin`` install,
  idempotent ``close``;
- chaos (``internals/chaos.py``): epoch-gated kill entries and the
  drop-rejoin-handshake schedule;
- runtime (spawn acceptance): SIGKILL one rank of ``spawn -n 4`` mid-run with
  persistence on — survivors never exit, exactly one rank is relaunched, and
  the final output is bit-identical to the failure-free run; a dropped rejoin
  handshake (and a second concurrent failure) degrade to PR 2 restart-all;
  persistence-off still refuses the rejoin loudly.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from pathway_tpu.internals.chaos import Chaos
from pathway_tpu.parallel.cluster import (
    ClusterExchange,
    ClusterFenceError,
    PeerShutdownError,
    PeerTimeoutError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PORT_SLOT = itertools.count()


def _port_base() -> int:
    # distinct base per wiring so back-to-back tests never contend on TIME_WAIT
    return 30000 + os.getpid() % 150 * 40 + next(_PORT_SLOT) * 8


def _wire(n: int, first_port: int) -> dict:
    made: dict = {}
    errors: list = []

    def mk(me: int) -> None:
        try:
            made[me] = ClusterExchange(n, me, first_port)
        except BaseException as exc:
            errors.append(exc)

    threads = [threading.Thread(target=mk, args=(me,)) for me in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, f"wiring failed: {errors}"
    assert set(made) == set(range(n))
    return made


def _rejoin_exchange(n: int, me: int, first_port: int, epoch: int, monkeypatch):
    monkeypatch.setenv("PATHWAY_CLUSTER_REJOIN", "1")
    monkeypatch.setenv("PATHWAY_CLUSTER_EPOCH", str(epoch))
    try:
        return ClusterExchange(n, me, first_port)
    finally:
        monkeypatch.delenv("PATHWAY_CLUSTER_REJOIN", raising=False)
        monkeypatch.delenv("PATHWAY_CLUSTER_EPOCH", raising=False)


# -- mesh layer ---------------------------------------------------------------


def test_stale_epoch_frame_dropped_not_delivered(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    made = _wire(2, _port_base())
    a, b = made[0], made[1]
    try:
        # receiver moved to a newer epoch (as after a rejoin install): a data
        # frame stamped with the old epoch must be DROPPED, not delivered
        with a._cv:
            a.epoch = 1
        b._send(0, b"stale-tag", b"old-epoch-payload")
        with pytest.raises(PeerTimeoutError):
            a._recv(1, b"stale-tag", timeout=1.0)
        assert a.stale_frames_dropped >= 1
        assert (1, b"stale-tag") not in a._inbox
        # heartbeats keep flowing whatever the epoch — a peer mid-fence is
        # alive, not stale
        time.sleep(0.4)
        assert a.heartbeat_ages()[1] < 0.4
    finally:
        a.close()
        b.close()


def test_fence_broadcast_interrupts_peer_waits(monkeypatch):
    """Rank 2 dies; rank 0 notices and broadcasts the fence. Rank 1 — blocked
    waiting on rank 0, whose frame will never come — must abort with the typed
    fence error within socket latency, not sit out the barrier deadline."""
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    made = _wire(3, _port_base())
    try:
        made[2].close()
        deadline = time.time() + 10
        while 2 not in made[0].dead_peers() and time.time() < deadline:
            time.sleep(0.02)
        assert 2 in made[0].dead_peers()
        made[0].begin_fence()
        t0 = time.monotonic()
        with pytest.raises(ClusterFenceError) as excinfo:
            made[1]._recv(0, b"never-sent", timeout=30)
        assert time.monotonic() - t0 < 5
        assert "2" in str(excinfo.value)  # names the dead rank
        # the fence error IS a PeerShutdownError: existing isinstance-based
        # failure triage keeps working with surgical mode off
        assert isinstance(excinfo.value, PeerShutdownError)
    finally:
        for ex in made.values():
            ex.close()


def test_rejoin_replaces_dead_rank_and_drops_stale_tag_collision(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    port = _port_base()
    made = _wire(2, port)
    a, b = made[0], made[1]
    b2 = None
    try:
        # b sends a frame under a tag the post-rejoin protocol will REUSE,
        # then dies: the classic replayed-barrier collision
        b._send(0, b"collide", b"stale")
        b.close()
        with pytest.raises(PeerShutdownError):
            a._recv(1, b"never", timeout=10)

        res: dict = {}

        def relaunch() -> None:
            try:
                res["b2"] = _rejoin_exchange(2, 1, port, epoch=1, monkeypatch=monkeypatch)
            except BaseException as exc:  # surfaced by the assert below
                res["err"] = exc

        a.begin_fence()
        waits: list = []
        t = threading.Thread(target=relaunch)
        t.start()
        new_epoch = a.await_rejoin(timeout=30, on_wait=lambda: waits.append(1))
        t.join(timeout=10)
        assert "err" not in res, res.get("err")
        b2 = res["b2"]
        assert new_epoch == 1 and a.epoch == 1 and b2.epoch == 1
        assert 1 not in a.dead_peers()

        # the reused tag must deliver the FRESH epoch-1 payload, not the stale one
        out: dict = {}
        t2 = threading.Thread(
            target=lambda: out.setdefault(
                "b2", b2.exchange_parts(b"collide", {0: b"fresh"})
            )
        )
        t2.start()
        got = a.exchange_parts(b"collide", {1: b"fresh-from-a"})
        t2.join(timeout=10)
        assert got == {1: b"fresh"}
        assert out["b2"] == {0: b"fresh-from-a"}
        assert a.stale_frames_dropped >= 1
    finally:
        a.close()
        b.close()
        if b2 is not None:
            b2.close()


def test_future_epoch_frame_parked_until_own_install(monkeypatch):
    """The staggered-install race: survivor A installs the rejoin first and
    immediately talks at the new epoch, while survivor B has not fenced yet.
    A's frame must be PARKED at B and delivered once B's own install adopts
    the epoch — dropping it would wedge B's post-rejoin replay until the
    barrier deadline (nobody retransmits barrier parts)."""
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    port = _port_base()
    made = _wire(3, port)
    a, b = made[0], made[1]
    b2 = None
    try:
        made[2].close()
        deadline = time.time() + 10
        while (
            2 not in a.dead_peers() or 2 not in b.dead_peers()
        ) and time.time() < deadline:
            time.sleep(0.02)

        res: dict = {}

        def relaunch() -> None:
            try:
                res["c2"] = _rejoin_exchange(3, 2, port, epoch=1, monkeypatch=monkeypatch)
            except BaseException as exc:
                res["err"] = exc

        t = threading.Thread(target=relaunch)
        t.start()
        # A fences and installs FIRST; B deliberately lags at epoch 0
        a.begin_fence()
        assert a.await_rejoin(timeout=30) == 1
        # A races ahead: an epoch-1 frame reaches B while B is still at epoch 0
        a._send(1, b"replay:ids", b"a-part")
        deadline = time.time() + 5
        while (0, b"replay:ids") not in b._future_inbox and time.time() < deadline:
            time.sleep(0.02)
        with b._cv:
            assert (0, b"replay:ids") in b._future_inbox, "frame was dropped, not parked"
            assert (0, b"replay:ids") not in b._inbox
        # now B fences and installs: the parked frame must be delivered
        b.begin_fence()
        assert b.await_rejoin(timeout=30) == 1
        assert b._recv(0, b"replay:ids", timeout=5) == b"a-part"
        t.join(timeout=10)
        assert "err" not in res, res.get("err")
        b2 = res["c2"]
    finally:
        for ex in made.values():
            ex.close()
        if b2 is not None:
            b2.close()


def test_await_rejoin_times_out_typed(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    made = _wire(2, _port_base())
    a, b = made[0], made[1]
    try:
        b.close()
        deadline = time.time() + 10
        while 1 not in a.dead_peers() and time.time() < deadline:
            time.sleep(0.02)
        t0 = time.monotonic()
        with pytest.raises(PeerTimeoutError, match="no replacement"):
            a.await_rejoin(timeout=0.6)
        assert time.monotonic() - t0 < 5
    finally:
        a.close()
        b.close()


def test_rejoin_acceptor_refuses_stale_epoch(monkeypatch):
    """A zombie replacement from an abandoned attempt (epoch <= current) must
    be refused at the acceptor, never parked for install."""
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0.1")
    port = _port_base()
    made = _wire(2, port)
    a, b = made[0], made[1]
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        try:
            s.sendall(b"PWRJ" + (1).to_bytes(4, "little") + (0).to_bytes(4, "little"))
            time.sleep(0.5)
            with a._cv:
                assert a._pending_rejoin == {}
        finally:
            s.close()
    finally:
        a.close()
        b.close()


def test_close_idempotent_and_closes_pending_rejoin(monkeypatch):
    monkeypatch.setenv("PATHWAY_HEARTBEAT_INTERVAL_S", "0")
    made = _wire(2, _port_base())
    a, b = made[0], made[1]
    # park a fake pending-rejoin socket: close() must release it (a rejoin
    # aborted mid-handshake must not leak the half-installed fd)
    fake_a, fake_b = socket.socketpair()
    with a._cv:
        a._pending_rejoin[1] = (fake_a, 7)
    a.close()
    a.close()  # idempotent: second call is a no-op, no double-close
    b.close()
    b.close()
    assert fake_a.fileno() == -1, "pending rejoin socket leaked by close()"
    fake_b.close()
    # the listener port is actually free again (no fd held by the acceptor)
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        probe.bind(("127.0.0.1", a.first_port + a.me))
    finally:
        probe.close()


# -- chaos plan ops -----------------------------------------------------------


def test_chaos_drop_rejoin_schedule(monkeypatch):
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "1")
    plan = {"rejoin": [{"rank": 0, "run": 1}, {"rank": 2}]}
    c = Chaos(0, plan)
    assert c.drop_rejoin(0) is True  # run matches PATHWAY_RESTART_COUNT
    assert c.drop_rejoin(1) is False  # unscheduled rank
    assert c.drop_rejoin(2) is True  # no run field: every attempt drops
    assert c.stats["rejoins_dropped"] == 2
    # a LATER escalation attempt is a fresh process with a bumped restart
    # count: run-gated entries stop firing there (the cross-attempt key)
    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "2")
    c2 = Chaos(0, {"rejoin": [{"rank": 0, "run": 1}, {"rank": 2}]})
    assert c2.drop_rejoin(0) is False  # wrong incarnation
    assert c2.drop_rejoin(2) is True  # run-less entries keep dropping


def test_chaos_kill_epoch_gating(monkeypatch):
    killed: list = []
    from pathway_tpu.internals import chaos as chaos_mod

    monkeypatch.setattr(
        chaos_mod.os, "kill", lambda pid, sig: killed.append((pid, sig))
    )
    plan = {"kill": [{"rank": 0, "commit": 3, "run": 0, "epoch": 1}]}
    c = Chaos(0, plan)
    c.maybe_kill(0, 3, epoch=0)  # wrong epoch
    assert killed == []
    c.maybe_kill(0, 3, epoch=1)
    assert killed == [(os.getpid(), signal.SIGKILL)]
    # entries without an epoch field keep firing in any epoch
    killed.clear()
    c2 = Chaos(0, {"kill": [{"rank": 0, "commit": 3, "run": 0}]})
    c2.maybe_kill(0, 3, epoch=5)
    assert len(killed) == 1


# -- runner guard: rejoin refused loudly without persistence ------------------


def test_surgical_rejoin_refused_without_persistence():
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.parse_graph import ParseGraph

    runner = GraphRunner(ParseGraph())

    class _FakeCluster:
        supports_rejoin = True
        epoch = 0

    runner._surgical = True
    runner._cluster = _FakeCluster()
    runner._supervise_dir = "/nonexistent"
    runner._persistence = None  # no journal shard: nothing to roll back to
    assert runner._surgical_rejoin(PeerShutdownError("peer died")) is False
    # and with surgical mode off, even a persistent runner declines
    runner._persistence = object()
    runner._surgical = False
    assert runner._surgical_rejoin(PeerShutdownError("peer died")) is False


def test_health_payload_exposes_epoch_and_rejoin_fields(monkeypatch, tmp_path):
    """Satellite: /healthz (via GraphRunner.health) and the supervisor status
    files carry cluster_epoch, restart counts, rejoin counts, last-rejoin
    duration, and the fencing state."""
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals.parse_graph import ParseGraph
    from pathway_tpu.parallel.supervisor import read_statuses, write_status

    monkeypatch.setenv("PATHWAY_RESTART_COUNT", "2")
    runner = GraphRunner(ParseGraph())

    class _FakeCluster:
        supports_rejoin = True
        epoch = 3

        def heartbeat_ages(self):
            return {1: 0.5}

        def dead_peers(self):
            return {}

    runner._cluster = _FakeCluster()
    runner._rejoins = 1
    runner._last_rejoin_s = 2.5
    runner._rejoin_state = "rejoining"
    health = runner.health()
    assert health["epoch"] == 3
    assert health["restarts"] == 2
    assert health["rejoins"] == 1
    assert health["last_rejoin_s"] == 2.5
    assert health["state"] == "rejoining"

    write_status(
        str(tmp_path), 0, commit=7, persistence=True, peers=health["peers"],
        epoch=health["epoch"], state=health["state"],
        restarts=health["restarts"], last_rejoin_s=health["last_rejoin_s"],
    )
    status = read_statuses(str(tmp_path), 1)[0]
    assert status["epoch"] == 3
    assert status["state"] == "rejoining"
    assert status["restarts"] == 2
    assert status["last_rejoin_s"] == 2.5


# -- spawn acceptance ---------------------------------------------------------

REJOIN_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

    out_path = os.path.join(tmp, f"out_{pid}.json")
    rows = {}
    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(repr(key), None)
        with open(out_path + ".tmp", "w") as f:
            json.dump(list(rows.values()), f)
        os.replace(out_path + ".tmp", out_path)

    pw.io.subscribe(counts, on_change)
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
    )
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    """
)


def _spawn(tmp_path, first_port, *, n, plan, max_restarts, extra_env=None):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PATHWAY_CHAOS_SEED"] = "7"
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(plan)
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "30"
    env.update(extra_env or {})
    prog = tmp_path / "prog.py"
    prog.write_text(REJOIN_PROG)
    return subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", str(n), "--first-port", str(first_port),
            "--max-restarts", str(max_restarts),
            sys.executable, str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        start_new_session=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def _read_merged(tmp_path, n: int) -> dict:
    merged: dict = {}
    for p in range(n):
        path = tmp_path / f"out_{p}.json"
        if not path.exists():
            continue
        try:
            for r in json.loads(path.read_text()):
                merged[r["word"]] = r["total"]
        except ValueError:
            pass
    return merged


def _terminate_group(proc) -> str:
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    try:
        _, err = proc.communicate(timeout=20)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        _, err = proc.communicate()
    return err or ""


def _await_counts(proc, tmp_path, n, expected, deadline_s=150) -> tuple:
    deadline = time.time() + deadline_s
    merged: dict = {}
    while time.time() < deadline:
        if proc.poll() is not None:
            _, err = proc.communicate()
            raise AssertionError(
                f"spawn exited early (rc={proc.returncode}): {err}"
            )
        merged = _read_merged(tmp_path, n)
        if merged == expected:
            break
        time.sleep(0.3)
    return merged


def _failure_free_counts(tmp_path) -> dict:
    """Reference output: the same pipeline run in-process with no faults."""
    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G

    G.clear()

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        str(tmp_path / "in"), format="csv", schema=WordSchema, mode="static"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    rows: dict = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = {"word": row["word"], "total": int(row["total"])}
        else:
            rows.pop(key, None)

    pw.io.subscribe(counts, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    G.clear()
    return {r["word"]: r["total"] for r in rows.values()}


@pytest.mark.chaos
def test_surgical_failover_n4_one_relaunch_exact(tmp_path):
    """THE acceptance scenario: SIGKILL rank 2 of ``spawn -n 4`` mid-run with
    persistence on — the three survivors hold at the epoch fence (never exit),
    exactly one rank is relaunched, data arriving after the failover is still
    ingested exactly once, and the merged output is bit-identical to the
    failure-free run. No restart-all anywhere."""
    (tmp_path / "in").mkdir()
    first_port = 31000 + os.getpid() % 400 * 8
    for i in range(4):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    plan = {"kill": [{"rank": 2, "commit": 3, "run": 0}]}
    proc = _spawn(tmp_path, first_port, n=4, plan=plan, max_restarts=1)
    err = ""
    try:
        time.sleep(10)  # kill + fence + rejoin window
        # post-failover data must be ingested exactly once by the healed cluster
        (tmp_path / "in" / "late.csv").write_text(
            "word\n" + "\n".join(["owl"] * 3 + ["cat"] * 1) + "\n"
        )
        expected = {"cat": 11, "dog": 8, "owl": 3}
        merged = _await_counts(proc, tmp_path, 4, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert err.count("surgically relaunching rank 2") == 1, (
        f"expected exactly one surgical relaunch of rank 2:\n{err}"
    )
    assert "restarting the cluster" not in err, (
        f"survivors were torn down — restart-all fired instead of surgical:\n{err}"
    )
    assert "rejoined the cluster at epoch 1" in err, (
        f"rejoin never completed:\n{err}"
    )
    # bit-identical to the failure-free run of the same pipeline
    assert _failure_free_counts(tmp_path) == merged


@pytest.mark.chaos
def test_rejoin_handshake_drop_falls_back_to_restart_all(tmp_path):
    """Escalation rung 2: the chaos plan drops the replacement's rejoin
    handshake, so the surgical attempt fails typed and the supervisor degrades
    to PR 2 restart-all — which still converges to exact output."""
    (tmp_path / "in").mkdir()
    first_port = 31000 + os.getpid() % 400 * 8 + 4
    for i in range(4):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    plan = {
        "kill": [{"rank": 0, "commit": 3, "run": 0}],
        # the relaunched rank 0 (restart count 1) loses its handshake once
        "rejoin": [{"rank": 0, "run": 1}],
    }
    proc = _spawn(tmp_path, first_port, n=2, plan=plan, max_restarts=2)
    err = ""
    try:
        # expected totals must REQUIRE post-recovery ingestion: with tiny
        # inputs the pipeline can converge milliseconds before the commit-3
        # kill even fires, and terminating on pre-kill convergence would race
        # the whole escalation ladder out of the test
        time.sleep(14)  # kill + failed surgical attempt + restart-all window
        (tmp_path / "in" / "late.csv").write_text(
            "word\n" + "\n".join(["owl"] * 3) + "\n"
        )
        expected = {"cat": 10, "dog": 8, "owl": 3}
        merged = _await_counts(proc, tmp_path, 2, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "surgically relaunching rank 0" in err, f"no surgical attempt:\n{err}"
    assert "falling back to restart-all" in err, (
        f"dropped handshake did not degrade to restart-all:\n{err}"
    )
    assert "restarting the cluster" in err, f"restart-all never ran:\n{err}"


@pytest.mark.chaos
def test_double_concurrent_failure_degrades_to_restart_all(tmp_path):
    """Two ranks die at the same commit boundary: the supervisor starts a
    surgical rejoin for the first, notices the second death while it is in
    flight, and degrades to restart-all — never a hang, exact output."""
    (tmp_path / "in").mkdir()
    first_port = 31000 + os.getpid() % 400 * 8 + 6
    for i in range(4):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 1) + ["dog"] * 2) + "\n"
        )

    plan = {
        "kill": [
            {"rank": 0, "commit": 3, "run": 0},
            {"rank": 1, "commit": 3, "run": 0},
        ]
    }
    proc = _spawn(
        tmp_path, first_port, n=2, plan=plan, max_restarts=2,
        # the doomed replacement must give up dialing the second corpse quickly
        extra_env={"PATHWAY_CONNECT_TIMEOUT_S": "8"},
    )
    err = ""
    try:
        # see test_rejoin_handshake_drop_falls_back_to_restart_all: expected
        # totals must require post-recovery ingestion or convergence can race
        # the kills
        time.sleep(16)  # both kills + failed rejoin dial + restart-all window
        (tmp_path / "in" / "late.csv").write_text(
            "word\n" + "\n".join(["owl"] * 3) + "\n"
        )
        expected = {"cat": 10, "dog": 8, "owl": 3}
        merged = _await_counts(proc, tmp_path, 2, expected)
        assert merged == expected, f"got {merged}, want {expected}"
    finally:
        err = _terminate_group(proc)
    assert "restarting the cluster" in err, (
        f"double failure did not degrade to restart-all:\n{err}"
    )
