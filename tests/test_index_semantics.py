"""Index query semantics: as-of-now (answers never revisited) vs full differential
(``DataIndex.query`` re-answers when the index changes) — reference
``ml/test_index.py`` ``update_old`` vs ``asof_now`` semantics — plus CSV error poisoning.
"""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
)

from .utils import T, capture_rows


@pw.udf
def _vec_embedder(text: str) -> np.ndarray:
    # deterministic 4-dim embedding: one-hot-ish on first char
    v = np.zeros(4, dtype=np.float32)
    v[ord(text[0]) % 4] = 1.0
    v[3] = len(text) / 100.0
    return v


def _make_index(docs):
    factory = BruteForceKnnFactory(
        dimensions=4, metric=BruteForceKnnMetricKind.L2SQ, embedder=_vec_embedder
    )
    return factory.build_index(docs.text, docs)


def test_query_reanswers_on_index_growth():
    # doc "dzz" (far) exists when the query arrives; doc "aaa" (exact) arrives later.
    docs = T(
        """
        text | __time__
        dzz  | 0
        aaa  | 4
        """
    )
    queries = T(
        """
        q   | __time__
        abc | 2
        """
    )
    index = _make_index(docs)
    res = index.query(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    # full differential semantics: the late-arriving closer doc replaces the answer
    assert rows[0]["text"] == ("aaa",)


def test_query_as_of_now_keeps_first_answer():
    docs = T(
        """
        text | __time__
        dzz  | 0
        aaa  | 4
        """
    )
    queries = T(
        """
        q   | __time__
        abc | 2
        """
    )
    index = _make_index(docs)
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    # as-of-now: answered against the index state at query arrival, never revisited
    assert rows[0]["text"] == ("dzz",)


def test_csv_malformed_field_poisons_with_error(tmp_path):
    from pathway_tpu.engine.columnar import Error

    csv_file = tmp_path / "data.csv"
    csv_file.write_text("a,b\n1,2\nbad,3\n")

    class Sch(pw.Schema):
        a: int
        b: int

    t = pw.io.csv.read(str(csv_file), schema=Sch, mode="static")
    rows = sorted(capture_rows(t), key=lambda r: r["b"])
    assert rows[0] == {"a": 1, "b": 2}
    # malformed int field poisons the cell rather than silently becoming None
    assert isinstance(rows[1]["a"], Error)
    assert rows[1]["b"] == 3

    # remove_errors drops the poisoned row (reference Value::Error propagation contract)
    clean = pw.io.csv.read(str(csv_file), schema=Sch, mode="static").remove_errors()
    assert capture_rows(clean) == [{"a": 1, "b": 2}]
