"""Index query semantics: as-of-now (answers never revisited) vs full differential
(``DataIndex.query`` re-answers when the index changes) — reference
``ml/test_index.py`` ``update_old`` vs ``asof_now`` semantics — plus CSV error poisoning.
"""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
)

from .utils import T, capture_rows


@pw.udf
def _vec_embedder(text: str) -> np.ndarray:
    # deterministic 4-dim embedding: one-hot-ish on first char
    v = np.zeros(4, dtype=np.float32)
    v[ord(text[0]) % 4] = 1.0
    v[3] = len(text) / 100.0
    return v


def _make_index(docs):
    factory = BruteForceKnnFactory(
        dimensions=4, metric=BruteForceKnnMetricKind.L2SQ, embedder=_vec_embedder
    )
    return factory.build_index(docs.text, docs)


def test_query_reanswers_on_index_growth():
    # doc "dzz" (far) exists when the query arrives; doc "aaa" (exact) arrives later.
    docs = T(
        """
        text | __time__
        dzz  | 0
        aaa  | 4
        """
    )
    queries = T(
        """
        q   | __time__
        abc | 2
        """
    )
    index = _make_index(docs)
    res = index.query(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    # full differential semantics: the late-arriving closer doc replaces the answer
    assert rows[0]["text"] == ("aaa",)


def test_query_as_of_now_keeps_first_answer():
    docs = T(
        """
        text | __time__
        dzz  | 0
        aaa  | 4
        """
    )
    queries = T(
        """
        q   | __time__
        abc | 2
        """
    )
    index = _make_index(docs)
    res = index.query_as_of_now(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    # as-of-now: answered against the index state at query arrival, never revisited
    assert rows[0]["text"] == ("dzz",)


def test_csv_malformed_field_poisons_with_error(tmp_path):
    from pathway_tpu.engine.columnar import Error

    csv_file = tmp_path / "data.csv"
    csv_file.write_text("a,b\n1,2\nbad,3\n")

    class Sch(pw.Schema):
        a: int
        b: int

    t = pw.io.csv.read(str(csv_file), schema=Sch, mode="static")
    rows = sorted(capture_rows(t), key=lambda r: r["b"])
    assert rows[0] == {"a": 1, "b": 2}
    # malformed int field poisons the cell rather than silently becoming None
    assert isinstance(rows[1]["a"], Error)
    assert rows[1]["b"] == 3

    # remove_errors drops the poisoned row (reference Value::Error propagation contract)
    clean = pw.io.csv.read(str(csv_file), schema=Sch, mode="static").remove_errors()
    assert capture_rows(clean) == [{"a": 1, "b": 2}]


def test_query_reanswers_on_doc_removal():
    """update_old semantics under retraction: removing the best doc re-answers
    with the next best (reference ml/test_index.py re-answering matrix)."""
    docs = T(
        """
        text | __time__ | __diff__
        aaa  | 0        | 1
        azz  | 0        | 1
        aaa  | 4        | -1
        """
    )
    queries = T(
        """
        q   | __time__
        abc | 2
        """
    )
    index = _make_index(docs)
    res = index.query(queries.q, number_of_matches=1, collapse_rows=True)
    rows = capture_rows(res)
    assert len(rows) == 1
    assert rows[0]["text"] == ("azz",)  # best doc retracted -> next best


def test_query_update_stream_reanswering_events():
    """The re-answer arrives as retract(old answer) + insert(new answer) on the
    SAME query key (DiffEntry fixture port, reference tests/utils.py:544+)."""
    from .utils import capture_update_stream

    docs = T(
        """
        text | __time__
        dzz  | 0
        aaa  | 4
        """
    )
    queries = T(
        """
        q   | __time__
        abc | 2
        """
    )
    index = _make_index(docs)
    res = index.query(queries.q, number_of_matches=1, collapse_rows=True)
    events = capture_update_stream(res)
    seq = [(e["text"], e["__diff__"]) for e in events]
    assert seq == [(("dzz",), 1), (("dzz",), -1), (("aaa",), 1)]
    # per-key ordering contract via the DiffEntry fixture
    assert len({e["__time__"] for e in events}) == 2  # answer, then re-answer


def test_query_variable_k_per_row():
    docs = T(
        """
        text
        aaa
        aab
        aac
        aad
        """
    )
    queries = T(
        """
        q   | k
        aaa | 1
        aab | 3
        """
    )
    index = _make_index(docs)
    res = index.query(queries.q, number_of_matches=queries.k, collapse_rows=True)
    rows = sorted(capture_rows(res), key=lambda r: len(r["text"]))
    assert len(rows[0]["text"]) == 1
    assert len(rows[1]["text"]) == 3


def test_query_metadata_filter():
    import json as _json

    docs = T(
        """
        text | meta
        aaa  | {"owner": "alice"}
        aab  | {"owner": "bob"}
        aac  | {"owner": "alice"}
        """
    )
    from pathway_tpu.internals.json import Json

    docs = docs.select(
        docs.text, meta=pw.apply_with_type(lambda s: Json(_json.loads(s)), Json, docs.meta)
    )
    factory = BruteForceKnnFactory(
        dimensions=4, metric=BruteForceKnnMetricKind.L2SQ, embedder=_vec_embedder
    )
    index = factory.build_index(docs.text, docs, metadata_column=docs.meta)
    queries = T(
        """
        q   | flt
        aaa | owner == 'alice'
        """
    )
    res = index.query(
        queries.q, number_of_matches=3, collapse_rows=True, metadata_filter=queries.flt
    )
    rows = capture_rows(res)
    assert len(rows) == 1
    assert sorted(rows[0]["text"]) == ["aaa", "aac"]  # bob's doc filtered out


def test_query_all_at_once_matches_asof_now():
    """With a static corpus, full-differential and as-of-now answers agree
    (reference all-at-once matrix)."""
    docs = T(
        """
        text
        aaa
        bzz
        czz
        """
    )
    queries = T(
        """
        q
        abc
        bcd
        """
    )
    index = _make_index(docs)
    r1 = index.query(queries.q, number_of_matches=2, collapse_rows=True)
    rows1 = sorted(tuple(sorted(r["text"])) for r in capture_rows(r1))

    import pathway_tpu.internals.parse_graph as pg

    pg.G.clear()
    docs2 = T(
        """
        text
        aaa
        bzz
        czz
        """
    )
    queries2 = T(
        """
        q
        abc
        bcd
        """
    )
    index2 = _make_index(docs2)
    r2 = index2.query_as_of_now(queries2.q, number_of_matches=2, collapse_rows=True)
    rows2 = sorted(tuple(sorted(r["text"])) for r in capture_rows(r2))
    assert rows1 == rows2


def test_groupby_update_stream_diffentry_fixture():
    """DiffEntry port smoke: a growing group emits retract+insert pairs in per-key
    order (reference CheckKeyEntriesInStreamCallback semantics)."""
    from .utils import DiffEntry, assert_key_entries_in_stream_consistent
    from pathway_tpu.internals.keys import pointer_from

    t = T(
        """
        word | __time__
        cat  | 0
        cat  | 4
        """
    )
    counts = t.groupby(t.word).reduce(t.word, cnt=pw.reducers.count())
    expected = [
        DiffEntry(pointer_from("cat"), 0, True, {"word": "cat", "cnt": 1}),
        DiffEntry(pointer_from("cat"), 1, False, {"word": "cat", "cnt": 1}),
        DiffEntry(pointer_from("cat"), 2, True, {"word": "cat", "cnt": 2}),
    ]
    assert_key_entries_in_stream_consistent(expected, counts)


def test_assert_stream_equality_fixture():
    from .utils import assert_stream_equality

    a = T(
        """
        v | __time__ | __diff__
        1 | 0        | 1
        2 | 2        | 1
        1 | 4        | -1
        """
    )
    b = T(
        """
        v | __time__ | __diff__
        1 | 2        | 1
        2 | 6        | 1
        1 | 8        | -1
        """
    )
    assert_stream_equality(a, b)  # same groups, times differ only by rank


def test_query_k_zero_and_k_exceeding_corpus():
    docs = T(
        """
        text | __time__
        aaa  | 0
        bbb  | 0
        """
    )
    queries = T(
        """
        q   | k | __time__
        abc | 0 | 2
        azz | 5 | 2
        """
    )
    index = _make_index(docs)
    res = index.query_as_of_now(
        queries.q, number_of_matches=queries.k, collapse_rows=True
    )
    rows = capture_rows(res)
    assert len(rows) == 2
    sizes = sorted(len(r["text"]) for r in rows)
    assert sizes == [0, 2]  # k=0 -> no matches; k=5 -> whole 2-doc corpus


def test_query_results_are_score_ordered():
    """Matches must come best-first (reference index contract: scores descend)."""
    docs = T(
        """
        text | __time__
        a    | 0
        aa   | 0
        aaaa | 0
        """
    )
    queries = T(
        """
        q  | __time__
        ab | 2
        """
    )
    index = _make_index(docs)
    res = index.query_as_of_now(queries.q, number_of_matches=3, collapse_rows=True)
    rows = capture_rows(
        res.select(res.text, score=res._pw_index_reply_score)
    )
    (row,) = rows
    scores = list(row["score"])
    assert scores == sorted(scores, reverse=True)  # best (least-negative L2) first
    # the embedder makes "a"-prefixed docs differ only in the length component:
    # "aa" (len 2) matches "ab" (len 2) exactly
    assert row["text"][0] == "aa"


def test_query_filter_combined_with_reanswer():
    """Metadata filters keep applying across re-answers (filter + update_old)."""
    import json as _json

    from pathway_tpu.internals.json import Json

    docs = T(
        """
        text | meta                | __time__
        dzz  | {"lang": "en"}      | 0
        aab  | {"lang": "fr"}      | 0
        aaa  | {"lang": "en"}      | 4
        """
    )
    docs = docs.select(
        docs.text,
        meta=pw.apply_with_type(lambda s: Json(_json.loads(s)), Json, docs.meta),
    )
    queries = T(
        """
        q   | __time__
        abc | 2
        """
    )
    factory = BruteForceKnnFactory(
        dimensions=4, metric=BruteForceKnnMetricKind.L2SQ, embedder=_vec_embedder
    )
    index = factory.build_index(docs.text, docs, metadata_column=docs.meta)
    res = index.query(
        queries.q,
        number_of_matches=1,
        collapse_rows=True,
        metadata_filter="lang == 'en'",
    )
    rows = capture_rows(res)
    assert len(rows) == 1
    # the French doc is filtered although it is the nearest at query time;
    # when "aaa" (en) arrives the answer upgrades from "dzz" to "aaa"
    assert rows[0]["text"] == ("aaa",)
