"""ML stdlib: HMM decoding, fuzzy joins, dataset loaders (VERDICT r2 §2.2: ml
stdlib was `ml/index.py` only — reference ``stdlib/ml/{hmm,smart_table_ops,datasets}``)."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg


def _run_capture_update_stream(table):
    got = []
    pw.io.subscribe(
        table,
        on_batch=lambda keys, diffs, columns, time: got.extend(
            (time, dict(zip(columns, vals)), d)
            for *vals, d in zip(*columns.values(), diffs.tolist())
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return got


def _manul_graph():
    import networkx as nx
    from functools import partial

    def emission(observation, state):
        table = {
            ("HUNGRY", "GRUMPY"): 0.9,
            ("HUNGRY", "HAPPY"): 0.1,
            ("FULL", "GRUMPY"): 0.7,
            ("FULL", "HAPPY"): 0.3,
        }
        return np.log(table[(state, observation)])

    g = nx.DiGraph()
    for s in ("HUNGRY", "FULL"):
        g.add_node(s, calc_emission_log_ppb=partial(emission, state=s))
    g.add_edge("HUNGRY", "HUNGRY", log_transition_ppb=np.log(0.4))
    g.add_edge("HUNGRY", "FULL", log_transition_ppb=np.log(0.6))
    g.add_edge("FULL", "HUNGRY", log_transition_ppb=np.log(0.6))
    g.add_edge("FULL", "FULL", log_transition_ppb=np.log(0.4))
    g.graph["start_nodes"] = ["HUNGRY", "FULL"]
    return g


def test_hmm_reducer_incremental_decode():
    """Streaming observations re-decode incrementally; final decode matches the
    reference's documented example (last 3 states for the manul HMM)."""
    pg.G.clear()
    obs = pw.debug.table_from_rows(
        pw.schema_builder({"observation": str}),
        [
            ("HAPPY", 0, 1),
            ("HAPPY", 2, 1),
            ("GRUMPY", 4, 1),
            ("GRUMPY", 6, 1),
            ("HAPPY", 8, 1),
            ("GRUMPY", 10, 1),
        ],
        is_stream=True,
    )
    reducer = pw.reducers.udf_reducer(
        pw.stdlib.ml.hmm.create_hmm_reducer(_manul_graph(), num_results_kept=3)
    )
    decoded = obs.reduce(decoded_state=reducer(pw.this.observation))
    events = _run_capture_update_stream(decoded)
    inserts = [row["decoded_state"] for _t, row, d in events if d > 0]
    # grows one state per observation until the kept-suffix window fills
    assert inserts[0] == ("FULL",)
    assert inserts[1] == ("FULL", "FULL")
    assert inserts[-1] == ("HUNGRY", "FULL", "HUNGRY")
    assert all(len(s) <= 3 for s in inserts)


def test_hmm_beam_size_limits_states():
    from pathway_tpu.stdlib.ml.hmm import create_hmm_reducer

    acc_cls = create_hmm_reducer(_manul_graph(), beam_size=1)
    acc = acc_cls.from_row(["GRUMPY"])
    acc.update(acc_cls.from_row(["GRUMPY"]))
    acc._drain()
    assert len(acc.beam) == 1  # beam pruned to the single best state


def test_fuzzy_match_tables_mutual_best():
    pg.G.clear()
    left = pw.debug.table_from_rows(
        pw.schema_builder({"name": str}),
        [("Alice Cooper",), ("Bob Marley",), ("Charlie Parker",)],
    )
    right = pw.debug.table_from_rows(
        pw.schema_builder({"person": str}),
        [("cooper alice",), ("marley bob",), ("parker charlie",)],
    )
    matches = pw.stdlib.ml.fuzzy_match_tables(left, right)
    mdf = pw.debug.table_to_pandas(matches)
    lcap = pw.debug.table_to_pandas(left)
    rcap = pw.debug.table_to_pandas(right)
    assert len(mdf) == 3
    lnames = {k: v for k, v in zip(lcap.index, lcap["name"])}
    rnames = {k: v for k, v in zip(rcap.index, rcap["person"])}
    pairs = {(lnames[l], rnames[r]) for l, r in zip(mdf["left"], mdf["right"])}
    assert pairs == {
        ("Alice Cooper", "cooper alice"),
        ("Bob Marley", "marley bob"),
        ("Charlie Parker", "parker charlie"),
    }
    assert (mdf["weight"] > 0).all()


def test_fuzzy_self_match_dedupes_pairs():
    pg.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_builder({"name": str}),
        [("Data Works Inc",), ("data works incorporated",), ("Quantum Cats",)],
    )
    matches = pw.stdlib.ml.fuzzy_self_match(t.name)
    mdf = pw.debug.table_to_pandas(matches)
    tdf = pw.debug.table_to_pandas(t)
    names = {k: v for k, v in zip(tdf.index, tdf["name"])}
    # exactly one row for the similar pair, reported once (left < right)
    assert len(mdf) == 1
    left, right = mdf["left"].iloc[0], mdf["right"].iloc[0]
    assert left < right
    assert {names[left], names[right]} == {
        "Data Works Inc",
        "data works incorporated",
    }


def test_synthetic_classification_dataset_tables():
    pg.G.clear()
    X_train, y_train, X_test, y_test = (
        pw.stdlib.ml.datasets.load_synthetic_classification(
            n_train=60, n_test=12, dim=4, n_classes=3
        )
    )
    xt = pw.debug.table_to_pandas(X_train)
    yt = pw.debug.table_to_pandas(y_train)
    assert len(xt) == 60 and len(yt) == 60
    assert xt["data"].iloc[0].shape == (4,)
    assert set(yt["label"]) <= {"0", "1", "2"}
    assert len(pw.debug.table_to_pandas(X_test)) == 12
