"""Native (C++) kernel tests: key-fingerprint parity with the Python serializer,
DSV splitter parity with the csv module, fused CSV parse parity with the fallback."""

from __future__ import annotations

import csv
import io
import os

import numpy as np
import pytest

import pathway_tpu.native as native
from pathway_tpu.internals import keys as K


requires_native = pytest.mark.skipif(
    native.get_lib() is None, reason="native toolchain unavailable"
)


def _python_keys(columns):
    os.environ["PATHWAY_TPU_DISABLE_NATIVE"] = "1"
    native._tried, native._lib = False, None
    try:
        return K.keys_from_values(columns)
    finally:
        del os.environ["PATHWAY_TPU_DISABLE_NATIVE"]
        native._tried, native._lib = False, None


@requires_native
@pytest.mark.parametrize(
    "col",
    [
        np.array(["a", "bb", "ccc"] * 30, dtype=object),
        np.arange(90).astype(object),
        np.array([1.5, -2.25, 0.0] * 30, dtype=object),
        np.array([True, False, True] * 30, dtype=object),
        np.array(["x", None, "z"] * 30, dtype=object),
        np.array([1, None, 3] * 30, dtype=object),
        np.array([np.int64(7), np.float64(1.5), "s", None] * 20, dtype=object),
        np.array([(1, 2), "x", 3.5, None] * 20, dtype=object),  # tuple → fallback path
        np.array([2**100, 1, 2] * 30, dtype=object),  # 128-bit int → fallback path
    ],
    ids=["str", "int", "float", "bool", "str-none", "int-none", "mixed", "tuple", "bigint"],
)
def test_key_parity(col):
    got = K.keys_from_values([col])
    want = _python_keys([col])
    assert (got == want).all()


@requires_native
def test_key_parity_typed_arrays():
    cols = [np.arange(80, dtype=np.int64), np.array(["q"] * 80, dtype=object)]
    assert (K.keys_from_values(cols) == _python_keys(cols)).all()


@requires_native
def test_sequential_key_parity():
    got = K.sequential_keys(5, 100)
    os.environ["PATHWAY_TPU_DISABLE_NATIVE"] = "1"
    native._tried, native._lib = False, None
    try:
        want = K.sequential_keys(5, 100)
    finally:
        del os.environ["PATHWAY_TPU_DISABLE_NATIVE"]
        native._tried, native._lib = False, None
    assert (got == want).all()


@requires_native
@pytest.mark.parametrize(
    "text",
    [
        "a,b,c\n1,2,3\n4,5,6\n",
        'a,b\n"x,y",2\n"with ""quotes""",3\n',
        "a,b\r\n1,2\r\n",
        "a\nonly\n",
        "",
        "a,b\n1,\n,2\n",
        'a,b\n"multi\nline",5\n',
        "a,b\nlast,noeol",
    ],
    ids=["plain", "quoted", "crlf", "single", "empty", "empties", "multiline", "noeol"],
)
def test_split_dsv_matches_csv_module(text):
    got = native.split_dsv(text.encode())
    want = [r for r in csv.reader(io.StringIO(text)) if r]
    assert got == want


@requires_native
def test_fused_csv_parse_parity(tmp_path):
    import pathway_tpu as pw
    from pathway_tpu.io import fs

    path = tmp_path / "t.csv"
    path.write_text('word,count,ok,score\n"a,b",notanint,true,1.5\nc,5,False,bad\n,,,\n')
    schema = pw.schema_from_types(word=str, count=int, ok=bool, score=float)
    with_native = fs._parse_file(str(path), "csv", schema, False)
    os.environ["PATHWAY_TPU_DISABLE_NATIVE"] = "1"
    native._tried, native._lib = False, None
    try:
        without = fs._parse_file(str(path), "csv", schema, False)
    finally:
        del os.environ["PATHWAY_TPU_DISABLE_NATIVE"]
        native._tried, native._lib = False, None
    assert with_native == without


@requires_native
def test_uint64_overflow_keys():
    col = np.array([2**63 + 5] * 70, dtype=np.uint64)
    assert (K.keys_from_values([col]) == _python_keys([col])).all()


@requires_native
def test_split_dsv_stray_quote_mid_field():
    text = 'a,b\n5\'10",x\n'
    got = native.split_dsv(text.encode())
    want = [r for r in csv.reader(io.StringIO(text)) if r]
    assert got == want


@requires_native
@pytest.mark.parametrize(
    "content,types",
    [
        ("i,f\n99999999999999999999999999,1e-320\n1_000,0x1p3\n", {"i": int, "f": float}),
        ('"a\nb",c\n1,2\n', {"a\nb": int, "c": int}),
        ('x\n""\nz\n', {"x": str}),
        ("x\n1\n", {"x": int, "missing": str}),
    ],
    ids=["bigint-subnormal", "quoted-header", "quoted-empty-row", "missing-col"],
)
def test_fused_parse_edge_parity(tmp_path, content, types):
    import pathway_tpu as pw
    from pathway_tpu.io import fs

    path = tmp_path / "t.csv"
    path.write_text(content)
    schema = pw.schema_from_types(**types)
    with_native = fs._parse_file(str(path), "csv", schema, False)
    os.environ["PATHWAY_TPU_DISABLE_NATIVE"] = "1"
    native._tried, native._lib = False, None
    try:
        without = fs._parse_file(str(path), "csv", schema, False)
    finally:
        del os.environ["PATHWAY_TPU_DISABLE_NATIVE"]
        native._tried, native._lib = False, None
    assert with_native == without


@requires_native
def test_split_dsv_cr_only_line_endings():
    # csv.reader errors on untranslated bare-CR input; the native splitter applies
    # universal-newline row breaks, matching csv over translated text
    text = "a,b\r1,2\r3,4\r"
    got = native.split_dsv(text.encode())
    translated = text.replace("\r\n", "\n").replace("\r", "\n")
    want = [r for r in csv.reader(io.StringIO(translated)) if r]
    assert got == want


@requires_native
def test_multibyte_delimiter_falls_back(tmp_path):
    import pathway_tpu as pw
    from pathway_tpu.io import fs

    path = tmp_path / "t.csv"
    path.write_text("a¦b\n1¦2\n")

    class Settings:
        delimiter = "¦"

    schema = pw.schema_from_types(a=int, b=int)
    rows = fs._parse_file(str(path), "csv", schema, False, csv_settings=Settings())
    assert rows == [{"a": 1, "b": 2}]


def test_hash_upsert_fused_matches_two_step():
    """The fused native fingerprint+upsert must produce byte-identical keys and
    identical slot assignments to the two-step path."""
    import numpy as np

    from pathway_tpu.engine.index import KeyIndex
    from pathway_tpu.internals.keys import hash_upsert, keys_from_values

    rng = np.random.default_rng(0)
    words = np.array([f"w{i % 500}" for i in range(5000)], dtype=object)
    nums = rng.integers(0, 100, 5000).astype(np.int64)

    idx_a, idx_b = KeyIndex(), KeyIndex()
    keys_f, slots_f, new_f = hash_upsert(idx_a, [words, nums])
    keys_t = keys_from_values([words, nums])
    slots_t, new_t = idx_b.upsert(keys_t)
    assert keys_f.tobytes() == keys_t.tobytes()
    assert (slots_f == slots_t).all()
    assert (new_f == new_t).all()
    # second batch reuses existing slots identically
    keys_f2, slots_f2, new_f2 = hash_upsert(idx_a, [words, nums])
    assert not new_f2.any()
    assert (slots_f2 == slots_f).all()


def test_hash_upsert_unsupported_value_leaves_index_untouched():
    """A native-unsupported cell mid-batch must fall back to the Python
    serializer WITHOUT having partially upserted (the native function hashes
    fully before any index mutation)."""
    import numpy as np

    from pathway_tpu.engine.index import KeyIndex
    from pathway_tpu.internals.keys import hash_upsert, keys_from_values

    col = np.empty(200, dtype=object)
    col[:] = [f"t{i}" for i in range(200)]
    col[150] = ("tuple", "cell")  # not natively serializable

    idx = KeyIndex()
    keys, slots, is_new = hash_upsert(idx, [col])
    assert keys.tobytes() == keys_from_values([col]).tobytes()
    assert len(idx) == 200 and is_new.all()
    assert sorted(slots.tolist()) == list(range(200))


def test_hash_upsert_small_batch_and_python_index_fallbacks():
    import numpy as np

    from pathway_tpu.engine.index import _PyKeyIndex
    from pathway_tpu.internals.keys import hash_upsert, keys_from_values

    col = np.array(["a", "b", "a"], dtype=object)
    idx = _PyKeyIndex()
    keys, slots, is_new = hash_upsert(idx, [col])
    assert keys.tobytes() == keys_from_values([col]).tobytes()
    assert slots[0] == slots[2] and slots[0] != slots[1]
    assert is_new.tolist() == [True, True, False]
