"""Distributed-tracing spawn acceptances (ISSUE 20): a real ``spawn -n 2``
cluster, tracing on —

- **one tree** — the deterministic ``(epoch, commit)`` trace id makes every
  rank's commit span a sibling in ONE trace with nothing riding the wire;
  the merged rank files must show a single commit trace holding spans from
  BOTH ranks with operator/barrier children parented inside it;
- **cli trace** — ``python -m pathway_tpu.cli trace <dir>`` merges the rank
  files and NAMES the critical-path span;
- **partial trace from the black box** — a chaos-SIGKILL'd rank's flight
  dump embeds its newest spans (the jsonl flush + payload ride the dump
  path), so the merger still renders the dead rank's side of the story.

Budgets mirror the other spawn acceptances: 240 s worst case, seconds on an
idle machine.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.trace


TRACED_WORDCOUNT_PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    words = json.load(open(os.path.join(tmp, f"input_{pid}.json")))
    # several timestamped batches -> several commits, so commit spans from
    # both ranks land in shared (epoch, commit) traces
    rows = [(w, 2 * (i // 40), 1) for i, w in enumerate(words)]
    tbl = pw.debug.table_from_rows(
        pw.schema_builder({"word": str}), rows, is_stream=True
    )
    counts = tbl.groupby(pw.this.word).reduce(
        pw.this.word, cnt=pw.reducers.count()
    )
    pw.io.subscribe(counts, lambda key, row, time, is_addition: None)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump({"done": pid}, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)


def _trace_env(trace_dir) -> dict:
    return {
        "JAX_PLATFORMS": "cpu",
        "PATHWAY_TRACE": "on",
        "PATHWAY_TRACE_SAMPLE": "1.0",
        "PATHWAY_TRACE_DIR": str(trace_dir),
        "PATHWAY_FLIGHT_RECORDER_DIR": str(trace_dir),
    }


def _spawn_blocking(n, program, tmp_path, extra_env, first_port) -> None:
    prog = tmp_path / "prog.py"
    prog.write_text(program)
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(extra_env)
    out = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", str(n), "--first-port", str(first_port),
            sys.executable, str(prog),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, (
        f"spawn failed:\nstdout={out.stdout}\nstderr={out.stderr}"
    )


def test_spawn_n2_commit_trace_merges_into_one_tree_and_cli_names_critical_path(
    tmp_path,
):
    """THE tracing acceptance: after a clean n=2 run, the merged rank files
    hold at least one trace whose commit spans come from BOTH ranks (the
    deterministic trace id needs no wire coordination), whose child spans all
    parent inside the trace, and ``cli trace`` names its critical path."""
    from pathway_tpu.engine.tracing import merge_trace_files

    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    for p in range(2):
        (tmp_path / f"input_{p}.json").write_text(
            json.dumps([f"word{i % 17}" for i in range(160)])
        )
    first_port = 21000 + os.getpid() % 400 * 4
    _spawn_blocking(
        2, TRACED_WORDCOUNT_PROG, tmp_path, _trace_env(trace_dir), first_port
    )

    paths = sorted(str(p) for p in trace_dir.glob("trace-rank-*.jsonl"))
    assert len(paths) == 2, f"expected both rank flushes, got {paths}"
    merged = merge_trace_files(paths)
    spans = merged["spans"]
    assert spans, "no spans in either rank flush"

    by_trace: dict = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    # at least one commit trace with commit spans from BOTH ranks
    shared = {
        tid: ss
        for tid, ss in by_trace.items()
        if {s["rank"] for s in ss if s["kind"] == "commit"} == {0, 1}
    }
    assert shared, (
        "no trace holds commit spans from both ranks — the deterministic "
        f"(epoch, commit) trace id broke; kinds seen: "
        f"{sorted({s['kind'] for s in spans})}"
    )
    tid, tree_spans = next(iter(sorted(shared.items())))
    ids = {s["span_id"] for s in tree_spans}
    dangling = [
        s for s in tree_spans
        if s["parent_id"] is not None and s["parent_id"] not in ids
    ]
    assert not dangling, f"spans parented OUTSIDE their own trace: {dangling}"
    # the commit spans have real children (operator / barrier substeps)
    child_kinds = {
        s["kind"] for s in tree_spans if s["parent_id"] is not None
    }
    assert child_kinds, f"commit spans have no children in trace {tid}"

    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "trace",
            str(trace_dir), "--limit", "2",
        ],
        env=env, capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, f"cli trace failed:\n{out.stdout}\n{out.stderr}"
    assert "critical path:" in out.stdout, out.stdout
    # the critical-path line names a registered span kind
    from pathway_tpu.engine.telemetry import TRACE_SPAN_KINDS

    assert any(k in out.stdout for k in TRACE_SPAN_KINDS), out.stdout


TRACED_STREAMING_PROG = textwrap.dedent(
    """
    import os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema,
        mode="streaming",
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    pw.io.subscribe(counts, lambda key, row, time, is_addition: None)
    cfg = pw.persistence.Config(
        pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
    )
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    """
)


@pytest.mark.chaos
def test_spawn_n2_chaos_killed_rank_leaves_partial_trace_in_flight_dump(
    tmp_path,
):
    """SIGKILL rank 1 mid-run: the black box written just before the kill
    must embed rank 1's newest spans (commit spans with the shared trace id),
    and the merger accepts the flight dump as a trace source — the dead
    rank's side of the story survives its death."""
    from pathway_tpu.engine.tracing import load_flight_spans, merge_trace_files

    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    (tmp_path / "in").mkdir()
    for i in range(2):
        (tmp_path / "in" / f"a{i}.csv").write_text(
            "word\n" + "\n".join(["cat"] * (i + 2) + ["dog"] * 3) + "\n"
        )
    prog = tmp_path / "prog.py"
    prog.write_text(TRACED_STREAMING_PROG)

    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(_trace_env(trace_dir))
    env["PATHWAY_CHAOS_SEED"] = "7"
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(
        {"kill": [{"rank": 1, "commit": 2, "run": 0}]}
    )
    env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
    env["PATHWAY_BARRIER_TIMEOUT_S"] = "30"
    first_port = 21000 + os.getpid() % 400 * 4 + 2
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(first_port),
            "--max-restarts", "1",
            sys.executable, str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        start_new_session=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )
    dump_path = trace_dir / "flight-rank-1.json"
    killed_payload = None
    try:
        deadline = time.time() + 150
        while time.time() < deadline:
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise AssertionError(
                    f"spawn exited early (rc={proc.returncode}): {err}"
                )
            if dump_path.exists():
                try:
                    payload = json.loads(dump_path.read_text())
                except ValueError:
                    payload = None  # racing the atomic rename
                if payload and payload.get("reason") == "chaos_kill":
                    killed_payload = payload
                    break
            time.sleep(0.3)
    finally:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.communicate()

    assert killed_payload is not None, "chaos_kill flight dump never appeared"
    spans = (killed_payload.get("trace") or {}).get("spans") or []
    assert spans, "killed rank's flight dump embeds no spans"
    assert any(s["kind"] == "commit" and s["rank"] == 1 for s in spans), (
        f"no rank-1 commit span in the dump; kinds: "
        f"{sorted({s['kind'] for s in spans})}"
    )
    # the merger accepts the dump as a trace source (partial-trace guarantee)
    flight_spans = load_flight_spans(str(dump_path))
    assert flight_spans, "merger read no spans back from the flight dump"
    merged = merge_trace_files([], flight_paths=[str(dump_path)])
    assert any(s["rank"] == 1 for s in merged["spans"])
