"""Repo-level lint gate: ``ruff check`` over the whole tree (config in
ruff.toml — critical rules only). Runs when a ruff binary is available and
skips cleanly when not (the CI image may not ship it)."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ruff_command() -> "list[str] | None":
    binary = shutil.which("ruff")
    candidates = [[binary]] if binary else []
    candidates.append([sys.executable, "-m", "ruff"])
    for cmd in candidates:
        try:
            probe = subprocess.run(
                [*cmd, "--version"], capture_output=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if probe.returncode == 0:
            return cmd
    return None


def test_ruff_critical_gate():
    cmd = _ruff_command()
    if cmd is None:
        pytest.skip("ruff is not installed in this environment")
    proc = subprocess.run(
        [*cmd, "check", "--no-cache", REPO],
        capture_output=True,
        text=True,
        timeout=300,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
