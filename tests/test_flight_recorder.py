"""Flight recorder end-to-end: a chaos-killed cluster leaves a dump whose last
profile is the commit before the kill (the SIGKILL itself is uncatchable — the
chaos harness dumps pre-kill), the supervisor post-mortem names the dump, and a
SIGTERM'd worker dumps from its signal hook."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STREAM_PROG = textwrap.dedent(
    """
    import os
    import pathway_tpu as pw

    tmp = os.environ["PATHWAY_TPU_TEST_DIR"]

    class WordSchema(pw.Schema):
        word: str

    t = pw.io.fs.read(
        os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming"
    )
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    pw.io.subscribe(counts, lambda *a, **k: None)
    open(os.path.join(tmp, f"ready-{os.environ.get('PATHWAY_PROCESS_ID', '0')}"), "w").close()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    """
)


def _base_env(tmp_path) -> dict:
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATHWAY_TPU_TEST_DIR"] = str(tmp_path)
    env["PATHWAY_FLIGHT_RECORDER_DIR"] = str(tmp_path / "flight")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


@pytest.mark.chaos
@pytest.mark.telemetry
def test_chaos_kill_leaves_flight_record_and_post_mortem_names_it(tmp_path):
    """A kill at commit k yields a recorder dump whose last profile is commit
    k-1, and the supervisor post-mortem attaches the dump path + summary."""
    (tmp_path / "in").mkdir()
    (tmp_path / "flight").mkdir()
    (tmp_path / "in" / "a.csv").write_text("word\ncat\ndog\ncat\n")
    first_port = 27000 + os.getpid() % 500 * 4
    kill_commit = 3
    env = _base_env(tmp_path)
    env["PATHWAY_CHAOS_SEED"] = "1"
    env["PATHWAY_CHAOS_PLAN"] = json.dumps(
        {"kill": [{"rank": 0, "commit": kill_commit, "run": 0}]}
    )
    prog = tmp_path / "prog.py"
    prog.write_text(STREAM_PROG)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "--first-port", str(first_port),
            sys.executable, str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        _, err = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
        _, err = proc.communicate()
        raise AssertionError(f"spawn hung after the chaos kill:\n{err}")
    assert proc.returncode != 0

    dump_path = tmp_path / "flight" / "flight-rank-0.json"
    assert dump_path.exists(), f"no flight dump after the chaos kill:\n{err}"
    payload = json.loads(dump_path.read_text())
    assert payload["reason"] == "chaos_kill"
    assert payload["profiles"], "the ring must hold pre-kill commits"
    assert payload["profiles"][-1]["commit"] == kill_commit - 1, (
        "last recorded profile must be the commit BEFORE the kill"
    )
    assert payload["summary"]["last_commit"] == kill_commit - 1
    assert payload["events"][-1]["kind"] == "chaos_kill"
    # every profile carries per-operator entries (ops may be empty only for
    # idle commits; the ingest commit is not idle)
    assert any(p["ops"] for p in payload["profiles"])

    # the supervisor post-mortem attaches the dump path + one-line summary
    assert "flight recorder" in err, err
    assert str(dump_path) in err
    assert f"last commit {kill_commit - 1}" in err


@pytest.mark.telemetry
def test_sigterm_dumps_flight_record(tmp_path):
    (tmp_path / "in").mkdir()
    (tmp_path / "flight").mkdir()
    (tmp_path / "in" / "a.csv").write_text("word\ncat\ndog\n")
    env = _base_env(tmp_path)
    prog = tmp_path / "prog.py"
    prog.write_text(STREAM_PROG)
    proc = subprocess.Popen(
        [sys.executable, str(prog)],
        env=env,
        cwd=str(tmp_path),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        deadline = time.time() + 60
        ready = tmp_path / "ready-0"
        while time.time() < deadline and not ready.exists():
            assert proc.poll() is None, proc.communicate()[1]
            time.sleep(0.05)
        assert ready.exists(), "program never reached pw.run"
        time.sleep(1.0)  # let the commit loop turn a few times
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert proc.returncode != 0  # SIGTERM re-raised after the dump
    dump_path = tmp_path / "flight" / "flight-rank-0.json"
    assert dump_path.exists(), proc.stderr.read() if proc.stderr else ""
    payload = json.loads(dump_path.read_text())
    assert payload["reason"] == "sigterm"
    assert payload["profiles"]
