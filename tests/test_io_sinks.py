"""Sink + airbyte connectors against injected fakes (VERDICT r2 padded-files list:
mongodb/bigquery/pubsub/slack/logstash/airbyte become real client code paths,
unit-tested with fakes — reference ``data_storage.rs:2232``, ``io/bigquery``,
``io/pubsub``, ``io/slack``, ``io/logstash``, ``io/airbyte``)."""

from __future__ import annotations

import json
import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg


def _run():
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)


def _source_table():
    return pw.debug.table_from_rows(
        pw.schema_builder({"name": str, "age": int}),
        [("Alice", 10), ("Bob", 9), ("Carol", 8)],
    )


# -- mongodb ----------------------------------------------------------------------


class FakeMongoCollection:
    def __init__(self):
        self.docs: list[dict] = []

    def insert_many(self, docs):
        self.docs.extend(docs)


class FakeMongoClient:
    def __init__(self):
        self.coll = FakeMongoCollection()
        self.closed = False

    def __getitem__(self, name):
        return {"c": self.coll}.get("c") and {"coll": self.coll} and _FakeDb(self.coll)

    def close(self):
        self.closed = True


class _FakeDb:
    def __init__(self, coll):
        self._coll = coll

    def __getitem__(self, name):
        return self._coll


def test_mongodb_write_batches_documents():
    pg.G.clear()
    t = _source_table()
    client = FakeMongoClient()
    pw.io.mongodb.write(t, "mongodb://unused", "db", "people", _client=client)
    _run()
    assert sorted(d["name"] for d in client.coll.docs) == ["Alice", "Bob", "Carol"]
    assert all(d["diff"] == 1 for d in client.coll.docs)
    assert client.closed


# -- bigquery ---------------------------------------------------------------------


class FakeBQClient:
    project = "proj"

    def __init__(self, fail=False):
        self.rows: list[tuple[str, dict]] = []
        self.fail = fail

    def insert_rows_json(self, target, rows):
        if self.fail:
            return [{"index": 0, "errors": ["boom"]}]
        self.rows.extend((target, r) for r in rows)
        return []


def test_bigquery_write_streams_rows():
    pg.G.clear()
    t = _source_table()
    client = FakeBQClient()
    pw.io.bigquery.write(t, "ds", "tbl", _client=client)
    _run()
    assert len(client.rows) == 3
    assert all(target == "proj.ds.tbl" for target, _ in client.rows)
    assert sorted(r["age"] for _, r in client.rows) == [8, 9, 10]


def test_bigquery_write_surfaces_insert_errors():
    pg.G.clear()
    t = _source_table()
    pw.io.bigquery.write(t, "ds", "tbl", _client=FakeBQClient(fail=True))
    with pytest.raises(Exception, match="BigQuery insert failed"):
        _run()


# -- pubsub -----------------------------------------------------------------------


class FakeFuture:
    def __init__(self):
        self.waited = False

    def result(self, timeout=None):
        self.waited = True


class FakePublisher:
    def __init__(self):
        self.published: list[tuple[str, bytes]] = []
        self.futures: list[FakeFuture] = []

    def topic_path(self, project, topic):
        return f"projects/{project}/topics/{topic}"

    def publish(self, topic_path, data):
        self.published.append((topic_path, data))
        fut = FakeFuture()
        self.futures.append(fut)
        return fut


def test_pubsub_write_publishes_and_flushes():
    pg.G.clear()
    t = _source_table()
    publisher = FakePublisher()
    pw.io.pubsub.write(t, publisher, "proj", "topic")
    _run()
    assert len(publisher.published) == 3
    path, payload = publisher.published[0]
    assert path == "projects/proj/topics/topic"
    assert json.loads(payload)["diff"] == 1
    assert all(f.waited for f in publisher.futures)  # on_end blocked on delivery


# -- slack + logstash (HTTP sinks against a local server) -------------------------


class _Recorder:
    def __init__(self):
        self.requests: list[dict] = []


def _local_http_server(recorder: _Recorder):
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            recorder.requests.append(
                {
                    "path": self.path,
                    "auth": self.headers.get("Authorization"),
                    "body": json.loads(body) if body else None,
                }
            )
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b'{"ok": true}')

        def log_message(self, *args):
            pass

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def test_slack_send_alerts_posts_messages():
    recorder = _Recorder()
    server = _local_http_server(recorder)
    try:
        pg.G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_builder({"msg": str}), [("alert one",), ("alert two",)]
        )
        pw.io.slack.send_alerts(
            t.msg,
            "C123",
            "xoxb-token",
            api_url=f"http://127.0.0.1:{server.server_port}/api/chat.postMessage",
        )
        _run()
        assert len(recorder.requests) == 2
        req = recorder.requests[0]
        assert req["auth"] == "Bearer xoxb-token"
        assert req["body"]["channel"] == "C123"
        assert {r["body"]["text"] for r in recorder.requests} == {
            "alert one",
            "alert two",
        }
    finally:
        server.shutdown()


def test_logstash_write_posts_documents():
    recorder = _Recorder()
    server = _local_http_server(recorder)
    try:
        pg.G.clear()
        t = _source_table()
        pw.io.logstash.write(t, f"http://127.0.0.1:{server.server_port}/")
        _run()
        assert len(recorder.requests) == 3
        assert sorted(r["body"]["name"] for r in recorder.requests) == [
            "Alice",
            "Bob",
            "Carol",
        ]
    finally:
        server.shutdown()


# -- airbyte (protocol fake) ------------------------------------------------------


class FakeAirbyteProcess:
    def __init__(self, lines: list[str]):
        self.stdout = iter(lines)

    def wait(self):
        return 0


def _airbyte_config(tmp_path):
    cfg = tmp_path / "connection.yaml"
    cfg.write_text(
        json.dumps(
            {"source": {"executable": "fake-source", "config": {"seed": 7}}}
        )
    )
    return str(cfg)


def test_airbyte_read_records_and_state(tmp_path):
    protocol = [
        json.dumps({"type": "LOG", "log": {"level": "INFO", "message": "hi"}}),
        json.dumps(
            {"type": "RECORD", "record": {"stream": "users", "data": {"id": 1, "n": "a"}}}
        ),
        json.dumps(
            {"type": "RECORD", "record": {"stream": "skipme", "data": {"id": 9}}}
        ),
        "free-form log line",
        json.dumps(
            {"type": "RECORD", "record": {"stream": "users", "data": {"id": 2, "n": "b"}}}
        ),
        json.dumps({"type": "STATE", "state": {"cursor": 2}}),
    ]
    seen_cmds: list[list[str]] = []

    def factory(cmd, env):
        seen_cmds.append(cmd)
        return FakeAirbyteProcess(protocol)

    pg.G.clear()
    t = pw.io.airbyte.read(
        _airbyte_config(tmp_path),
        streams=["users"],
        mode="static",
        _process_factory=factory,
    )
    got = []
    pw.io.subscribe(
        t, lambda key, row, time, is_addition: got.append(row["data"].value)
    )
    _run()
    assert sorted(d["id"] for d in got) == [1, 2]  # 'skipme' stream filtered out
    (cmd,) = seen_cmds
    assert cmd[0] == "fake-source" and cmd[1] == "read"
    # the configured catalog requested exactly the selected stream, incremental
    cat_path = cmd[cmd.index("--catalog") + 1]
    # workdir is deleted after the sync; the command shape is the contract here
    assert cat_path.endswith("catalog.json")


def test_airbyte_resumes_from_state(tmp_path):
    """A restored STATE blob must reach the next read via --state."""
    from pathway_tpu.io.airbyte import _AirbyteSubject

    state_files: list[dict] = []

    def factory(cmd, env):
        if "--state" in cmd:
            with open(cmd[cmd.index("--state") + 1]) as f:
                state_files.append(json.load(f))
        return FakeAirbyteProcess(
            [json.dumps({"type": "STATE", "state": {"cursor": 5}})]
        )

    subject = _AirbyteSubject(
        factory, {"executable": "fake", "config": {}}, ["s"], "static", 1.0, None
    )
    subject.restore([{"state": {"cursor": 3}}])

    class _Src:
        def push(self, *a, **kw):
            pass

        def push_state(self, *a, **kw):
            pass

    subject.run(_Src())
    assert state_files == [{"cursor": 3}]
    # and the newest state wins the fold
    assert _AirbyteSubject.fold_state_deltas(
        [{"state": {"cursor": 3}}, {"state": {"cursor": 5}}]
    ) == [{"state": {"cursor": 5}}]


def test_airbyte_surfaces_trace_errors(tmp_path):
    def factory(cmd, env):
        return FakeAirbyteProcess(
            [
                json.dumps(
                    {
                        "type": "TRACE",
                        "trace": {"type": "ERROR", "error": {"message": "cred bad"}},
                    }
                )
            ]
        )

    pg.G.clear()
    t = pw.io.airbyte.read(
        _airbyte_config(tmp_path),
        streams=["users"],
        mode="static",
        _process_factory=factory,
    )
    pw.io.subscribe(t, lambda *a, **kw: None)
    with pytest.raises(Exception, match="cred bad"):
        _run()


def test_airbyte_docker_command_forwards_env(tmp_path):
    from pathway_tpu.io.airbyte import _build_command

    cmd = _build_command(
        {"docker_image": "airbyte/source-faker"},
        "/w/config.json",
        "/w/catalog.json",
        None,
        {"API_KEY": "x", "A": "1"},
    )
    assert cmd[:4] == ["docker", "run", "--rm", "-i"]
    # env forwarded INTO the container, deterministic order
    assert cmd[4:8] == ["-e", "A", "-e", "API_KEY"]
    assert cmd[-5:] == ["read", "--config", "/w/config.json", "--catalog", "/w/catalog.json"]
