"""Deterministic fake embedders/LLMs (parity: reference ``xpacks/llm/tests/mocks.py``)."""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np

from pathway_tpu.internals.json import Json
from pathway_tpu.internals.udfs import UDF


def fake_embedding(text: str, dim: int = 16) -> np.ndarray:
    """Deterministic unit vector per text; similar prefixes do NOT imply similarity — exact
    text match gives identical vectors, which is what index tests need."""
    digest = hashlib.sha256(str(text).encode()).digest()
    rng = np.random.default_rng(int.from_bytes(digest[:8], "little"))
    v = rng.normal(size=dim).astype(np.float32)
    return v / np.linalg.norm(v)


class FakeEmbedder(UDF):
    def __init__(self, dim: int = 16, **kwargs: Any):
        super().__init__(**kwargs)
        self.dim = dim

        def embed(text: str) -> np.ndarray:
            return fake_embedding(text, self.dim)

        self.func = embed

    def get_embedding_dimension(self, **kwargs: Any) -> int:
        return self.dim


class FakeChat(UDF):
    """Echoes the last user message back, prefixed — deterministic."""

    def __init__(self, prefix: str = "ANSWER:", **kwargs: Any):
        super().__init__(**kwargs)
        self.prefix = prefix

        def chat(messages: Any, **kw: Any) -> str:
            if isinstance(messages, Json):
                messages = messages.value
            if isinstance(messages, str):
                content = messages
            else:
                content = messages[-1]["content"]
            return f"{self.prefix}{content[-80:]}"

        self.func = chat


class _DirS3Body:
    def __init__(self, data: bytes):
        self._data = data

    def read(self) -> bytes:
        return self._data


class DirS3Client:
    """boto3 S3 client surface backed by a local directory — objects survive
    process kills (PUT = atomic temp+rename), so cross-process persistence
    torture tests can exercise the real S3 code path hermetically."""

    def __init__(self, root: str, page_size: int = 100):
        import os

        self.root = str(root)
        self.page_size = page_size
        os.makedirs(self.root, exist_ok=True)

    def _path(self, bucket: str, key: str) -> str:
        import os

        return os.path.join(self.root, bucket, key)

    def put_object(self, Bucket: str, Key: str, Body: bytes) -> dict:
        import os

        path = self._path(Bucket, Key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp-put"
        with open(tmp, "wb") as f:
            f.write(Body if isinstance(Body, bytes) else Body.read())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return {}

    def get_object(self, Bucket: str, Key: str) -> dict:
        with open(self._path(Bucket, Key), "rb") as f:
            return {"Body": _DirS3Body(f.read())}

    def delete_object(self, Bucket: str, Key: str) -> dict:
        import os

        try:
            os.unlink(self._path(Bucket, Key))
        except OSError:
            pass
        return {}

    def list_objects_v2(self, Bucket: str, Prefix: str, ContinuationToken=None) -> dict:
        import os

        base = os.path.join(self.root, Bucket)
        keys = []
        for dirpath, _dirs, files in os.walk(base):
            for name in files:
                if name.endswith(".tmp-put"):
                    continue
                full = os.path.join(dirpath, name)
                key = os.path.relpath(full, base).replace(os.sep, "/")
                if key.startswith(Prefix):
                    keys.append(key)
        keys.sort()
        start = int(ContinuationToken) if ContinuationToken else 0
        page = keys[start : start + self.page_size]
        truncated = start + self.page_size < len(keys)
        out = {
            "Contents": [
                {"Key": k, "Size": os.path.getsize(self._path(Bucket, k))} for k in page
            ],
            "IsTruncated": truncated,
        }
        if truncated:
            out["NextContinuationToken"] = str(start + self.page_size)
        return out
