"""Deterministic fake embedders/LLMs (parity: reference ``xpacks/llm/tests/mocks.py``)."""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np

from pathway_tpu.internals.json import Json
from pathway_tpu.internals.udfs import UDF


def fake_embedding(text: str, dim: int = 16) -> np.ndarray:
    """Deterministic unit vector per text; similar prefixes do NOT imply similarity — exact
    text match gives identical vectors, which is what index tests need."""
    digest = hashlib.sha256(str(text).encode()).digest()
    rng = np.random.default_rng(int.from_bytes(digest[:8], "little"))
    v = rng.normal(size=dim).astype(np.float32)
    return v / np.linalg.norm(v)


class FakeEmbedder(UDF):
    def __init__(self, dim: int = 16, **kwargs: Any):
        super().__init__(**kwargs)
        self.dim = dim

        def embed(text: str) -> np.ndarray:
            return fake_embedding(text, self.dim)

        self.func = embed

    def get_embedding_dimension(self, **kwargs: Any) -> int:
        return self.dim


class FakeChat(UDF):
    """Echoes the last user message back, prefixed — deterministic."""

    def __init__(self, prefix: str = "ANSWER:", **kwargs: Any):
        super().__init__(**kwargs)
        self.prefix = prefix

        def chat(messages: Any, **kw: Any) -> str:
            if isinstance(messages, Json):
                messages = messages.value
            if isinstance(messages, str):
                content = messages
            else:
                content = messages[-1]["content"]
            return f"{self.prefix}{content[-80:]}"

        self.func = chat
