"""Distributed tracing against a LIVE REST route (always-on tier-1): the
``X-Pathway-Trace`` header echoes on every response, the route's span parents
to the caller's context, and a coalesced encoder tick links the N query spans
whose texts it batched (the fan-in edge ``cli trace`` renders).

Lives at the end of the suite's alphabetical order on purpose — these tests
start a real ``pw.run`` engine behind a REST connector, and streaming REST
sources run forever (daemon threads); see ``test_zz_brownout_serving.py``.
"""

from __future__ import annotations

import json
import socket
import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine import tracing
from pathway_tpu.engine.tracing import (
    TRACE_HEADER,
    get_tracer,
    parse_trace_header,
    reset_tracing,
)
from pathway_tpu.internals.parse_graph import G

pytestmark = pytest.mark.trace

_PORT = 18803


@pytest.fixture(autouse=True)
def _always_sample(monkeypatch):
    monkeypatch.setenv("PATHWAY_TRACE", "on")
    monkeypatch.setenv("PATHWAY_TRACE_SAMPLE", "1.0")
    reset_tracing()
    yield
    # env is still patched "on" here — reset alone would leave the global
    # tracer live for whatever outlives this module (daemon engine threads)
    reset_tracing()
    get_tracer().enabled = False


_started = threading.Event()


def _ensure_server():
    """One echo engine for the whole module (REST sources stream forever)."""
    if _started.is_set():
        return
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    G.clear()
    ws = PathwayWebserver(host="127.0.0.1", port=_PORT)

    class Q(pw.Schema):
        text: str

    queries, writer = rest_connector(
        webserver=ws, route="/v1/retrieve", schema=Q,
        max_pending=64, delete_completed_queries=True,
        autocommit_duration_ms=25,
    )
    writer(queries.select(result=pw.this.text))
    threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE),
        daemon=True,
    ).start()
    deadline = time.monotonic() + 20
    while True:
        try:
            socket.create_connection(("127.0.0.1", _PORT), timeout=1).close()
            _started.set()
            return
        except OSError:
            assert time.monotonic() < deadline, "REST server never came up"
            time.sleep(0.2)


def _post(text: str, *, trace: "str | None" = None, timeout: float = 30.0):
    """POST one query; returns (status, response_headers)."""
    import urllib.error
    import urllib.request

    headers = {"Content-Type": "application/json"}
    if trace is not None:
        headers[TRACE_HEADER] = trace
    req = urllib.request.Request(
        f"http://127.0.0.1:{_PORT}/v1/retrieve",
        data=json.dumps({"text": text}).encode(),
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status, dict(r.headers)
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code, dict(exc.headers)


def test_zz_rest_echoes_trace_header_and_parents_the_route_span():
    _ensure_server()
    sent_trace, sent_span = "ab" * 8, "12" * 8
    status, headers = _post(
        "trace echo probe", trace=f"{sent_trace}-{sent_span}-01"
    )
    assert status == 200
    echoed = parse_trace_header(headers.get(TRACE_HEADER))
    assert echoed is not None, headers
    # same trace id, NEW span id (the route's own span), sampled flag kept
    assert echoed.trace_id == sent_trace
    assert echoed.span_id != sent_span
    assert echoed.sampled is True
    spans = [
        s for s in get_tracer().recent_spans(limit=4096)
        if s["trace_id"] == sent_trace
    ]
    assert spans, "route span never reached the ring"
    rest = next(s for s in spans if s["kind"] == "rest")
    assert rest["parent_id"] == sent_span  # child of the CALLER's span
    assert rest["span_id"] == echoed.span_id
    assert rest["attrs"]["route"] == "/v1/retrieve"
    assert rest["attrs"]["status"] == 200


def test_zz_headerless_request_still_gets_a_trace_id():
    _ensure_server()
    status, headers = _post("no inbound header")
    assert status == 200
    minted = parse_trace_header(headers.get(TRACE_HEADER))
    assert minted is not None, headers
    assert minted.sampled is True  # PATHWAY_TRACE_SAMPLE=1.0 head decision


def test_zz_coalesced_encode_tick_links_the_batched_query_spans():
    """Two REST queries register their span contexts under their texts; the
    encoder tick that batches those texts drains the registry and emits ONE
    ``encode`` span linking BOTH parents — the coalesced fan-in edge."""
    from pathway_tpu.models.encoder_service import EncoderService

    _ensure_server()
    text_a, text_b = "coalesce probe alpha", "coalesce probe beta"
    status_a, headers_a = _post(text_a, trace="aa" * 8 + "-" + "01" * 8 + "-01")
    status_b, headers_b = _post(text_b, trace="bb" * 8 + "-" + "02" * 8 + "-01")
    assert status_a == 200 and status_b == 200
    parent_a = parse_trace_header(headers_a[TRACE_HEADER])
    parent_b = parse_trace_header(headers_b[TRACE_HEADER])

    class _HashEncoder:
        dim = 8

        def encode_device(self, texts):
            rows = [
                np.frombuffer(
                    str(t).encode().ljust(8, b"\0")[:8], dtype=np.uint8
                ).astype(np.float32)
                for t in texts
            ]
            return np.stack(rows)

    svc = EncoderService(_HashEncoder(), prewarm=False)
    try:
        out = svc.submit([text_a, text_b])
        assert len(out) == 2
    finally:
        svc.close()
    encodes = [
        s for s in get_tracer().recent_spans(limit=4096)
        if s["kind"] == "encode"
    ]
    assert encodes, "encode tick span never reached the ring"
    linked = {
        link["span_id"] for span in encodes for link in span["links"]
    }
    # the tick links the ROUTE spans the queries got (their echoed span ids)
    assert parent_a.span_id in linked and parent_b.span_id in linked
    span = next(
        s for s in encodes
        if {l["span_id"] for l in s["links"]}
        >= {parent_a.span_id, parent_b.span_id}
    )
    assert span["attrs"]["unique"] == 2


def test_zz_trace_current_context_does_not_leak_between_requests():
    # the route wrapper resets the contextvar: after serving, no ambient span
    assert tracing.current_context() is None
