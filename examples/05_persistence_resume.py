"""Persistence: journaled inputs + resume with exact recovery.

Run one: ingest two files, record the journal, stop. Run two (same store):
resume WITHOUT re-reading finished inputs, pick up a new file, exact totals.
This script simulates both runs in one process via two separate graphs."""

import os
import tempfile

import pathway_tpu as pw
from pathway_tpu.internals import parse_graph as pg


class WordSchema(pw.Schema):
    word: str


def run_once(input_dir: str, store: str) -> dict:
    pg.G.clear()
    t = pw.io.fs.read(input_dir, format="csv", schema=WordSchema, mode="static")
    counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
    got = {}
    pw.io.subscribe(
        counts,
        lambda key, row, time, is_addition: got.__setitem__(row["word"], row["total"])
        if is_addition
        else got.pop(row["word"], None),
    )
    cfg = pw.persistence.Config(pw.persistence.Backend.filesystem(store))
    pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
    return got


with tempfile.TemporaryDirectory() as tmp:
    input_dir = os.path.join(tmp, "in")
    store = os.path.join(tmp, "store")
    os.makedirs(input_dir)

    with open(os.path.join(input_dir, "a.csv"), "w") as f:
        f.write("word\ncat\ncat\ndog\n")
    first = run_once(input_dir, store)
    print("run 1:", first)
    assert first == {"cat": 2, "dog": 1}

    # new data lands while the pipeline is down
    with open(os.path.join(input_dir, "b.csv"), "w") as f:
        f.write("word\ncat\nowl\n")
    second = run_once(input_dir, store)
    print("run 2 (resumed):", second)
    assert second == {"cat": 3, "dog": 1, "owl": 1}
    print("OK")
