"""Streaming wordcount: the smallest end-to-end incremental pipeline.

An update stream feeds a groupby/reduce; every commit delivers exactly the
CHANGES to the counts (insertions and retractions), not a recomputation."""

import pathway_tpu as pw

# __time__ groups rows into commits; __diff__ = +1 insert / -1 retract
words = pw.debug.table_from_markdown(
    """
    word | __time__ | __diff__
    cat  | 0        | 1
    dog  | 0        | 1
    cat  | 2        | 1
    dog  | 4        | -1
    """
)

counts = words.groupby(pw.this.word).reduce(
    pw.this.word, n=pw.reducers.count()
)

events = []
pw.io.subscribe(
    counts,
    lambda key, row, time, is_addition: events.append(
        (row["word"], row["n"], "+" if is_addition else "-")
    ),
)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)

for word, n, sign in events:
    print(f"{sign} {word}={n}")

# final state: cat=2; dog was inserted then fully retracted
final = {}
for word, n, sign in events:
    if sign == "+":
        final[word] = n
    elif final.get(word) == n:
        del final[word]
assert final == {"cat": 2}, final
print("OK:", final)
