"""Tumbling windows with temporal behaviors: late data, buffering, cutoffs.

``common_behavior(delay, cutoff)`` postpones a window's emission until the
stream's time passes start+delay (so early results don't churn) and drops rows
arriving later than cutoff past the window (bounded memory — the engine can
forget closed windows)."""

import pathway_tpu as pw

readings = pw.debug.table_from_markdown(
    """
    sensor | t  | value | __time__ | __diff__
    1      | 2  | 10    | 0        | 1
    1      | 7  | 20    | 0        | 1
    2      | 3  | 5     | 0        | 1
    1      | 13 | 40    | 2        | 1
    1      | 4  | 30    | 2        | 1
    2      | 25 | 9     | 4        | 1
    1      | 38 | 1     | 6        | 1
    """
)

stats = readings.windowby(
    readings.t,
    window=pw.temporal.tumbling(duration=10),
    instance=readings.sensor,
    behavior=pw.temporal.common_behavior(delay=2, cutoff=30, keep_results=True),
).reduce(
    sensor=pw.this._pw_instance,
    start=pw.this._pw_window_start,
    total=pw.reducers.sum(pw.this.value),
    n=pw.reducers.count(),
)

got = {}
pw.io.subscribe(
    stats,
    lambda key, row, time, is_addition: got.__setitem__(
        (row["sensor"], row["start"]), (row["total"], row["n"])
    )
    if is_addition
    else got.pop((row["sensor"], row["start"]), None),
)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
print(got)
# sensor 1 window [0,10): rows t=2,7 plus the LATE row t=4 (arrived while still
# under the cutoff) -> total 60; window [10,20): t=13 -> 40; [30,40): t=38 -> 1
assert got[(1, 0)] == (60, 3)
assert got[(1, 10)] == (40, 1)
print("OK")
