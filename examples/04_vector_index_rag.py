"""Live vector retrieval: a KNN index over a document table.

On a TPU the score matrix runs on the MXU over an HBM-resident store; on CPU
the same code runs through XLA's CPU backend. The index is INCREMENTAL — the
retrieval below sees a document that arrives after the first commit."""

import numpy as np

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.index import KNNIndex

rng = np.random.default_rng(0)
base = {
    "getting started guide": [9.0, 1.0, 0.0, 0.0],
    "billing and invoices": [0.0, 9.0, 1.0, 0.0],
    "api reference": [0.0, 0.0, 9.0, 1.0],
}
docs = pw.debug.table_from_rows(
    pw.schema_builder({"title": str, "vec": np.ndarray}),
    [(t, np.asarray(v, dtype=np.float32)) for t, v in base.items()],
)

queries = pw.debug.table_from_rows(
    pw.schema_builder({"q": str, "qvec": np.ndarray}),
    [("how do I pay?", np.asarray([0.5, 8.0, 1.0, 0.0], dtype=np.float32))],
)

res = KNNIndex(docs.vec, docs, n_dimensions=4).get_nearest_items(
    queries.qvec, k=2
)
got = {}
pw.io.subscribe(
    res,
    lambda key, row, time, is_addition: got.__setitem__("titles", row["title"]),
)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
print(got)
assert got["titles"][0] == "billing and invoices"
print("OK")
