"""Multi-process scale-out: ``pathway_tpu spawn -n 2`` with exact global counts.

Each spawned process ingests its own shard; the cluster exchange hash-routes
rows so every group is owned by exactly one process and the merged answer is
exact. This driver script launches the spawn and checks the merged output.

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python examples/06_multiprocess_spawn.py
"""

import collections
import json
import os
import subprocess
import sys
import tempfile
import textwrap

PROG = textwrap.dedent(
    """
    import json, os
    import pathway_tpu as pw

    tmp = os.environ["EXAMPLE_DIR"]
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    words = json.load(open(os.path.join(tmp, f"shard_{pid}.json")))
    t = pw.debug.table_from_rows(pw.schema_builder({"word": str}), [(w,) for w in words])
    counts = t.groupby(t.word).reduce(t.word, n=pw.reducers.count())
    got = {}
    pw.io.subscribe(
        counts,
        lambda key, row, time, is_addition: got.__setitem__(row["word"], row["n"])
        if is_addition
        else got.pop(row["word"], None),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    json.dump(got, open(os.path.join(tmp, f"out_{pid}.json"), "w"))
    """
)

with tempfile.TemporaryDirectory() as tmp:
    shards = {0: ["cat", "dog", "cat"], 1: ["cat", "owl"]}
    for pid, words in shards.items():
        with open(os.path.join(tmp, f"shard_{pid}.json"), "w") as f:
            json.dump(words, f)
    prog = os.path.join(tmp, "prog.py")
    with open(prog, "w") as f:
        f.write(PROG)
    env = {**os.environ, "EXAMPLE_DIR": tmp}
    subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "spawn", "-n", "2",
         "--first-port", "27300", sys.executable, prog],
        env=env, check=True, timeout=180,
    )
    merged = collections.Counter()
    for pid in shards:
        with open(os.path.join(tmp, f"out_{pid}.json")) as f:
            merged.update(json.load(f))
    print("merged:", dict(merged))
    assert dict(merged) == {"cat": 3, "dog": 1, "owl": 1}
    print("OK")
