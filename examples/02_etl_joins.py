"""Incremental joins: probe-side streaming, build-side churn, asof joins.

The defining obligation of an incremental join: when a build-side row changes,
every previously-emitted joined row retracts and re-emits with the new value —
without reprocessing the probe side."""

import pathway_tpu as pw

orders = pw.debug.table_from_markdown(
    """
    sku | qty | __time__ | __diff__
    a   | 2   | 0        | 1
    b   | 1   | 0        | 1
    a   | 5   | 2        | 1
    """
)
# the price of sku 'a' changes at time 4 — AFTER all its orders arrived
prices = pw.debug.table_from_markdown(
    """
    psku | price | __time__ | __diff__
    a    | 10    | 0        | 1
    b    | 7     | 0        | 1
    a    | 10    | 4        | -1
    a    | 12    | 4        | 1
    """
)

lines = orders.join(prices, orders.sku == prices.psku).select(
    orders.sku, total=orders.qty * prices.price
)
pw.debug.compute_and_print_update_stream(lines)
# the time-4 price change retracts both 'a' order lines and re-emits them at 12

# asof join: each event picks the LATEST quote at-or-before its timestamp
events = pw.debug.table_from_markdown(
    """
      | inst | t
    1 | x    | 4
    2 | x    | 9
    """
)
quotes = pw.debug.table_from_markdown(
    """
      | qinst | qt | px
    1 | x     | 1  | 100
    2 | x     | 5  | 105
    3 | x     | 8  | 103
    """
)
priced = events.asof_join(
    quotes, events.t, quotes.qt, events.inst == quotes.qinst
).select(events.inst, events.t, px=quotes.px)
pw.debug.compute_and_print(priced)  # t=4 -> 100, t=9 -> 103
print("OK")
