"""North-star benchmark: brute-force KNN retrieval at 1M docs × 128 dims.

Measures the engine's hot kernel — the replacement for the reference's
``src/external_integration/brute_force_knn_integration.rs:113`` (ndarray matmul + partial
sort via ``src/mat_mul.rs:5``) — on the TPU at the BASELINE north-star scale (HBM-resident
million-doc store), against a CPU numpy implementation of the exact same computation (BLAS
matmul + ``argpartition``), an in-process stand-in for the reference's Rust/ndarray CPU
kernel. The CPU side is timed on a 64-query subset (cost is linear in queries; the full
1024-query run takes ~2 minutes on CPU). Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_DOCS = 1_000_000
DIM = 128
N_QUERIES = 1024
K = 10
CPU_SUBSET = 64
INGEST_CHUNK = 50_000  # one staged scatter per chunk, constant shape → single compile


def _run_cpu(data: np.ndarray, norms: np.ndarray, q: np.ndarray) -> np.ndarray:
    scores = q @ data.T
    qn = np.sum(q * q, axis=1, keepdims=True)
    dist = qn + norms[None, :] - 2.0 * scores
    idx = np.argpartition(dist, K, axis=1)[:, :K]
    part = np.take_along_axis(dist, idx, axis=1)
    order = np.argsort(part, axis=1)
    return np.take_along_axis(idx, order, axis=1)


def main() -> None:
    import jax

    from pathway_tpu.ops.knn import DenseKNNStore

    rng = np.random.default_rng(0)
    data = rng.normal(size=(N_DOCS, DIM)).astype(np.float32)
    queries = rng.normal(size=(N_QUERIES, DIM)).astype(np.float32)

    store = DenseKNNStore(DIM, metric="l2sq", initial_capacity=N_DOCS)

    # ingest in commit-sized batches (the engine stages adds per commit, one scatter each)
    t0 = time.perf_counter()
    for i in range(0, N_DOCS, INGEST_CHUNK):
        store.add_many(list(range(i, i + INGEST_CHUNK)), data[i : i + INGEST_CHUNK])
        store._flush()
    jax.block_until_ready(store._data)
    ingest_s = time.perf_counter() - t0
    ingest_dps = N_DOCS / ingest_s

    # warmup / compile (also drives any tunnel-side caching out of the measurement:
    # timed repeats below use distinct query batches)
    store.search_batch(queries, K)

    reps = [rng.normal(size=(N_QUERIES, DIM)).astype(np.float32) for _ in range(4)]
    latencies = []
    for q in [queries] + reps:
        t1 = time.perf_counter()
        scores, idx, valid = store.search_batch(q, K)
        latencies.append(time.perf_counter() - t1)
    med = float(np.median(latencies))
    tpu_qps = N_QUERIES / med

    # CPU baseline + exact-answer recall check on the subset
    norms = np.sum(data * data, axis=1)
    t0 = time.perf_counter()
    cpu_idx = _run_cpu(data, norms, queries[:CPU_SUBSET])
    cpu_qps = CPU_SUBSET / (time.perf_counter() - t0)

    _, tpu_idx, _ = store.search_batch(queries[:CPU_SUBSET], K)
    tpu_keys = np.vectorize(lambda s: store.key_of.get(int(s), -1))(tpu_idx)
    recall = float(
        np.mean(
            [len(set(tpu_keys[r]) & set(cpu_idx[r])) / K for r in range(CPU_SUBSET)]
        )
    )

    print(
        json.dumps(
            {
                "metric": "knn_query_qps_1Mx128",
                "value": round(tpu_qps, 1),
                "unit": "queries/s",
                "vs_baseline": round(tpu_qps / cpu_qps, 1),
                "ingest_docs_per_s": round(ingest_dps, 1),
                "p50_query_batch1024_ms": round(med * 1000.0, 2),
                "recall_at_10": round(recall, 4),
                "baseline": "numpy BLAS matmul+argpartition (reference rust-kernel proxy)",
                "device": str(jax.devices()[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
